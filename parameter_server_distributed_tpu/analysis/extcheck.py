"""Extension-protocol pass: every ``*/messages.py`` under one golden.

The wire manifest (:mod:`wirecheck`) pins the *reference* contract in
``rpc/messages.py`` — but the system has since grown extension RPC
modules (``replication/``, ``tiers/``, ``elastic/``, ``delta/``,
``fleet/`` ``messages.py``) that deliberately live outside it.  They are
wire contracts all the same: their field tags ride the network and their
method names share gRPC services with the reference tables and with each
other.  This pass

1. **auto-discovers** every extension ``messages.py`` (any ``*/messages.py``
   except ``rpc/messages.py``) and extracts per-extension manifests —
   message field specs keyed by tag, method tables attributed to their
   gRPC service — purely from the AST (no imports: ``tiers/messages.py``
   pulls in the whole core, and fixture trees must analyze too);
2. **diffs** them against the committed golden
   ``analysis/ext_manifests.json`` with the same structural-diff gate as
   the core manifest (``pst-analyze --write-ext-manifests`` regenerates);
3. **checks cross-extension collisions** statically: duplicate method
   names registered on the same gRPC service, duplicate message-type
   definitions across modules, field tags colliding with the core
   definition of a same-named message, duplicate tags within a message,
   and the reserved trace tag — field 999 is ``trace_context``/``bytes``
   everywhere, and nothing else may claim it.
"""

from __future__ import annotations

import ast
import os

from .findings import EXT_PROTOCOL, Finding
from .wirecheck import _diff_tree

MANIFEST_VERSION = 1

# Mirrors rpc.messages.TRACE_FIELD_NUMBER; _core_constants() re-reads the
# authoritative value from source when the analyzed tree has one.
TRACE_FIELD_NUMBER = 999
TRACE_FIELD_NAME = "trace_context"

# Core service names (rpc/messages.py); extension tables are attributed by
# table-name convention, see _table_service().
_PS_SERVICE = "parameter_server.ParameterServer"
_COORD_SERVICE = "coordinator.Coordinator"


def default_manifest_path() -> str:
    return os.path.join(os.path.dirname(__file__), "ext_manifests.json")


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------- AST extraction

def _module_constants(tree: ast.Module) -> dict[str, object]:
    """Module-level ``NAME = <int|str literal>`` assignments."""
    consts: dict[str, object] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, (int, str))):
            consts[stmt.targets[0].id] = stmt.value.value
    return consts


def _const(node: ast.AST, consts: dict[str, object]):
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _field_from_call(call: ast.Call, consts: dict[str, object]) -> dict | None:
    """``Field(number, name, kind, message_type=..., repeated=...)`` as a
    manifest spec dict (with ``number``), or None when it isn't one."""
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name != "Field" or len(call.args) < 3:
        return None
    number = _const(call.args[0], consts)
    fname = _const(call.args[1], consts)
    kind = _const(call.args[2], consts)
    if not isinstance(number, int) or not isinstance(fname, str):
        return None
    spec: dict = {"number": number, "name": fname, "kind": kind,
                  "repeated": False}
    for kw in call.keywords:
        if kw.arg == "repeated" and isinstance(kw.value, ast.Constant):
            spec["repeated"] = bool(kw.value.value)
        elif kw.arg == "message_type" and isinstance(kw.value, ast.Name):
            spec["message_type"] = kw.value.id
    return spec


def _message_classes(tree: ast.Module,
                     consts: dict[str, object]) -> dict[str, list[dict]]:
    """Every class with a ``FIELDS = (Field(...), ...)`` tuple — the
    declarative wire-message convention of rpc/wire.py."""
    out: dict[str, list[dict]] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.ClassDef):
            continue
        for node in stmt.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "FIELDS"):
                continue
            fields: list[dict] = []
            if isinstance(node.value, ast.Tuple):
                for elem in node.value.elts:
                    if isinstance(elem, ast.Call):
                        spec = _field_from_call(elem, consts)
                        if spec is not None:
                            fields.append(spec)
            out[stmt.name] = fields
    return out


def _method_tables(tree: ast.Module) -> dict[str, dict[str, dict]]:
    """Module-level ``X_METHODS = {"Name": (Req, Resp[, "style"])}``."""
    out: dict[str, dict[str, dict]] = {}
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id.endswith("_METHODS")
                and isinstance(stmt.value, ast.Dict)):
            continue
        table: dict[str, dict] = {}
        for key, value in zip(stmt.value.keys, stmt.value.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and isinstance(value, ast.Tuple)
                    and len(value.elts) >= 2):
                continue
            names = [e.id if isinstance(e, ast.Name) else None
                     for e in value.elts[:2]]
            style = "unary"
            if (len(value.elts) > 2
                    and isinstance(value.elts[2], ast.Constant)):
                style = value.elts[2].value
            table[key.value] = {"request": names[0], "response": names[1],
                                "style": style}
        out[stmt.targets[0].id] = table
    return out


def _table_service(table_name: str, consts: dict[str, object]) -> str | None:
    """gRPC service a method table registers on, by the naming convention
    the extension modules follow (``*_PS_METHODS`` / ``*_COORD_METHODS``),
    the core table names, or a sibling ``<BASE>_SERVICE`` constant
    (``DECODE_METHODS`` -> ``DECODE_SERVICE``)."""
    if table_name.endswith("_PS_METHODS") or \
            table_name.startswith("PARAMETER_SERVER"):
        return _PS_SERVICE
    if table_name.endswith("_COORD_METHODS") or \
            table_name.startswith("COORDINATOR"):
        return _COORD_SERVICE
    svc = consts.get(table_name.removesuffix("_METHODS") + "_SERVICE")
    return svc if isinstance(svc, str) else None


# ------------------------------------------------------------- discovery

def discover(root: str | None = None) -> list[tuple[str, str]]:
    """``(manifest_key, abs_path)`` for every extension messages.py under
    ``root`` — any ``*/messages.py`` except the reference ``rpc/`` one."""
    root = os.path.abspath(root or _package_root())
    found: list[tuple[str, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("build", "__pycache__"))
        if "messages.py" in filenames and dirpath != root:
            rel = os.path.relpath(os.path.join(dirpath, "messages.py"),
                                  root).replace(os.sep, "/")
            if rel != "rpc/messages.py":
                found.append((rel, os.path.join(dirpath, "messages.py")))
    return sorted(found)


def _parse(path: str) -> tuple[ast.Module, dict[str, object]]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    return tree, _module_constants(tree)


def _core_extract(root: str) -> tuple[dict[str, list[dict]],
                                      dict[str, dict[str, dict]], int]:
    """(messages, method tables, trace tag) of ``rpc/messages.py`` under
    ``root`` — empty when the tree has none (fixture dirs)."""
    core_path = os.path.join(root, "rpc", "messages.py")
    if not os.path.exists(core_path):
        return {}, {}, TRACE_FIELD_NUMBER
    tree, consts = _parse(core_path)
    trace = consts.get("TRACE_FIELD_NUMBER", TRACE_FIELD_NUMBER)
    consts.setdefault("TRACE_FIELD_NUMBER", trace)
    return (_message_classes(tree, consts), _method_tables(tree),
            int(trace))


def build_manifests(root: str | None = None) -> dict:
    """Per-extension manifests, extracted statically (see module doc)."""
    root = os.path.abspath(root or _package_root())
    _, _, trace = _core_extract(root)
    extensions: dict = {}
    for rel, path in discover(root):
        tree, consts = _parse(path)
        consts.setdefault("TRACE_FIELD_NUMBER", trace)
        messages = {
            name: {"fields": {str(f["number"]):
                              {k: v for k, v in f.items() if k != "number"}
                              for f in fields}}
            for name, fields in _message_classes(tree, consts).items()}
        tables = {}
        for tname, table in _method_tables(tree).items():
            tables[tname] = {
                "service": _table_service(tname, consts),
                "methods": table,
            }
        extensions[rel] = {"messages": messages, "method_tables": tables}
    return {"version": MANIFEST_VERSION, "extensions": extensions}


def write_manifests(path: str | None = None,
                    root: str | None = None) -> str:
    import json
    path = path or default_manifest_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(build_manifests(root), fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def load_manifests(path: str | None = None) -> dict | None:
    import json
    path = path or default_manifest_path()
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


# ----------------------------------------------------------------- checks

def _finding(path: str, symbol: str, message: str, slug: str) -> Finding:
    return Finding(pass_id=EXT_PROTOCOL, path=path, line=0, symbol=symbol,
                   message=message, slug=slug)


def _pkg_rel(root: str, rel: str) -> str:
    """Finding path in the repo-relative convention of the runner."""
    return f"{os.path.basename(os.path.abspath(root))}/{rel}"


def check_collisions(root: str | None = None) -> list[Finding]:
    root = os.path.abspath(root or _package_root())
    core_messages, core_tables, trace = _core_extract(root)
    core_rel = _pkg_rel(root, "rpc/messages.py")
    out: list[Finding] = []

    # (service, method) -> first registration site; seeded with the core
    # tables so an extension colliding with the reference contract reports
    # against the extension, not the core.
    methods_seen: dict[tuple[str, str], str] = {}
    for tname, table in core_tables.items():
        svc = _table_service(tname, {})
        for method in table:
            methods_seen.setdefault((svc, method), f"{core_rel}:{tname}")
    # message name -> defining module (core first, same reasoning)
    defined: dict[str, str] = {name: core_rel for name in core_messages}

    def check_fields(rel_path: str, msg: str, fields: list[dict]) -> None:
        by_tag: dict[int, str] = {}
        for f in fields:
            tag, name = f["number"], f["name"]
            if tag in by_tag:
                out.append(_finding(
                    rel_path, msg,
                    f"duplicate field tag {tag} in {msg}: "
                    f"{by_tag[tag]!r} and {name!r} — the decoder keeps one "
                    f"and silently drops the other",
                    slug=f"dup-tag:{tag}"))
            by_tag.setdefault(tag, name)
            if tag == trace and (name != TRACE_FIELD_NAME
                                 or f.get("kind") != "bytes"):
                out.append(_finding(
                    rel_path, msg,
                    f"field tag {trace} is reserved for "
                    f"{TRACE_FIELD_NAME!r} (bytes) everywhere; {msg} "
                    f"declares it as {name!r} ({f.get('kind')})",
                    slug=f"trace-tag:{name}"))
            if name == TRACE_FIELD_NAME and tag != trace:
                out.append(_finding(
                    rel_path, msg,
                    f"{TRACE_FIELD_NAME!r} must always be tag {trace} "
                    f"(the cross-service trace span convention); {msg} "
                    f"numbers it {tag}",
                    slug=f"trace-num:{tag}"))

    for name, fields in core_messages.items():
        check_fields(core_rel, name, fields)

    for rel, path in discover(root):
        rel_path = _pkg_rel(root, rel)
        tree, consts = _parse(path)
        consts.setdefault("TRACE_FIELD_NUMBER", trace)
        messages = _message_classes(tree, consts)
        for msg, fields in messages.items():
            check_fields(rel_path, msg, fields)
            if msg in defined:
                # duplicate message-type registration; when it shadows a
                # core message, also diff the tags so the report names the
                # colliding field numbers
                out.append(_finding(
                    rel_path, msg,
                    f"message type {msg} already defined in "
                    f"{defined[msg]} — two decoders for one name cannot "
                    f"agree on the wire",
                    slug="dup-message"))
                core_def = core_messages.get(msg)
                if core_def is not None:
                    core_tags = {f["number"]: f["name"] for f in core_def}
                    for f in fields:
                        have = core_tags.get(f["number"])
                        if have is not None and have != f["name"]:
                            out.append(_finding(
                                rel_path, msg,
                                f"field tag {f['number']} of {msg} "
                                f"collides with the core definition "
                                f"({have!r} there, {f['name']!r} here)",
                                slug=f"core-tag:{f['number']}"))
            else:
                defined[msg] = rel_path
        for tname, table in _method_tables(tree).items():
            svc = _table_service(tname, consts)
            if svc is None:
                out.append(_finding(
                    rel_path, tname,
                    f"method table {tname} cannot be attributed to a gRPC "
                    f"service — name it *_PS_METHODS / *_COORD_METHODS or "
                    f"declare a sibling "
                    f"{tname.removesuffix('_METHODS')}_SERVICE constant",
                    slug="unattributed-service"))
                continue
            for method in table:
                prev = methods_seen.get((svc, method))
                if prev is not None:
                    out.append(_finding(
                        rel_path, tname,
                        f"RPC method {method!r} on service {svc} already "
                        f"registered by {prev} — a server binding both "
                        f"tables would dispatch one arbitrarily",
                        slug=f"dup-method:{method}"))
                else:
                    methods_seen[(svc, method)] = f"{rel_path}:{tname}"
    return out


def run(manifest_path: str | None = None, root: str | None = None,
        check_golden: bool = True) -> list[Finding]:
    """The pass: collision checks plus the golden-manifest diff gate."""
    root = os.path.abspath(root or _package_root())
    findings = check_collisions(root)
    if not check_golden:
        return findings
    golden = load_manifests(manifest_path)
    if golden is None:
        findings.append(_finding(
            _pkg_rel(root, "analysis/ext_manifests.json"), "manifest",
            "golden extension manifests missing — run "
            "pst-analyze --write-ext-manifests and commit the result",
            slug="missing"))
        return findings
    current = build_manifests(root)
    if golden.get("version") != current.get("version"):
        findings.append(_finding(
            _pkg_rel(root, "analysis/ext_manifests.json"), "manifest",
            f"ext manifest version drift: golden {golden.get('version')} "
            f"vs current {current.get('version')}", slug="version"))
    gold_ext = golden.get("extensions", {})
    cur_ext = current.get("extensions", {})
    for rel in sorted(set(gold_ext) | set(cur_ext)):
        rel_path = _pkg_rel(root, rel)
        if rel not in cur_ext:
            findings.append(_finding(
                rel_path, rel,
                f"extension module {rel} removed but still in the golden "
                f"ext manifests — regenerate (--write-ext-manifests) if "
                f"deliberate", slug="removed"))
        elif rel not in gold_ext:
            findings.append(_finding(
                rel_path, rel,
                f"extension module {rel} not in the golden ext manifests "
                f"— regenerate (--write-ext-manifests) to pin its "
                f"contract", slug="added"))
        else:
            _diff_tree(gold_ext[rel], cur_ext[rel], rel_path, rel,
                       findings, pass_id=EXT_PROTOCOL,
                       regen="pst-analyze --write-ext-manifests")
    return findings
