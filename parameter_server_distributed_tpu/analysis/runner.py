"""Pass orchestration + report rendering for ``pst-analyze``.

Walks every ``.py`` file of the package (or any root you point it at),
runs the AST passes per file, the wire-compat pass once, folds the
acquisition-graph edges into order findings, then filters through the
reviewed baseline.  Exit contract (consumed by scripts/analyze.sh and the
gate test in tests/test_analysis.py): 0 = clean (all findings baselined),
1 = non-baselined violations, and stale baseline entries are reported but
do not fail the run (they are a cleanup prompt, not a regression).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from . import (eventcheck, extcheck, hygiene, knobcheck, lockcheck,
               wirecheck)
from .findings import (BaselineEntry, Finding, apply_baseline,
                       load_baseline)

# Directories never analyzed: generated build output only.
_SKIP_DIRS = {"build", "__pycache__"}


def package_root() -> str:
    """The installed package directory — the default analysis root."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class Report:
    root: str
    files: int = 0
    violations: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale_baseline: list[BaselineEntry] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.errors

    def to_json(self) -> dict:
        return {
            "root": self.root,
            "files": self.files,
            "ok": self.ok,
            "violations": [f.to_json() for f in self.violations],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline": [{"key": e.key, "reason": e.reason}
                               for e in self.stale_baseline],
            "errors": self.errors,
        }

    def render(self) -> str:
        lines = [f"pst-analyze: {self.files} files under {self.root}"]
        for f in self.violations:
            lines.append("  " + f.render())
        for err in self.errors:
            lines.append(f"  [error] {err}")
        if self.baselined:
            lines.append(f"  {len(self.baselined)} finding(s) baselined "
                         f"(analysis/baseline.json)")
        for e in self.stale_baseline:
            lines.append(f"  [stale-baseline] {e.key} matches nothing — "
                         f"delete the entry (reason was: {e.reason})")
        lines.append("OK: no non-baselined violations" if self.ok else
                     f"FAIL: {len(self.violations)} violation(s)")
        return "\n".join(lines)


def _iter_sources(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def analyze_file(path: str, rel: str,
                 summaries: list | None = None) -> tuple[
                     list[Finding], list[lockcheck.Edge]]:
    """All AST passes over one file (shared by the runner and the fixture
    tests, which feed synthetic sources through the same entry points)."""
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return analyze_source(source, rel, summaries=summaries)


def analyze_source(source: str, rel: str,
                   summaries: list | None = None) -> tuple[
                       list[Finding], list[lockcheck.Edge]]:
    # one parse + one symbol map, shared by all three AST passes
    tree = ast.parse(source, filename=rel)
    symbols = hygiene._enclosing_symbols(tree)
    findings, edges = lockcheck.analyze_module(source, rel, tree=tree,
                                               summaries=summaries)
    findings += hygiene.check_excepts(source, rel, tree=tree,
                                      symbols=symbols)
    findings += hygiene.check_threads(source, rel, tree=tree,
                                      symbols=symbols)
    return findings, edges


def run(root: str | None = None,
        baseline_path: str | None = None,
        manifest_path: str | None = None,
        wire: bool = True,
        ext: bool = True,
        knobs: bool = True,
        events: bool = True,
        interproc: bool = True,
        ext_manifest_path: str | None = None,
        knob_registry_path: str | None = None) -> Report:
    explicit_root = root is not None
    root = os.path.abspath(root or package_root())
    report = Report(root=root)
    if not os.path.isdir(root):
        report.errors.append(f"analysis root {root} is not a directory")
        return report
    # golden comparisons (ext manifests, knob registry) only bind when we
    # analyze the package itself or the caller pointed at goldens — an
    # arbitrary fixture root has no committed goldens to diff against
    pinned = (root == package_root()
              or ext_manifest_path is not None
              or knob_registry_path is not None
              or not explicit_root)
    findings: list[Finding] = []
    edges: list[lockcheck.Edge] = []
    summaries: list[lockcheck.FnSummary] = []
    repo_prefix = os.path.dirname(root)
    for path in _iter_sources(root):
        rel = os.path.relpath(path, repo_prefix).replace(os.sep, "/")
        report.files += 1
        try:
            file_findings, file_edges = analyze_file(
                path, rel, summaries=summaries)
        except (SyntaxError, ValueError) as exc:
            report.errors.append(f"{rel}: {exc}")
            continue
        findings += file_findings
        edges += file_edges
    if interproc:
        ip_edges, ip_findings = lockcheck.interprocedural(summaries)
        edges += ip_edges
        findings += ip_findings
    findings += lockcheck.check_edges(edges)
    if wire:
        try:
            findings += wirecheck.run(manifest_path)
        except Exception as exc:  # noqa: BLE001 — analyzer must report,
            # not crash: a broken rpc import IS the finding
            report.errors.append(f"wire-compat pass failed: {exc}")
    if ext:
        try:
            findings += extcheck.run(manifest_path=ext_manifest_path,
                                     root=root,
                                     check_golden=pinned)
        except Exception as exc:  # noqa: BLE001
            report.errors.append(f"ext-protocol pass failed: {exc}")
    if knobs:
        try:
            findings += knobcheck.run(root=root,
                                      registry_path=knob_registry_path,
                                      check_registry=pinned)
        except Exception as exc:  # noqa: BLE001
            report.errors.append(f"knob-registry pass failed: {exc}")
    if events:
        try:
            findings += eventcheck.run(root=root)
        except Exception as exc:  # noqa: BLE001
            report.errors.append(f"flight-event pass failed: {exc}")
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.slug))
    try:
        entries = load_baseline(baseline_path)
    except ValueError as exc:
        report.errors.append(str(exc))
        entries = []
    (report.violations, report.baselined,
     report.stale_baseline) = apply_baseline(findings, entries)
    return report


def to_json_str(report: Report) -> str:
    return json.dumps(report.to_json(), indent=1, sort_keys=True)
