"""Finding model + reviewed-baseline handling for the analysis passes.

A :class:`Finding` is one violation, keyed by a *stable* identity
(``pass_id:path:symbol:slug``) that deliberately excludes line numbers, so
a baseline entry keeps matching while unrelated edits move code around.
``analysis/baseline.json`` is the reviewed allowlist: each entry carries
the key and a one-line justification; ``pst-analyze`` fails on any finding
NOT in it, and reports baseline entries that no longer match anything so
the file cannot silently rot.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field

# Pass identifiers (stable — baseline keys embed them)
LOCK_ORDER = "lock-order"          # inversion against declared order / cycle
LOCK_RAW_ACQUIRE = "lock-raw-acquire"  # acquire() outside a with-statement
LOCK_BLOCKING = "lock-blocking"    # blocking call while holding a lock
EXCEPT_HYGIENE = "except-hygiene"  # bare/overbroad except that swallows
THREAD_HYGIENE = "thread-hygiene"  # unnamed / non-daemon helper thread
WIRE_COMPAT = "wire-compat"        # drift against the golden wire manifest
EXT_PROTOCOL = "ext-protocol"      # extension messages.py manifest drift /
#                                    cross-extension protocol collisions
KNOB_REGISTRY = "knob-registry"    # PSDT_* knob registry drift / doc drift /
#                                    conflicting parse defaults
FLIGHT_EVENT = "flight-event"      # flight event-code registry: uniqueness,
#                                    pairing, postmortem decode coverage

ALL_PASSES = (LOCK_ORDER, LOCK_RAW_ACQUIRE, LOCK_BLOCKING, EXCEPT_HYGIENE,
              THREAD_HYGIENE, WIRE_COMPAT, EXT_PROTOCOL, KNOB_REGISTRY,
              FLIGHT_EVENT)


@dataclass
class Finding:
    pass_id: str
    path: str        # repo-relative (or "rpc/messages.py" for wire findings)
    line: int        # 1-based; 0 when not anchored to a source line
    symbol: str      # "Class.method", "function", or message/field name
    message: str     # human sentence
    slug: str = ""   # short stable discriminator within (pass, path, symbol)
    baselined_by: str | None = field(default=None, compare=False)

    @property
    def key(self) -> str:
        parts = [self.pass_id, self.path, self.symbol]
        if self.slug:
            parts.append(self.slug)
        return ":".join(parts)

    def to_json(self) -> dict:
        d = asdict(self)
        d["key"] = self.key
        return d

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.pass_id}] {loc} ({self.symbol}): {self.message}"


@dataclass
class BaselineEntry:
    key: str
    reason: str


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> list[BaselineEntry]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    entries = []
    for raw in doc.get("entries", []):
        if not raw.get("reason", "").strip():
            raise ValueError(
                f"baseline entry {raw.get('key')!r} has no justification — "
                f"every baselined finding needs a one-line reason")
        entries.append(BaselineEntry(key=raw["key"], reason=raw["reason"]))
    return entries


def apply_baseline(findings: list[Finding],
                   entries: list[BaselineEntry]) -> tuple[
                       list[Finding], list[Finding], list[BaselineEntry]]:
    """Split into (violations, baselined, stale_entries).  An entry is
    stale when its key matches no current finding — it should be deleted
    (the code was fixed, or the key drifted and must be re-reviewed)."""
    by_key = {e.key: e for e in entries}
    violations, baselined = [], []
    matched: set[str] = set()
    for f in findings:
        entry = by_key.get(f.key)
        if entry is not None:
            f.baselined_by = entry.reason
            baselined.append(f)
            matched.add(entry.key)
        else:
            violations.append(f)
    stale = [e for e in entries if e.key not in matched]
    return violations, baselined, stale
