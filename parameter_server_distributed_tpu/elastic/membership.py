"""Membership runtime: worker announce/poll client + PS width provider.

The coordinator owns the epoch-numbered membership table
(:class:`~..core.coordinator_core.CoordinatorCore`); this module is the
two remote consumers:

- :class:`MembershipClient` — the worker (and ``pst-ctl``) side of the
  ``UpdateMembership`` extension RPC: announce join after registration,
  announce leave at graceful shutdown (drain/SIGTERM), and poll own
  state at heartbeat cadence so a coordinator-side ``pst-ctl drain``
  reaches the worker without any wire-manifest change.  A reference
  coordinator answers UNIMPLEMENTED and the client latches unsupported
  forever — membership degrades to today's static behavior.
- :class:`MembershipWidthProvider` — the PS side: a live-worker
  provider (drop-in ``live_workers_fn``) that reads the membership
  table, counting every non-GONE member, and exposes the membership
  epoch as its ``generation`` so
  :meth:`~..core.ps_core.ParameterServerCore.barrier_width` invalidates
  its TTL cache the instant the epoch moves (a reap marks GONE and
  bumps the epoch — the shrink lands at the next epoch poll instead of
  a TTL lapse).  Falls back to the classic ``ListWorkers`` count when
  the coordinator lacks the extension.
"""

from __future__ import annotations

import logging

import grpc

from ..analysis.lock_order import checked_lock
from ..rpc import messages as m
from ..rpc.service import RpcClient
from . import messages as emsg

log = logging.getLogger("pst.elastic")


class MembershipClient:
    """Worker/ctl-side membership announcements over the coordinator
    connection.  Every method degrades to ``None`` (unsupported /
    unreachable) instead of raising — membership is advisory and must
    never fail a training step."""

    def __init__(self, coordinator_address: str, worker_id: int = -1,
                 client: RpcClient | None = None):
        self.worker_id = int(worker_id)
        self._client = client or RpcClient(
            coordinator_address, m.COORDINATOR_SERVICE,
            {**m.COORDINATOR_METHODS, **emsg.ELASTIC_COORD_METHODS})
        self._supported: bool | None = None

    def close(self) -> None:
        self._client.close()

    @property
    def supported(self) -> bool | None:
        """True/False once proven; None before the first call."""
        return self._supported

    def _call(self, action: int, target: int = -1,
              timeout: float = 5.0) -> emsg.MembershipResponse | None:
        if self._supported is False:
            return None
        try:
            resp = self._client.call(
                "UpdateMembership",
                emsg.MembershipRequest(worker_id=self.worker_id,
                                       action=action,
                                       target_worker_id=target),
                timeout=timeout)
        except grpc.RpcError as exc:
            code = getattr(exc, "code", None)
            if callable(code) and code() == grpc.StatusCode.UNIMPLEMENTED:
                log.info("coordinator does not speak UpdateMembership; "
                         "membership stays static")
                self._supported = False
            return None
        self._supported = True
        return resp

    def join(self) -> emsg.MembershipResponse | None:
        return self._call(emsg.MEMBER_JOIN)

    def leave(self) -> emsg.MembershipResponse | None:
        return self._call(emsg.MEMBER_LEAVE)

    def poll_state(self) -> int | None:
        """Own membership state (the drain signal), or None when the
        extension is unsupported/unreachable."""
        resp = self._call(emsg.MEMBER_QUERY)
        if resp is None:
            return None
        return int(resp.self_state)

    def drain(self, target_worker_id: int
              ) -> emsg.MembershipResponse | None:
        """``pst-ctl drain``: ask the coordinator to mark ``target``
        DRAINING; the worker notices at its next heartbeat-cadence
        poll."""
        return self._call(emsg.MEMBER_DRAIN, target=int(target_worker_id))

    def query(self, timeout: float = 5.0
              ) -> emsg.MembershipResponse | None:
        return self._call(emsg.MEMBER_QUERY, timeout=timeout)


def live_member_count(entries) -> int:
    """Barrier-width view of a membership table: every non-GONE member
    counts — DRAINING workers are still finishing an in-flight
    iteration and must keep their barrier slot until they leave."""
    return sum(1 for e in entries
               if int(e.state) != emsg.MEMBER_GONE)


def draining_member_ids(entries) -> tuple[int, ...]:
    """Worker ids that announced they are leaving (DRAINING): the
    K-of-N quorum threshold pre-shrinks by their count, and the
    skip-the-grace close needs the IDS — only commits from NON-draining
    workers may satisfy "everyone still staying has committed"
    (elastic/quorum.py + ps_core._quorum_ready_locked, ISSUE 14
    satellite)."""
    return tuple(int(e.worker_id) for e in entries
                 if int(e.state) == emsg.MEMBER_DRAINING)


class MembershipWidthProvider:
    """Drop-in ``live_workers_fn`` for ``ParameterServerCore`` backed by
    the membership table, with the membership epoch as ``generation``.

    The core's ``barrier_width()`` TTL cache refreshes when the TTL
    lapses OR when ``generation()`` moved — so an in-process topology
    (tests, colocated bench) sees an eviction immediately, and a remote
    PS sees it at the next epoch poll.  ``generation()`` itself must be
    cheap: it returns the LAST SEEN epoch (updated by every ``__call__``)
    rather than issuing its own RPC — the epoch rides the same response
    as the width."""

    def __init__(self, coordinator_address: str,
                 client: RpcClient | None = None):
        self._address = coordinator_address
        self._client = MembershipClient(coordinator_address, worker_id=-1,
                                        client=client)
        # held across the membership RPC — single-flight per refresh,
        # the barrier_width _live_lock (rank 50) is already held by the
        # caller, hence rank 51 and BLOCKING_ALLOWED
        # (analysis/lock_order.py)
        self._lock = checked_lock("MembershipWidthProvider._lock")
        self._epoch = 0
        self._draining: tuple[int, ...] = ()
        self._fallback: RpcClient | None = None

    def close(self) -> None:
        self._client.close()
        if self._fallback is not None:
            self._fallback.close()

    def generation(self) -> int:
        """Last-seen membership epoch (no RPC — see class docstring)."""
        with self._lock:
            return self._epoch

    def draining(self) -> tuple[int, ...]:
        """Last-seen DRAINING worker IDS, refreshed by every
        ``__call__`` from the same membership response as the width (no
        RPC) — the quorum-threshold pre-shrink input, and the identity
        evidence the skip-the-grace close needs
        (``ParameterServerCore._quorum_ready_locked``)."""
        with self._lock:
            return self._draining

    def _list_workers_count(self) -> int:
        """Classic registry count — the downgrade path for reference
        coordinators without the membership extension."""
        if self._fallback is None:
            self._fallback = RpcClient(self._address,
                                       m.COORDINATOR_SERVICE,
                                       m.COORDINATOR_METHODS)
        try:
            resp = self._fallback.call("ListWorkers",
                                       m.ListWorkersRequest(), timeout=2.0)
            return int(resp.total_workers)
        except Exception:  # noqa: BLE001 — registry unreachable: fall back
            return 0

    def __call__(self) -> int:
        with self._lock:
            # short timeout: this runs under the barrier-width locks —
            # against a partitioned coordinator every push/poll would
            # otherwise queue behind a multi-second refresh (the 2 s
            # budget of the classic ListWorkers live_fn this replaced)
            resp = self._client.query(timeout=2.0)
            if resp is None:
                return self._list_workers_count()
            self._epoch = int(resp.epoch)
            self._draining = draining_member_ids(resp.entries)
            return live_member_count(resp.entries)
