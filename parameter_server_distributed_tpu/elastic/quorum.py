"""K-of-N quorum barrier policy (ISSUE 13).

The synchronous barrier historically closes at **all of N**: one lost or
slow worker stalls every healthy peer until the fused-barrier timeout.
``PSDT_QUORUM`` (a fraction of the live width, e.g. ``0.75``) arms the
K-of-N close in :class:`~..core.ps_core.ParameterServerCore`: once
``K = ceil(quorum * width)`` contributors have committed AND a grace
window (``PSDT_QUORUM_GRACE_MS``, default 250) past the K-th commit has
elapsed, the barrier seals and applies over the contributors it has —
the mean stays a mean over *contributors* (per-name counts, exactly the
machinery disjoint-subset sharded pushes already use).  Stragglers whose
push lands after the seal are not rejected: they fold into the NEXT
iteration's accumulator as a staleness-tagged, learning-rate-damped
contribution (:mod:`..async_sgd.damping`).

Unset (the default) the policy is OFF and every barrier is today's
all-of-N, byte-identical.  ``PSDT_QUORUM=1.0`` is likewise all-of-N and
treated as off.  The grace window exists so a quorum reached moments
before the last stragglers' commits does not cut them off: the common
case (everyone healthy) still closes at full width, and only a worker
slower than grace is folded forward.
"""

from __future__ import annotations

import math
import os

ENV_QUORUM = "PSDT_QUORUM"
ENV_GRACE_MS = "PSDT_QUORUM_GRACE_MS"
DEFAULT_GRACE_MS = 250.0


def quorum_fraction(override: float | None = None) -> float:
    """The armed quorum fraction in (0, 1), or 0.0 = off (all-of-N).
    ``override`` is the config value (0/None = env decides)."""
    if override is not None and override > 0:
        value = float(override)
    else:
        raw = os.environ.get(ENV_QUORUM, "")
        if not raw:
            return 0.0
        value = float(raw)
    if not 0.0 < value <= 1.0:
        raise ValueError(f"{ENV_QUORUM} must be a fraction in (0, 1], "
                         f"got {value}")
    # 1.0 == all-of-N == the pre-existing barrier: treat as off so the
    # default path stays byte-identical
    return value if value < 1.0 else 0.0


def grace_s(override_ms: float | None = None) -> float:
    """The post-K-th-commit grace window, in seconds."""
    if override_ms is not None and override_ms >= 0:
        ms = float(override_ms)
    else:
        ms = float(os.environ.get(ENV_GRACE_MS, str(DEFAULT_GRACE_MS)))
    return max(0.0, ms) / 1e3


def threshold(quorum: float, width: int, draining: int = 0) -> int:
    """K for a barrier of ``width``: ``ceil(quorum * width)``, clamped
    to [1, width] — a quorum can never be satisfied by zero contributors
    and never demands more than the (possibly elastic) width.

    ``draining`` PRE-SHRINKS the threshold (ISSUE 14 satellite, the
    PR 13 leftover): a DRAINING worker still holds its barrier slot —
    it may be finishing an in-flight iteration — but it is leaving, so
    the close must never *demand* its commit.  K is additionally capped
    at ``width - draining`` (floor 1): with the drain announced, the
    healthy workers alone satisfy the quorum, and a graceful drain
    costs zero grace windows instead of one per barrier until the
    leave lands (see ``_quorum_ready_locked`` for the matching
    skip-the-grace rule when every non-draining worker committed)."""
    if width <= 0:
        return 1
    k = min(width, max(1, math.ceil(quorum * width - 1e-9)))
    if draining > 0:
        k = min(k, max(1, width - int(draining)))
    return k
