"""Membership extension RPC messages (ISSUE 13).

Deliberately NOT in ``rpc/messages.py``: the analyzer's wire manifest
pins the reference contract (field tags, method tables) and the elastic
subsystem must leave it byte-unchanged.  ``UpdateMembership`` is an
extra method name on the existing coordinator gRPC service — a reference
coordinator never implements it and answers UNIMPLEMENTED, which every
caller (:class:`~.membership.MembershipClient`, the PS width provider)
treats as a PERMANENT downgrade to today's static membership (the
PR-2/PR-6/PR-7/PR-9 fallback discipline).

One RPC serves four roles, so the membership protocol needs no extra
round trips:

- **join announce** — a worker reports itself ACTIVE after registering
  (``action = MEMBER_JOIN``); until then a registered worker sits in
  JOINING (a legacy worker without the extension simply stays there —
  membership is advisory for it, the live count is unchanged);
- **leave announce** — graceful deregistration (``MEMBER_LEAVE``): the
  worker finished its in-flight iteration, the registry drops it NOW
  and the barrier narrows at the next width refresh instead of a
  stale-heartbeat reap 30 s later;
- **drain request** — ``pst-ctl drain <worker>`` (``MEMBER_DRAIN`` with
  ``target_worker_id``) marks the target DRAINING; the worker sees its
  own state on its next heartbeat-cadence poll, finishes the in-flight
  iteration, and leaves;
- **membership query** — the response carries the epoch-numbered state
  table (``action = MEMBER_QUERY`` registers nothing; the PS width
  provider and ``pst-ctl members`` are pure reads).

Every state transition bumps the membership epoch, so a poller holding
epoch E knows a response with epoch > E supersedes its view.
"""

from __future__ import annotations

from ..rpc.messages import TRACE_FIELD_NUMBER
from ..rpc.wire import Field, Message

# Membership states (MembershipEntry.state).  Append-only: the values
# ride the wire and pst-trace notes.
MEMBER_JOINING = 0   # registered, join not yet announced (or legacy worker)
MEMBER_ACTIVE = 1    # announced via UpdateMembership(MEMBER_JOIN)
MEMBER_DRAINING = 2  # preemption requested; finishing in-flight iteration
MEMBER_GONE = 3      # left gracefully or reaped; never counts live again

STATE_NAMES = {MEMBER_JOINING: "joining", MEMBER_ACTIVE: "active",
               MEMBER_DRAINING: "draining", MEMBER_GONE: "gone"}

# MembershipRequest.action values.
MEMBER_QUERY = 0
MEMBER_JOIN = 1
MEMBER_LEAVE = 2
MEMBER_DRAIN = 3


class MembershipEntry(Message):
    """One worker's membership row: state + the epoch at which it last
    transitioned."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "state", "int32"),
        Field(3, "epoch", "int32"),
    )


class MembershipRequest(Message):
    """Announce-and-query (see module docstring).  ``target_worker_id``
    is only read for ``MEMBER_DRAIN`` (the ``pst-ctl`` path drains a
    worker other than the caller); every other action acts on
    ``worker_id``.  ``worker_id = -1`` with ``MEMBER_QUERY`` is a pure
    read (the PS width provider, ``pst-ctl members``)."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "action", "int32"),
        Field(3, "target_worker_id", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class MembershipResponse(Message):
    """``self_state`` answers the REQUESTING worker directly (its row is
    also in ``entries``): the heartbeat-cadence drain poll only needs
    this one field.  -1 when the caller is unknown to the table."""
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "success", "bool"),
        Field(3, "message", "string"),
        Field(4, "entries", "message", message_type=MembershipEntry,
              repeated=True),
        Field(5, "self_state", "int32"),
    )


ELASTIC_COORD_METHODS = {
    "UpdateMembership": (MembershipRequest, MembershipResponse),
}
