"""Elastic membership + K-of-N quorum barriers (ISSUE 13).

The subsystem that makes membership a first-class, epoch-numbered
object and the synchronous barrier a quorum:

- :mod:`.messages` — the ``UpdateMembership`` coordinator extension RPC
  (OUTSIDE ``rpc/messages.py``: the wire manifest stays byte-unchanged;
  reference coordinators answer UNIMPLEMENTED => permanent static
  membership);
- :mod:`.membership` — the worker-side join/leave/drain announce client
  and the PS-side width provider whose ``generation`` (the membership
  epoch) invalidates the barrier-width TTL cache the instant a member
  transitions;
- :mod:`.quorum` — the ``PSDT_QUORUM`` / ``PSDT_QUORUM_GRACE_MS``
  policy consumed by ``core/ps_core.py``: close at K of N once a grace
  window past the K-th commit elapses, fold stragglers forward damped
  (:mod:`..async_sgd.damping`).

Kept import-light deliberately (like the sibling extension packages):
``core/`` imports :mod:`.messages` and :mod:`.quorum`, which must not
drag the gRPC client stack in through this ``__init__``.

See docs/training.md "Elastic membership & quorum barriers".
"""
