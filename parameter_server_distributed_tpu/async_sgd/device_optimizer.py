"""Device-resident optimizer for the async parameter server.

In bounded-staleness mode updates apply on arrival (no barrier), so the
apply path is the PS hot loop.  The host optimizers in core/optimizer.py
walk numpy arrays on the CPU — fine for MNIST, not for a 1B-param store.
This optimizer keeps parameters and slots as jax Arrays on the accelerator
and applies updates under jit with donated buffers: the PS's HBM footprint
stays flat and the apply is one fused XLA program per push.

Drops into `ParameterServerCore(optimizer=...)` unchanged — it satisfies the
HostOptimizer protocol (apply/state_dict/load_state_dict).
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.optimizer import HostOptimizer


class DeviceOptimizer(HostOptimizer):
    def __init__(self, transformation: optax.GradientTransformation,
                 learning_rate: float = 0.0):
        super().__init__(learning_rate)
        self._tx = transformation
        self._opt_state = None

        def apply(params, grads, opt_state):
            updates, new_opt = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        self._apply = jax.jit(apply, donate_argnums=(0, 2))

    @classmethod
    def sgd(cls, learning_rate: float = 1.0) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate), learning_rate)

    @classmethod
    def momentum(cls, learning_rate: float = 1.0,
                 momentum: float = 0.9) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate, momentum=momentum), learning_rate)

    @classmethod
    def adam(cls, learning_rate: float = 1e-3) -> "DeviceOptimizer":
        return cls(optax.adam(learning_rate), learning_rate)

    def apply(self, params: Mapping[str, np.ndarray],
              grads: Mapping[str, np.ndarray]) -> dict:
        device_params = {k: jnp.asarray(v) for k, v in params.items()}
        device_grads = {k: jnp.asarray(np.asarray(grads[k], np.float32))
                        if k in grads else jnp.zeros_like(device_params[k])
                        for k in device_params}
        if self._opt_state is None:
            self._opt_state = self._tx.init(device_params)
        new_params, self._opt_state = self._apply(device_params, device_grads,
                                                  self._opt_state)
        return new_params

    def state_dict(self) -> dict:
        """Checkpoint-codec-friendly: a single uint8 'pickle' entry holding
        (leaves-as-numpy, treedef) so the optimizer sidecar (an npz) can
        store it without knowing optax's pytree structure."""
        import pickle

        if self._opt_state is None:
            return {}
        leaves, treedef = jax.tree.flatten(self._opt_state)
        blob = pickle.dumps(([np.asarray(leaf) for leaf in leaves], treedef))
        return {"pickle": np.frombuffer(blob, dtype=np.uint8)}

    def load_state_dict(self, state: dict) -> None:
        import pickle

        if not state or "pickle" not in state:
            self._opt_state = None
            return
        leaves, treedef = pickle.loads(np.asarray(state["pickle"],
                                                  np.uint8).tobytes())
        self._opt_state = jax.tree.unflatten(
            treedef, [jnp.asarray(leaf) for leaf in leaves])
