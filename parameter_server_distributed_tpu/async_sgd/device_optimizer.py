"""Device-resident optimizers for the async parameter server.

In bounded-staleness mode updates apply on arrival (no barrier), so the
apply path is the PS hot loop.  The host optimizers in core/optimizer.py
walk numpy arrays on the CPU — fine for MNIST, not for a 1B-param store.
These optimizers keep parameters and slots as jax Arrays on the accelerator
and apply updates under jit, donating the optimizer slot buffers.  Params
are deliberately NOT donated: ps_core keeps serving previously-returned
param dicts concurrently and those may alias the apply inputs, so each
apply transiently holds old+new param buffers (~2x the store) before the
old copy is released.

Two apply backends, A/B-comparable via ``PSDT_BENCH_PS_OPT`` in bench.py:

- :class:`DeviceOptimizer` — optax transformation under jit (XLA fuses it).
- :class:`PallasOptimizer` — the hand-fused pallas kernels from
  ops/pallas/fused_update.py (one VMEM-tiled pass per tensor).

Both drop into `ParameterServerCore(optimizer=...)` unchanged — they satisfy
the HostOptimizer protocol (apply/state_dict/load_state_dict) and are
selected by name through `core.optimizer.make_optimizer`
(``device_*`` / ``pallas_*``).

They are equally valid on the SYNCHRONOUS barrier path (opt in with
``--optimizer pallas_sgd`` etc. on the PS): the streaming close hands the
contributor mean to ``apply`` exactly as it would a host optimizer, and
the whole-store jit program runs the update on the accelerator.  Both
keep ``supports_striping = False`` — a jit-compiled whole-store program
is not name-sliceable, and splitting it into S programs would recompile
per stripe and serialize on the device queue anyway, so the striped
barrier close (core/ps_core.py, PSDT_STRIPES) deliberately falls back to
this serial whole-store apply for them.  The accelerator IS the
parallelism in that configuration.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.optimizer import HostOptimizer


def _stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """Unbiased f32 -> bf16 rounding: add uniform noise to the 16 bits
    being dropped, then truncate.  E[result] == x, so a narrow EMA keeps
    tracking even when its per-step change is below the bf16 half-ulp —
    deterministic round-to-nearest would freeze it there (an EMA with
    decay 0.999 moves ~0.1%/step; bf16's half-ulp is ~0.2%)."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    # carry from the low 16 bits rounds up to the next representable bf16
    # with probability = dropped-fraction; NaN/inf inputs don't occur here
    # (moments are finite EMAs of finite gradients)
    rounded = ((bits + noise) >> 16).astype(jnp.uint16)
    return jax.lax.bitcast_convert_type(rounded, jnp.bfloat16)


def _adam_with_bf16_slots(b1: float, b2: float,
                          eps: float) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moment slots stored in bfloat16 (half the
    optimizer-state HBM: 8 GB -> 4 GB for a 1B-param store).

    All arithmetic runs in f32 — only the carried state is narrowed, and
    the narrowing uses STOCHASTIC rounding (:func:`_stochastic_round_bf16`)
    so the EMAs stay unbiased: with round-to-nearest, b2=0.999's ~0.1%
    per-step change is below bf16's ~0.2% half-ulp and the second moment
    would freeze at a stale value the moment gradients shrink (exactly why
    optax's own ``mu_dtype`` narrows only the FIRST moment).  The PRNG key
    rides in the optimizer state."""

    def init_fn(params):
        zeros = lambda p: jnp.zeros(jnp.shape(p), jnp.bfloat16)  # noqa: E731
        # old-style uint32 key: the checkpoint sidecar snapshots state
        # leaves via np.asarray, which typed key arrays reject
        return {"count": jnp.zeros((), jnp.int32),
                "key": jax.random.PRNGKey(0),
                "mu": jax.tree.map(zeros, params),
                "nu": jax.tree.map(zeros, params)}

    def update_fn(updates, state, params=None):
        del params
        count = state["count"] + 1
        f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
        mu = jax.tree.map(lambda m, g: b1 * f32(m) + (1 - b1) * f32(g),
                          state["mu"], updates)
        nu = jax.tree.map(
            lambda v, g: b2 * f32(v) + (1 - b2) * jnp.square(f32(g)),
            state["nu"], updates)
        bc1 = 1.0 - b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
        key, sub = jax.random.split(state["key"])
        leaves, treedef = jax.tree.flatten({"mu": mu, "nu": nu})
        narrowed = jax.tree.unflatten(treedef, [
            _stochastic_round_bf16(leaf, k)
            for leaf, k in zip(leaves,
                               jax.random.split(sub, len(leaves)))])
        return out, {"count": count, "key": key,
                     "mu": narrowed["mu"], "nu": narrowed["nu"]}

    return optax.GradientTransformation(init_fn, update_fn)


class DeviceOptimizer(HostOptimizer):
    # whole-store jit program — not name-sliceable (see module docstring)
    supports_striping = False

    def __init__(self, transformation: optax.GradientTransformation,
                 learning_rate: float = 0.0):
        super().__init__(learning_rate)
        self._tx = transformation
        self._opt_state = None

        def apply(params, grads, opt_state):
            updates, new_opt = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        # Donate the opt state (private to this object) but NOT params:
        # ps_core keeps serving previously-returned param dicts concurrently,
        # and under async pushes those alias the apply inputs — donating
        # them would invalidate in-flight pull snapshots.
        self._apply = jax.jit(apply, donate_argnums=(2,))

    @classmethod
    def sgd(cls, learning_rate: float = 1.0) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate), learning_rate)

    @classmethod
    def momentum(cls, learning_rate: float = 1.0,
                 momentum: float = 0.9) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate, momentum=momentum), learning_rate)

    @classmethod
    def adam(cls, learning_rate: float = 1e-3) -> "DeviceOptimizer":
        return cls(optax.adam(learning_rate), learning_rate)

    @classmethod
    def adamw(cls, learning_rate: float = 1e-3,
              weight_decay: float = 1e-4) -> "DeviceOptimizer":
        # matrices-only decay mask, matching parallel/train_step and the
        # host AdamW (decaying norm scales/biases is a quality bug)
        return cls(optax.adamw(
            learning_rate, weight_decay=weight_decay,
            mask=lambda params: jax.tree.map(
                lambda p: p.ndim >= 2, params)), learning_rate)

    @classmethod
    def adamw_bf16(cls, learning_rate: float = 1e-3,
                   weight_decay: float = 1e-4) -> "DeviceOptimizer":
        """AdamW with both moment slots carried in bfloat16 (stochastic
        rounding keeps the EMAs unbiased) — half the optimizer-state HBM
        of :meth:`adamw`; same matrices-only decoupled decay."""
        return cls(optax.chain(
            _adam_with_bf16_slots(0.9, 0.999, 1e-8),
            optax.add_decayed_weights(
                weight_decay, mask=lambda params: jax.tree.map(
                    lambda p: p.ndim >= 2, params)),
            optax.scale(-learning_rate)), learning_rate)

    def apply(self, params: Mapping[str, np.ndarray],
              grads: Mapping[str, np.ndarray]) -> dict:
        device_params = {k: jnp.asarray(v) for k, v in params.items()}
        device_grads = {k: jnp.asarray(np.asarray(grads[k], np.float32))
                        if k in grads else jnp.zeros_like(device_params[k])
                        for k in device_params}
        if self._opt_state is None:
            self._opt_state = self._tx.init(device_params)
        new_params, self._opt_state = self._apply(device_params, device_grads,
                                                  self._opt_state)
        return new_params

    def state_dict(self) -> dict:
        """Checkpoint-codec-friendly: a single uint8 'pickle' entry holding
        (leaves-as-numpy, treedef) so the optimizer sidecar (an npz) can
        store it without knowing optax's pytree structure."""
        import pickle

        if self._opt_state is None:
            return {}
        leaves, treedef = jax.tree.flatten(self._opt_state)
        blob = pickle.dumps(([np.asarray(leaf) for leaf in leaves], treedef))
        return {"pickle": np.frombuffer(blob, dtype=np.uint8)}

    def load_state_dict(self, state: dict) -> None:
        import pickle

        if not state or "pickle" not in state:
            self._opt_state = None
            return
        leaves, treedef = pickle.loads(np.asarray(state["pickle"],
                                                  np.uint8).tobytes())
        self._opt_state = jax.tree.unflatten(
            treedef, [jnp.asarray(leaf) for leaf in leaves])


class PallasOptimizer(HostOptimizer):
    """Device-resident PS optimizer whose apply path is the fused pallas
    update kernels (ops/pallas/fused_update.py) instead of an optax chain.
    One jit-compiled, buffer-donating program per rule; Adam's per-step bias
    corrections ride in as data (SMEM scalars), so stepping never
    recompiles."""

    # whole-store jit program — not name-sliceable (see module docstring)
    supports_striping = False

    RULES = ("sgd", "momentum", "adam")

    def __init__(self, rule: str = "sgd", learning_rate: float = 1.0,
                 momentum: float = 0.9, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8):
        super().__init__(learning_rate)
        if rule not in self.RULES:
            raise ValueError(f"unknown pallas rule {rule!r}; options {self.RULES}")
        self.rule = rule
        self.momentum = momentum
        self.b1, self.b2, self.eps = b1, b2, eps
        self._slots: dict[str, jax.Array] = {}   # vel/<n>, m/<n>, v/<n>
        self.step = 0
        from ..ops.pallas import fused_update as fu

        # Donate slot buffers (private to this object) but NOT params — see
        # DeviceOptimizer: served param snapshots may alias apply inputs.
        if rule == "sgd":
            def apply_fn(params, grads):
                return fu.fused_sgd(params, grads, lr=learning_rate), {}
            donate = ()
        elif rule == "momentum":
            def apply_fn(params, grads, velocity):
                new_p, new_v = fu.fused_momentum(
                    params, grads, velocity, lr=learning_rate, mu=momentum)
                return new_p, {"vel": new_v}
            donate = (2,)
        else:
            def apply_fn(params, grads, m, v, step):
                new_p, new_m, new_v = fu.fused_adam(
                    params, grads, m, v, step, lr=learning_rate, b1=b1,
                    b2=b2, eps=eps)
                return new_p, {"m": new_m, "v": new_v}
            donate = (2, 3)
        self._apply = jax.jit(apply_fn, donate_argnums=donate)

    def apply(self, params: Mapping[str, np.ndarray],
              grads: Mapping[str, np.ndarray]) -> dict:
        device_params = {k: jnp.asarray(v) for k, v in params.items()}
        device_grads = {k: jnp.asarray(np.asarray(v, np.float32))
                        for k, v in grads.items() if k in device_params}
        self.step += 1
        if self.rule == "sgd":
            new_params, _ = self._apply(device_params, device_grads)
        elif self.rule == "momentum":
            vel = {k: self._slots.get(f"vel/{k}")
                   if f"vel/{k}" in self._slots
                   else jnp.zeros(np.shape(p), jnp.float32)
                   for k, p in device_params.items()}
            new_params, slots = self._apply(device_params, device_grads, vel)
            self._slots = {f"vel/{k}": v for k, v in slots["vel"].items()}
        else:
            # independent zero buffers per slot — both m and v are donated,
            # so they must never alias
            m = {k: self._slots.get(f"m/{k}")
                 if f"m/{k}" in self._slots
                 else jnp.zeros(np.shape(p), jnp.float32)
                 for k, p in device_params.items()}
            v = {k: self._slots.get(f"v/{k}")
                 if f"v/{k}" in self._slots
                 else jnp.zeros(np.shape(p), jnp.float32)
                 for k, p in device_params.items()}
            new_params, slots = self._apply(device_params, device_grads, m, v,
                                            jnp.int32(self.step))
            self._slots = {
                **{f"m/{k}": x for k, x in slots["m"].items()},
                **{f"v/{k}": x for k, x in slots["v"].items()},
            }
        return new_params

    def state_dict(self) -> dict:
        out = {k: np.asarray(v) for k, v in self._slots.items()
               if v is not None}
        if self.step:
            out["step"] = np.asarray([self.step], np.int64)
        return out

    def load_state_dict(self, state: dict) -> None:
        state = dict(state or {})
        step = state.pop("step", None)
        self.step = int(np.asarray(step)[0]) if step is not None else 0
        self._slots = {k: jnp.asarray(np.asarray(v, np.float32))
                       for k, v in state.items()}
