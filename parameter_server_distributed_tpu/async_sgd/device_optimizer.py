"""Device-resident optimizers for the async parameter server.

In bounded-staleness mode updates apply on arrival (no barrier), so the
apply path is the PS hot loop.  The host optimizers in core/optimizer.py
walk numpy arrays on the CPU — fine for MNIST, not for a 1B-param store.
These optimizers keep parameters and slots as jax Arrays on the accelerator
and apply updates under jit, donating the optimizer slot buffers.  Params
are deliberately NOT donated: ps_core keeps serving previously-returned
param dicts concurrently and those may alias the apply inputs, so each
apply transiently holds old+new param buffers (~2x the store) before the
old copy is released.

Two apply backends, A/B-comparable via ``PSDT_BENCH_PS_OPT`` in bench.py:

- :class:`DeviceOptimizer` — optax transformation under jit (XLA fuses it).
- :class:`PallasOptimizer` — the hand-fused pallas kernels from
  ops/pallas/fused_update.py (one VMEM-tiled pass per tensor).

Both drop into `ParameterServerCore(optimizer=...)` unchanged — they satisfy
the HostOptimizer protocol (apply/state_dict/load_state_dict) and are
selected by name through `core.optimizer.make_optimizer`
(``device_*`` / ``pallas_*``).

They are equally valid on the SYNCHRONOUS barrier path (opt in with
``--optimizer pallas_sgd`` etc. on the PS): the streaming close hands the
contributor mean to ``apply`` exactly as it would a host optimizer, and
the whole-store jit program runs the update on the accelerator.  Both
keep ``supports_striping = False`` — a jit-compiled whole-store program
is not name-sliceable, and splitting it into S programs would recompile
per stripe and serialize on the device queue anyway, so the striped
barrier close (core/ps_core.py, PSDT_STRIPES) deliberately falls back to
this serial whole-store apply for them.  The accelerator IS the
parallelism in that configuration.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..analysis.lock_order import checked_lock
from ..core import device_apply
from ..core.optimizer import HostOptimizer


def _stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """Unbiased f32 -> bf16 rounding: add uniform noise to the 16 bits
    being dropped, then truncate.  E[result] == x, so a narrow EMA keeps
    tracking even when its per-step change is below the bf16 half-ulp —
    deterministic round-to-nearest would freeze it there (an EMA with
    decay 0.999 moves ~0.1%/step; bf16's half-ulp is ~0.2%)."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    # carry from the low 16 bits rounds up to the next representable bf16
    # with probability = dropped-fraction; NaN/inf inputs don't occur here
    # (moments are finite EMAs of finite gradients)
    rounded = ((bits + noise) >> 16).astype(jnp.uint16)
    return jax.lax.bitcast_convert_type(rounded, jnp.bfloat16)


def _adam_with_bf16_slots(b1: float, b2: float,
                          eps: float) -> optax.GradientTransformation:
    """scale_by_adam with BOTH moment slots stored in bfloat16 (half the
    optimizer-state HBM: 8 GB -> 4 GB for a 1B-param store).

    All arithmetic runs in f32 — only the carried state is narrowed, and
    the narrowing uses STOCHASTIC rounding (:func:`_stochastic_round_bf16`)
    so the EMAs stay unbiased: with round-to-nearest, b2=0.999's ~0.1%
    per-step change is below bf16's ~0.2% half-ulp and the second moment
    would freeze at a stale value the moment gradients shrink (exactly why
    optax's own ``mu_dtype`` narrows only the FIRST moment).  The PRNG key
    rides in the optimizer state."""

    def init_fn(params):
        zeros = lambda p: jnp.zeros(jnp.shape(p), jnp.bfloat16)  # noqa: E731
        # old-style uint32 key: the checkpoint sidecar snapshots state
        # leaves via np.asarray, which typed key arrays reject
        return {"count": jnp.zeros((), jnp.int32),
                "key": jax.random.PRNGKey(0),
                "mu": jax.tree.map(zeros, params),
                "nu": jax.tree.map(zeros, params)}

    def update_fn(updates, state, params=None):
        del params
        count = state["count"] + 1
        f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
        mu = jax.tree.map(lambda m, g: b1 * f32(m) + (1 - b1) * f32(g),
                          state["mu"], updates)
        nu = jax.tree.map(
            lambda v, g: b2 * f32(v) + (1 - b2) * jnp.square(f32(g)),
            state["nu"], updates)
        bc1 = 1.0 - b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count.astype(jnp.float32)
        out = jax.tree.map(
            lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
        key, sub = jax.random.split(state["key"])
        leaves, treedef = jax.tree.flatten({"mu": mu, "nu": nu})
        narrowed = jax.tree.unflatten(treedef, [
            _stochastic_round_bf16(leaf, k)
            for leaf, k in zip(leaves,
                               jax.random.split(sub, len(leaves)))])
        return out, {"count": count, "key": key,
                     "mu": narrowed["mu"], "nu": narrowed["nu"]}

    return optax.GradientTransformation(init_fn, update_fn)


class DeviceOptimizer(HostOptimizer):
    # whole-store jit program — not name-sliceable (see module docstring)
    supports_striping = False

    def __init__(self, transformation: optax.GradientTransformation,
                 learning_rate: float = 0.0):
        super().__init__(learning_rate)
        self._tx = transformation
        self._opt_state = None

        def apply(params, grads, opt_state):
            updates, new_opt = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        # Donate the opt state (private to this object) but NOT params:
        # ps_core keeps serving previously-returned param dicts concurrently,
        # and under async pushes those alias the apply inputs — donating
        # them would invalidate in-flight pull snapshots.
        self._apply = jax.jit(apply, donate_argnums=(2,))

    @classmethod
    def sgd(cls, learning_rate: float = 1.0) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate), learning_rate)

    @classmethod
    def momentum(cls, learning_rate: float = 1.0,
                 momentum: float = 0.9) -> "DeviceOptimizer":
        return cls(optax.sgd(learning_rate, momentum=momentum), learning_rate)

    @classmethod
    def adam(cls, learning_rate: float = 1e-3) -> "DeviceOptimizer":
        return cls(optax.adam(learning_rate), learning_rate)

    @classmethod
    def adamw(cls, learning_rate: float = 1e-3,
              weight_decay: float = 1e-4) -> "DeviceOptimizer":
        # matrices-only decay mask, matching parallel/train_step and the
        # host AdamW (decaying norm scales/biases is a quality bug)
        return cls(optax.adamw(
            learning_rate, weight_decay=weight_decay,
            mask=lambda params: jax.tree.map(
                lambda p: p.ndim >= 2, params)), learning_rate)

    @classmethod
    def adamw_bf16(cls, learning_rate: float = 1e-3,
                   weight_decay: float = 1e-4) -> "DeviceOptimizer":
        """AdamW with both moment slots carried in bfloat16 (stochastic
        rounding keeps the EMAs unbiased) — half the optimizer-state HBM
        of :meth:`adamw`; same matrices-only decoupled decay."""
        return cls(optax.chain(
            _adam_with_bf16_slots(0.9, 0.999, 1e-8),
            optax.add_decayed_weights(
                weight_decay, mask=lambda params: jax.tree.map(
                    lambda p: p.ndim >= 2, params)),
            optax.scale(-learning_rate)), learning_rate)

    def apply(self, params: Mapping[str, np.ndarray],
              grads: Mapping[str, np.ndarray]) -> dict:
        device_params = {k: jnp.asarray(v) for k, v in params.items()}
        device_grads = {k: jnp.asarray(np.asarray(grads[k], np.float32))
                        if k in grads else jnp.zeros_like(device_params[k])
                        for k in device_params}
        if self._opt_state is None:
            self._opt_state = self._tx.init(device_params)
        new_params, self._opt_state = self._apply(device_params, device_grads,
                                                  self._opt_state)
        return new_params

    def state_dict(self) -> dict:
        """Checkpoint-codec-friendly: a single uint8 'pickle' entry holding
        (leaves-as-numpy, treedef) so the optimizer sidecar (an npz) can
        store it without knowing optax's pytree structure."""
        import pickle

        if self._opt_state is None:
            return {}
        leaves, treedef = jax.tree.flatten(self._opt_state)
        blob = pickle.dumps(([np.asarray(leaf) for leaf in leaves], treedef))
        return {"pickle": np.frombuffer(blob, dtype=np.uint8)}

    def load_state_dict(self, state: dict) -> None:
        import pickle

        if not state or "pickle" not in state:
            self._opt_state = None
            return
        leaves, treedef = pickle.loads(np.asarray(state["pickle"],
                                                  np.uint8).tobytes())
        self._opt_state = jax.tree.unflatten(
            treedef, [jnp.asarray(leaf) for leaf in leaves])


class PallasOptimizer(HostOptimizer):
    """Device-resident PS optimizer whose apply path is the fused pallas
    update kernels (ops/pallas/fused_update.py) instead of an optax chain.
    One jit-compiled, buffer-donating program per rule; Adam's per-step bias
    corrections ride in as data (SMEM scalars), so stepping never
    recompiles."""

    # whole-store jit program — not name-sliceable (see module docstring)
    supports_striping = False

    RULES = ("sgd", "momentum", "adam")

    def __init__(self, rule: str = "sgd", learning_rate: float = 1.0,
                 momentum: float = 0.9, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8):
        super().__init__(learning_rate)
        if rule not in self.RULES:
            raise ValueError(f"unknown pallas rule {rule!r}; options {self.RULES}")
        self.rule = rule
        self.momentum = momentum
        self.b1, self.b2, self.eps = b1, b2, eps
        self._slots: dict[str, jax.Array] = {}   # vel/<n>, m/<n>, v/<n>
        self.step = 0
        from ..ops.pallas import fused_update as fu

        # Donate slot buffers (private to this object) but NOT params — see
        # DeviceOptimizer: served param snapshots may alias apply inputs.
        if rule == "sgd":
            def apply_fn(params, grads):
                return fu.fused_sgd(params, grads, lr=learning_rate), {}
            donate = ()
        elif rule == "momentum":
            def apply_fn(params, grads, velocity):
                new_p, new_v = fu.fused_momentum(
                    params, grads, velocity, lr=learning_rate, mu=momentum)
                return new_p, {"vel": new_v}
            donate = (2,)
        else:
            def apply_fn(params, grads, m, v, step):
                new_p, new_m, new_v = fu.fused_adam(
                    params, grads, m, v, step, lr=learning_rate, b1=b1,
                    b2=b2, eps=eps)
                return new_p, {"m": new_m, "v": new_v}
            donate = (2, 3)
        self._apply = jax.jit(apply_fn, donate_argnums=donate)

    def apply(self, params: Mapping[str, np.ndarray],
              grads: Mapping[str, np.ndarray]) -> dict:
        device_params = {k: jnp.asarray(v) for k, v in params.items()}
        device_grads = {k: jnp.asarray(np.asarray(v, np.float32))
                        for k, v in grads.items() if k in device_params}
        self.step += 1
        if self.rule == "sgd":
            new_params, _ = self._apply(device_params, device_grads)
        elif self.rule == "momentum":
            vel = {k: self._slots.get(f"vel/{k}")
                   if f"vel/{k}" in self._slots
                   else jnp.zeros(np.shape(p), jnp.float32)
                   for k, p in device_params.items()}
            new_params, slots = self._apply(device_params, device_grads, vel)
            self._slots = {f"vel/{k}": v for k, v in slots["vel"].items()}
        else:
            # independent zero buffers per slot — both m and v are donated,
            # so they must never alias
            m = {k: self._slots.get(f"m/{k}")
                 if f"m/{k}" in self._slots
                 else jnp.zeros(np.shape(p), jnp.float32)
                 for k, p in device_params.items()}
            v = {k: self._slots.get(f"v/{k}")
                 if f"v/{k}" in self._slots
                 else jnp.zeros(np.shape(p), jnp.float32)
                 for k, p in device_params.items()}
            new_params, slots = self._apply(device_params, device_grads, m, v,
                                            jnp.int32(self.step))
            self._slots = {
                **{f"m/{k}": x for k, x in slots["m"].items()},
                **{f"v/{k}": x for k, x in slots["v"].items()},
            }
        return new_params

    def state_dict(self) -> dict:
        out = {k: np.asarray(v) for k, v in self._slots.items()
               if v is not None}
        if self.step:
            out["step"] = np.asarray([self.step], np.int64)
        return out

    def load_state_dict(self, state: dict) -> None:
        state = dict(state or {})
        step = state.pop("step", None)
        self.step = int(np.asarray(step)[0]) if step is not None else 0
        self._slots = {k: jnp.asarray(np.asarray(v, np.float32))
                       for k, v in state.items()}


# --------------------------------------------------------------------------
# ISSUE 11: the accelerator-resident SHARDED apply family.  Unlike the
# whole-store optax/pallas programs above, these are name-sliceable
# (supports_striping = True): slot state is keyed per tensor name exactly
# like the host optimizers', so the striped barrier close runs
# apply_shard concurrently over disjoint name subsets, each tensor's
# update executing as a short chain of jit-compiled FUSED device stages
# (core/device_apply.py).  Each stage obeys the no-product-into-add rule
# that makes it bit-identical to the numpy oracle while sweeping memory
# once instead of once per ufunc — see that module's docstring for the
# XLA:CPU FMA-contraction story.  Retired slot buffers and intermediates
# are DONATED through the stage chain; parameters and gradients never
# are — ps_core keeps serving previously-returned param dicts (and the
# PR-10 delta sink reads old stores), so old param buffers must stay
# valid.
# --------------------------------------------------------------------------


class ShardedDeviceOptimizer(HostOptimizer):
    """Device-resident, stripe-sliceable PS optimizer (ISSUE 11).

    Update rules mirror core/optimizer.py's numpy sequences rounding for
    rounding (same f32 scalars, same operation order), so a device apply
    is bit-identical to the host apply at f32 — the oracle tests pin it.
    State layout matches the host optimizers' ``state_dict`` exactly
    (``velocity`` / ``m``+``v``+``step`` / ``m``), so checkpoints
    round-trip between host and device optimizers through the existing
    .ckpt sidecar layout bit-identically, across restore stripe counts
    (per-name slots make the state stripe-count independent by
    construction).

    Thread-safety matches the host optimizers: ``apply_shard`` over
    disjoint name subsets is safe by construction (each tensor touches
    only its own slot entries; per-key dict writes are GIL-atomic), the
    caller serializes logical steps, and ``_lock`` only fences the
    checkpoint snapshot/restore paths, whose D2H slot readback may block
    under it (analysis/lock_order.py: rank 45, BLOCKING_ALLOWED)."""

    supports_striping = True
    device_resident = True
    # flat-arena apply (core/arena.py, ISSUE 15): the five rules also run
    # as ONE fused kernel per stage per stripe over per-stripe mega-array
    # slabs when the core arms PSDT_ARENA — see apply_arena below
    supports_arena = True

    RULES = ("sgd", "momentum", "adam", "adamw", "lion")
    _RULE_SLOTS = {"sgd": (), "momentum": ("velocity",),
                   "adam": ("m", "v"), "adamw": ("m", "v"), "lion": ("m",)}

    def __init__(self, rule: str, learning_rate: float,
                 momentum: float = 0.9, weight_decay: float = 1e-4,
                 b1: float | None = None, b2: float | None = None,
                 eps: float = 1e-8):
        if rule not in self.RULES:
            raise ValueError(
                f"unknown sharded device rule {rule!r}; options {self.RULES}")
        super().__init__(learning_rate)
        self.rule = rule
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.b1 = 0.9 if b1 is None else b1
        self.b2 = ((0.99 if rule == "lion" else 0.999) if b2 is None
                   else b2)
        self.eps = eps
        self.step = 0
        # slot: name -> device f32 array, per slot kind — the same
        # per-name keying as the host optimizers (stripe-sliceable)
        self._slots: dict[str, dict] = {
            s: {} for s in self._RULE_SLOTS[rule]}
        # retained per-tensor scratch for short-lived update
        # intermediates (kind -> name -> device array): recycled through
        # kernel donation every close (core/device_apply.py "scratch
        # recycling"), the device analogue of the host optimizers'
        # thread-local scratch.  NOT optimizer state — never
        # checkpointed; holds garbage values between closes by design.
        # Space cost: up to 3 extra store-sized buffers for adam/adamw,
        # 3 for lion, 0 for sgd/momentum — the same space-for-page-fault
        # trade the host scratch makes.
        self._scr: dict[str, dict] = {}
        self._bc_step = -1
        self._bc1 = np.float32(1.0)
        self._bc2 = np.float32(1.0)
        # flat-arena slot state (core/arena.py, ISSUE 15): when the core
        # runs the arena close, each slot kind lives as ONE flat device
        # slab per stripe instead of the per-name tables above —
        # `_arena_slots[kind][stripe]`, packed for `_arena_table`'s
        # epoch.  The per-name `_slots` tables then hold STALE entries;
        # every per-tensor consumer (apply_shard fallback closes,
        # checkpoint snapshots) goes through _spill_arena_locked /
        # _arena_state_dict first, so the slabs are always the single
        # source of truth while they exist.
        self._arena_slots: dict[str, dict[int, object]] = {}
        self._arena_table = None
        self._arena_scr: dict[tuple, object] = {}  # (kind, stripe) slabs
        # fences checkpoint snapshot/restore of the slot tables; the D2H
        # slot readback runs under it (rank 45, BLOCKING_ALLOWED —
        # analysis/lock_order.py).  The apply path does NOT take it:
        # stripe applies are disjoint by name and serialized against
        # state_dict by the core's _apply_lock, like the host optimizers.
        self._lock = checked_lock("ShardedDeviceOptimizer._lock")

    # ------------------------------------------------------------- steps
    def tick(self) -> None:
        if self.rule in ("adam", "adamw"):
            self.step += 1

    def _bias_corrections(self) -> tuple[np.float32, np.float32]:
        if self._bc_step != self.step:
            # python-float powers then ONE f32 round — exactly the numpy
            # path's cast-on-use of `1.0 - b1 ** step`.  Benign if two
            # stripes race here: both write identical values.
            self._bc1 = np.float32(1.0 - self.b1 ** self.step)
            self._bc2 = np.float32(1.0 - self.b2 ** self.step)
            self._bc_step = self.step
        return self._bc1, self._bc2

    # ------------------------------------------------------------- apply
    def apply_shard(self, params, grads) -> dict:
        """One shard's update as BATCHED per-stripe device programs: the
        shard's tensors run through each update stage as ONE jit
        dispatch over the tensor list (lists are pytrees, so programs
        are shape-bucketed by the shard's shape-signature — a fixed set
        per stripe config), with per-tensor arithmetic identical to the
        host optimizers' ufunc sequences."""
        if self._arena_slots:
            # a per-tensor apply while arena slot slabs are live (a
            # fallback close, a mode flip): the slabs are the source of
            # truth — spill them back into the per-name tables first
            with self._lock:
                self._spill_arena_locked()
        out: dict = {}
        todo: list[str] = []
        for name, p in params.items():
            if name not in grads:
                # pass-through, like the host optimizers' np.asarray —
                # a device-resident value stays device-resident
                out[name] = (p if device_apply.is_device_array(p)
                             else np.asarray(p, np.float32))
            else:
                todo.append(name)
        if todo:
            # deterministic order => one program signature per shard
            todo.sort()
            ps = [device_apply.owned_f32(params[n]) for n in todo]
            gs = [device_apply.owned_f32(grads[n]) for n in todo]
            # validate slot shapes BEFORE any stage runs: the batched
            # kernels DONATE slot buffers, so a shape mismatch surfacing
            # at trace time after a donation would leave self._slots
            # holding deleted arrays (every later step bricked) — and a
            # broadcast-compatible mismatch would not surface at all.
            # Raising here mirrors the host optimizers: error out with
            # the slot tables untouched and the apply retryable.
            for name, p, g in zip(todo, ps, gs):
                if p.shape != g.shape:
                    raise ValueError(
                        f"param/gradient shape mismatch for {name!r}: "
                        f"{p.shape} vs {g.shape}")
            for slot, table in self._slots.items():
                for name, g in zip(todo, gs):
                    s = table.get(name)
                    if s is not None and s.shape != g.shape:
                        raise ValueError(
                            f"slot {slot!r} shape mismatch for {name!r}: "
                            f"{s.shape} vs gradient {g.shape}")
            for name, newp in zip(todo, self._apply_batch(todo, ps, gs)):
                out[name] = newp
        return out

    def _scratch_list(self, kind: str, names, gs) -> list:
        """The retained scratch buffers for (kind, each name) — a
        one-time zeros seed on first touch / shape change (elastic
        reshard).  Callers stash the stage outputs back via
        :meth:`_stash` so the buffers recycle through donation."""
        table = self._scr.setdefault(kind, {})
        out = []
        for name, g in zip(names, gs):
            s = table.get(name)
            if s is None or s.shape != g.shape:
                s = _zeros_f32(g.shape)
            out.append(s)
        return out

    def _stash(self, kind: str, names, arrs) -> None:
        table = self._scr[kind]
        for name, arr in zip(names, arrs):
            table[name] = arr

    def _apply_batch(self, names: list[str], ps: list, gs: list) -> list:
        k = device_apply.k
        false = np.bool_(False)  # runtime pred: XLA cannot fold the select
        lr = np.float32(self.learning_rate)
        if self.rule == "sgd":
            # us = g*lr are the close's fresh buffers; b_psub donates
            # them and their buffers leave as the new params
            return k("b_psub")(ps, k("b_mul")(gs, lr))
        if self.rule == "momentum":
            return self._momentum_batch(names, ps, gs, lr)
        if self.rule == "lion":
            return self._lion_batch(names, ps, gs, lr, false)
        return self._adam_batch(names, ps, gs, lr, false)

    def _momentum_batch(self, names, ps, gs, lr) -> list:
        k = device_apply.k
        slots = self._slots["velocity"]
        out: list = [None] * len(names)
        seed = [i for i, n in enumerate(names) if n not in slots]
        upd = [i for i, n in enumerate(names) if n in slots]
        if seed:
            # first touch: v = g (a bit-copy, the numpy `np.array(g)`
            # seed — a FRESH buffer, because the slot is donated on the
            # next step), step = v * lr (not donated: v2 is the slot)
            v2s = [device_apply.owned_copy(gs[i]) for i in seed]
            news = k("b_psub")([ps[i] for i in seed],
                               k("b_mul")(v2s, lr))
            for j, i in enumerate(seed):
                slots[names[i]] = v2s[j]
                out[i] = news[j]
        if upd:
            # v2 = mu*v + g and step = v2*lr in two fused stages; the
            # old slot buffers are donated into the products
            ts = k("b_mul_d0")([slots[names[i]] for i in upd],
                               np.float32(self.momentum))
            v2s, steps = k("b_mom_pair")(ts, [gs[i] for i in upd], lr)
            news = k("b_psub")([ps[i] for i in upd], steps)
            for j, i in enumerate(upd):
                slots[names[i]] = v2s[j]
                out[i] = news[j]
        return out

    def _lion_batch(self, names, ps, gs, lr, false) -> list:
        k = device_apply.k
        b1 = np.float32(self.b1)
        b2 = np.float32(self.b2)
        one = np.float32(1.0)
        slots = self._slots["m"]
        ms = [slots.get(n) for n in names]
        ms = [m if m is not None else _zeros_f32(g.shape)
              for m, g in zip(ms, gs)]
        t1s, t2s, t3s, t4s = k("b_lion_mul4")(
            ms, gs, b1, one - b1, b2, one - b2,
            self._scratch_list("t2", names, gs),
            self._scratch_list("t4", names, gs), false)
        self._stash("t2", names, t2s)
        self._stash("t4", names, t4s)
        us = k("b_sign_add")(t1s, t2s)
        for name, m2 in zip(names, k("b_add_d0")(t3s, t4s)):
            slots[name] = m2
        # decoupled decay on matrices only (the host mask): split the
        # shard into the decayed and plain lanes, each one batch
        wd = np.float32(self.weight_decay)
        dec = [i for i, p in enumerate(ps)
               if self.weight_decay and getattr(p, "ndim", 0) >= 2]
        plain = [i for i in range(len(ps)) if i not in dec]
        if dec:
            dnames = [names[i] for i in dec]
            dgs = [gs[i] for i in dec]
            ts = k("b_wd_mul")([ps[i] for i in dec], wd,
                               self._scratch_list("wd", dnames, dgs),
                               false)
            self._stash("wd", dnames, ts)
            for j, u in zip(dec, k("b_addmul")([us[i] for i in dec],
                                               ts, lr)):
                us[j] = u
        if plain:
            for j, u in zip(plain,
                            k("b_mul_d0")([us[i] for i in plain], lr)):
                us[j] = u
        return k("b_psub")(ps, us)

    def _adam_batch(self, names, ps, gs, lr, false) -> list:
        k = device_apply.k
        b1 = np.float32(self.b1)
        b2 = np.float32(self.b2)
        one = np.float32(1.0)
        ms_t, vs_t = self._slots["m"], self._slots["v"]
        ms = [ms_t.get(n) for n in names]
        ms = [m if m is not None else _zeros_f32(g.shape)
              for m, g in zip(ms, gs)]
        vs = [vs_t.get(n) for n in names]
        vs = [v if v is not None else _zeros_f32(g.shape)
              for v, g in zip(vs, gs)]
        t1s, t2s, t3s, t4s = k("b_adam_mul4")(
            ms, vs, gs, b1, one - b1, b2, one - b2,
            self._scratch_list("t2", names, gs),
            self._scratch_list("t4", names, gs), false)
        self._stash("t2", names, t2s)
        self._stash("t4", names, t4s)
        m2s, v2s = k("b_add2")(t1s, t2s, t3s, t4s)
        for name, m2, v2 in zip(names, m2s, v2s):
            ms_t[name], vs_t[name] = m2, v2
        bc1, bc2 = self._bias_corrections()
        eps = np.float32(self.eps)
        if self.rule == "adam":
            # single-sweep tail (see b_adam_fin1): no den/mh
            # materialization, the output is the fresh params buffer
            return k("b_adam_fin1")(ps, m2s, v2s, bc1, bc2, eps, lr)
        # adamw: decoupled decay from the PRE-update param, matrices
        # only (the host mask), lr LAST
        dens, mhs = k("b_adamw_den_mh")(
            v2s, bc2, eps, m2s, bc1,
            self._scratch_list("den", names, gs), false)
        self._stash("den", names, dens)
        us: list = [None] * len(names)
        dec = [i for i, p in enumerate(ps)
               if self.weight_decay and getattr(p, "ndim", 0) >= 2]
        plain = [i for i in range(len(ps)) if i not in dec]
        if dec:
            dnames = [names[i] for i in dec]
            dgs = [gs[i] for i in dec]
            ts = k("b_wd_mul")([ps[i] for i in dec],
                               np.float32(self.weight_decay),
                               self._scratch_list("wd", dnames, dgs),
                               false)
            self._stash("wd", dnames, ts)
            for j, u in zip(dec, k("b_adamw_fin_wd")(
                    [mhs[i] for i in dec], [dens[i] for i in dec],
                    ts, lr)):
                us[j] = u
        if plain:
            for j, u in zip(plain, k("b_adamw_fin")(
                    [mhs[i] for i in plain],
                    [dens[i] for i in plain], lr)):
                us[j] = u
        return k("b_psub")(ps, us)

    # ------------------------------------------------------------ arena
    # Flat-arena apply (core/arena.py, ISSUE 15): the same five update
    # rules over per-stripe mega-array slabs — one fused kernel per
    # STAGE per STRIPE regardless of tensor count, reusing the batched
    # stage kernels above with single-slab operand lists (plus the
    # masked a_* tails for the AdamW/Lion decay lanes).  Per-element
    # arithmetic is untouched, so the numpy oracle holds bit for bit.

    def arena_ready(self, table) -> bool:
        """True when this optimizer can run ``table`` flat.  Only
        Momentum can refuse: its first-touch slot seed is a BIT COPY of
        the gradient (not ``mu*0 + g`` — that flips -0.0), so a MIXED
        velocity table (some names seeded, some not — reshard merges)
        cannot flatten and takes the per-tensor close instead.  Slabs
        short-circuit the check only at the SAME table epoch: slabs
        packed for an older layout (the store grew) spill back to
        per-name first, so the new name's missing velocity is seen —
        repacking it as zeros would break the copy-seed contract."""
        if self.rule != "momentum":
            return True
        if self._arena_slots:
            if (self._arena_table is not None
                    and self._arena_table.epoch == table.epoch):
                return True
            with self._lock:
                self._spill_arena_locked()
        have = set(self._slots["velocity"]) & set(table.entries)
        return not have or have == set(table.entries)

    def apply_arena(self, table, param_slabs: Mapping[int, object],
                    grad_slabs: Mapping[int, object]) -> dict:
        """One logical step over flat slabs: per stripe, the rule's
        stage chain as fused kernels over the whole slab.  Slot slabs
        update in place (donated through the chain exactly like the
        per-tensor slot buffers); param and gradient slabs are never
        donated (serves alias old stores, failed applies put sums
        back).  Returns the fresh param slabs.  Caller serializes
        logical steps (the core's _apply_lock) and has proven full
        gradient coverage and :meth:`arena_ready`."""
        from ..core.stripes import run_striped

        self._ensure_arena_slots(table)
        lr = np.float32(self.learning_rate)
        false = np.bool_(False)
        stripes = sorted(param_slabs)
        if len(stripes) <= 1:
            return {s: self._arena_stripe(table, s, param_slabs[s],
                                          grad_slabs[s], lr, false)
                    for s in stripes}
        # fan the per-stripe chains across the stripe executor: each
        # chain is a handful of dispatches over disjoint slabs (disjoint
        # slot/scratch keys, GIL-atomic dict writes), so concurrent
        # dispatch costs nothing when XLA parallelizes internally and
        # recovers the multi-core sweeps when the runtime executes a
        # call synchronously (the default thunk runtime)
        results = run_striped([
            (lambda s=s: (s, self._arena_stripe(
                table, s, param_slabs[s], grad_slabs[s], lr, false)))
            for s in stripes])
        return dict(results)

    def _arena_stripe(self, table, stripe, p, g, lr, false):
        chunk = device_apply.stage_chunk_elems()
        if chunk > 0:
            size = int(table.stripe_sizes[stripe])
            if size > chunk:
                return self._arena_stripe_chunked(table, stripe, p, g, lr,
                                                  false, chunk, size)
        k = device_apply.k
        if self.rule == "sgd":
            return k("b_psub")([p], k("b_mul")([g], lr))[0]
        if self.rule == "momentum":
            slots = self._arena_slots["velocity"]
            v = slots.get(stripe)
            if v is None:
                # unseeded stripe: the host's copy-seed, flat — a bit
                # copy into a FRESH buffer (the sums slab must survive
                # for put-back; the slot is donated next step)
                v2 = k("a_copy")(g, false)
                slots[stripe] = v2
                return k("b_psub")([p], k("b_mul")([v2], lr))[0]
            ts = k("b_mul_d0")([v], np.float32(self.momentum))
            v2s, steps = k("b_mom_pair")(ts, [g], lr)
            slots[stripe] = v2s[0]
            return k("b_psub")([p], steps)[0]
        if self.rule == "lion":
            return self._arena_lion(table, stripe, p, g, lr, false)
        return self._arena_adam(table, stripe, p, g, lr, false)

    def _arena_scratch(self, kind: str, stripe: int, g):
        s = self._arena_scr.get((kind, stripe))
        if s is None or s.shape != g.shape:
            s = _zeros_f32(g.shape)
        return s

    def _arena_adam(self, table, stripe, p, g, lr, false):
        k = device_apply.k
        b1, b2 = np.float32(self.b1), np.float32(self.b2)
        one = np.float32(1.0)
        ms, vs = self._arena_slots["m"], self._arena_slots["v"]
        m = ms.get(stripe)
        v = vs.get(stripe)
        if m is None:
            m = _zeros_f32(g.shape)   # the host zeros-seed, flat
        if v is None:
            v = _zeros_f32(g.shape)
        t1s, t2s, t3s, t4s = k("b_adam_mul4")(
            [m], [v], [g], b1, one - b1, b2, one - b2,
            [self._arena_scratch("t2", stripe, g)],
            [self._arena_scratch("t4", stripe, g)], false)
        self._arena_scr[("t2", stripe)] = t2s[0]
        self._arena_scr[("t4", stripe)] = t4s[0]
        m2s, v2s = k("b_add2")(t1s, t2s, t3s, t4s)
        ms[stripe], vs[stripe] = m2s[0], v2s[0]
        bc1, bc2 = self._bias_corrections()
        eps = np.float32(self.eps)
        if self.rule == "adam":
            return k("b_adam_fin1")([p], m2s, v2s, bc1, bc2, eps, lr)[0]
        dens, mhs = k("b_adamw_den_mh")(
            v2s, bc2, eps, m2s, bc1,
            [self._arena_scratch("den", stripe, g)], false)
        self._arena_scr[("den", stripe)] = dens[0]
        if not self.weight_decay:
            us = k("b_adamw_fin")(mhs, dens, lr)
            return k("b_psub")([p], us)[0]
        mask = table.decay_mask(stripe)
        t = k("a_wd_mul")(p, np.float32(self.weight_decay), mask,
                          self._arena_scratch("wd", stripe, g), false)
        self._arena_scr[("wd", stripe)] = t
        u = k("a_adamw_fin")(mhs[0], dens[0], t, mask, lr)
        return k("b_psub")([p], [u])[0]

    def _arena_lion(self, table, stripe, p, g, lr, false):
        k = device_apply.k
        b1, b2 = np.float32(self.b1), np.float32(self.b2)
        one = np.float32(1.0)
        slots = self._arena_slots["m"]
        m = slots.get(stripe)
        if m is None:
            m = _zeros_f32(g.shape)
        t1s, t2s, t3s, t4s = k("b_lion_mul4")(
            [m], [g], b1, one - b1, b2, one - b2,
            [self._arena_scratch("t2", stripe, g)],
            [self._arena_scratch("t4", stripe, g)], false)
        self._arena_scr[("t2", stripe)] = t2s[0]
        self._arena_scr[("t4", stripe)] = t4s[0]
        us = k("b_sign_add")(t1s, t2s)
        slots[stripe] = k("b_add_d0")(t3s, t4s)[0]
        if not self.weight_decay:
            return k("b_psub")([p], k("b_mul_d0")(us, lr))[0]
        mask = table.decay_mask(stripe)
        t = k("a_wd_mul")(p, np.float32(self.weight_decay), mask,
                          self._arena_scratch("wd", stripe, g), false)
        self._arena_scr[("wd", stripe)] = t
        u = k("a_lion_fin")(us[0], t, mask, lr)
        return k("b_psub")([p], [u])[0]

    # --------------------------------------- arena range apply (pure)
    # Per-[lo, hi) slices of the per-stripe stage chain: the shared
    # machinery behind intra-host stage chunking (PSDT_DEVICE_STAGE_CHUNK)
    # and the cross-replica sharded update (replication/sharded_update.py),
    # where each replica runs only its owned slices.  Every stage is
    # elementwise, so a slice-of-apply is bit-identical to the
    # apply-of-slice — pinned by tests/test_sharded_update.py.

    def _arena_stripe_chunked(self, table, stripe, p, g, lr, false,
                              chunk, size):
        """The whole-stripe apply as ceil(size/chunk) independent range
        programs (sub-chunked stage programs, ISSUE 15 leftover).  Slot
        reads all happen against the pre-close slabs (the range apply is
        pure); the fresh slot slices commit at the end, exactly like the
        one-shot path's in-place donation semantics."""
        import jax.numpy as jnp

        pieces = []
        slot_pieces: dict[str, list] = {
            kind: [] for kind in self._RULE_SLOTS[self.rule]}
        for lo in range(0, size, chunk):
            hi = min(lo + chunk, size)
            new_p, slots = self.apply_arena_range(
                table, stripe, p[lo:hi], g[lo:hi], lo, hi, false=false)
            pieces.append(new_p)
            for kind, arr in slots.items():
                slot_pieces[kind].append((lo, hi, arr))
        self.commit_arena_ranges(
            table, stripe, {k: v for k, v in slot_pieces.items() if v})
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    def apply_arena_range(self, table, stripe, p, g, lo, hi, false=None):
        """PURE per-range arena apply: run the rule's fused stage chain
        over one contiguous ``[lo, hi)`` slice of stripe ``stripe`` and
        return ``(new_param_slice, {slot_kind: new_slot_slice})``
        WITHOUT touching the arena slot slabs — the caller commits the
        slot slices via :meth:`commit_arena_ranges` once its close
        passes the point of no return (a degraded sharded close must be
        able to fall back to the full local apply against unmodified
        slots, and a backup whose install leg never arrives must drop
        the slices without trace).

        ``p``/``g`` are f32 slices of the param and fold-sum slabs
        (device or host); slot state is read as SLICES of the live
        slabs — fresh buffers, so the stage kernels' donation consumes
        the slices, never the slabs.  Caller has run
        :meth:`ensure_arena_slots` and serializes logical steps."""
        k = device_apply.k
        if false is None:
            false = np.bool_(False)
        lr = np.float32(self.learning_rate)
        p = device_apply.owned_f32(p)
        g = device_apply.owned_f32(g)
        if self.rule == "sgd":
            return k("b_psub")([p], k("b_mul")([g], lr))[0], {}
        if self.rule == "momentum":
            slab = self._arena_slots.get("velocity", {}).get(stripe)
            if slab is None:
                # unseeded stripe: the copy-seed, per slice (a bit copy,
                # so concatenated slices == the whole-slab a_copy)
                v2 = k("a_copy")(g, false)
                return (k("b_psub")([p], k("b_mul")([v2], lr))[0],
                        {"velocity": v2})
            ts = k("b_mul_d0")([slab[lo:hi]], np.float32(self.momentum))
            v2s, steps = k("b_mom_pair")(ts, [g], lr)
            return k("b_psub")([p], steps)[0], {"velocity": v2s[0]}
        if self.rule == "lion":
            return self._arena_lion_range(table, stripe, p, g, lo, hi,
                                          lr, false)
        return self._arena_adam_range(table, stripe, p, g, lo, hi, lr,
                                      false)

    def _range_scratch(self, kind: str, stripe: int, lo: int, hi: int, g):
        s = self._arena_scr.get((kind, stripe, lo, hi))
        if s is None or s.shape != g.shape:
            s = _zeros_f32(g.shape)
        return s

    def _arena_adam_range(self, table, stripe, p, g, lo, hi, lr, false):
        k = device_apply.k
        b1, b2 = np.float32(self.b1), np.float32(self.b2)
        one = np.float32(1.0)
        m_slab = self._arena_slots.get("m", {}).get(stripe)
        v_slab = self._arena_slots.get("v", {}).get(stripe)
        m = _zeros_f32(g.shape) if m_slab is None else m_slab[lo:hi]
        v = _zeros_f32(g.shape) if v_slab is None else v_slab[lo:hi]
        t1s, t2s, t3s, t4s = k("b_adam_mul4")(
            [m], [v], [g], b1, one - b1, b2, one - b2,
            [self._range_scratch("t2", stripe, lo, hi, g)],
            [self._range_scratch("t4", stripe, lo, hi, g)], false)
        self._arena_scr[("t2", stripe, lo, hi)] = t2s[0]
        self._arena_scr[("t4", stripe, lo, hi)] = t4s[0]
        m2s, v2s = k("b_add2")(t1s, t2s, t3s, t4s)
        out_slots = {"m": m2s[0], "v": v2s[0]}
        bc1, bc2 = self._bias_corrections()
        eps = np.float32(self.eps)
        if self.rule == "adam":
            return (k("b_adam_fin1")([p], m2s, v2s, bc1, bc2, eps,
                                     lr)[0], out_slots)
        dens, mhs = k("b_adamw_den_mh")(
            v2s, bc2, eps, m2s, bc1,
            [self._range_scratch("den", stripe, lo, hi, g)], false)
        self._arena_scr[("den", stripe, lo, hi)] = dens[0]
        if not self.weight_decay:
            us = k("b_adamw_fin")(mhs, dens, lr)
            return k("b_psub")([p], us)[0], out_slots
        mask = table.decay_mask(stripe)[lo:hi]
        t = k("a_wd_mul")(p, np.float32(self.weight_decay), mask,
                          self._range_scratch("wd", stripe, lo, hi, g),
                          false)
        self._arena_scr[("wd", stripe, lo, hi)] = t
        u = k("a_adamw_fin")(mhs[0], dens[0], t, mask, lr)
        return k("b_psub")([p], [u])[0], out_slots

    def _arena_lion_range(self, table, stripe, p, g, lo, hi, lr, false):
        k = device_apply.k
        b1, b2 = np.float32(self.b1), np.float32(self.b2)
        one = np.float32(1.0)
        m_slab = self._arena_slots.get("m", {}).get(stripe)
        m = _zeros_f32(g.shape) if m_slab is None else m_slab[lo:hi]
        t1s, t2s, t3s, t4s = k("b_lion_mul4")(
            [m], [g], b1, one - b1, b2, one - b2,
            [self._range_scratch("t2", stripe, lo, hi, g)],
            [self._range_scratch("t4", stripe, lo, hi, g)], false)
        self._arena_scr[("t2", stripe, lo, hi)] = t2s[0]
        self._arena_scr[("t4", stripe, lo, hi)] = t4s[0]
        us = k("b_sign_add")(t1s, t2s)
        out_slots = {"m": k("b_add_d0")(t3s, t4s)[0]}
        if not self.weight_decay:
            return (k("b_psub")([p], k("b_mul_d0")(us, lr))[0],
                    out_slots)
        mask = table.decay_mask(stripe)[lo:hi]
        t = k("a_wd_mul")(p, np.float32(self.weight_decay), mask,
                          self._range_scratch("wd", stripe, lo, hi, g),
                          false)
        self._arena_scr[("wd", stripe, lo, hi)] = t
        u = k("a_lion_fin")(us[0], t, mask, lr)
        return k("b_psub")([p], [u])[0], out_slots

    def ensure_arena_slots(self, table) -> None:
        """Public face of the slot-slab pack for the range-apply
        callers (the sharded-update exchange runs it before slicing)."""
        self._ensure_arena_slots(table)

    def arena_slot_kinds(self) -> tuple:
        return self._RULE_SLOTS[self.rule]

    def arena_slot_slab(self, kind: str, stripe: int):
        """The live slot slab for (kind, stripe), or None (unseeded
        momentum / no slabs packed)."""
        return self._arena_slots.get(kind, {}).get(stripe)

    def commit_arena_ranges(self, table, stripe: int,
                            slot_pieces: Mapping[str, list]) -> None:
        """Write fresh slot slices into the arena slot slabs — the
        deferred other half of :meth:`apply_arena_range`, run only once
        a close commits.  ``slot_pieces`` maps slot kind to a list of
        ``(lo, hi, values)``; full contiguous coverage rebinds the slab
        as one concatenation (no read of the old slab), partial
        coverage scatters into the existing slab (a sharded backup
        commits only its OWNED ranges — its non-owned slot elements go
        stale by design, healed by the next flat state ship)."""
        import jax.numpy as jnp

        for kind, pieces in slot_pieces.items():
            if not pieces:
                continue
            per_stripe = self._arena_slots.setdefault(kind, {})
            pieces = sorted(pieces, key=lambda t: t[0])
            size = int(table.stripe_sizes[stripe])
            full = (pieces[0][0] == 0 and pieces[-1][1] == size
                    and all(pieces[i][1] == pieces[i + 1][0]
                            for i in range(len(pieces) - 1)))
            if full:
                vals = [device_apply.owned_f32(a) for _, _, a in pieces]
                per_stripe[stripe] = (vals[0] if len(vals) == 1
                                      else jnp.concatenate(vals))
                continue
            slab = per_stripe.get(stripe)
            if slab is None:
                slab = _zeros_f32((size,))
            for piece_lo, piece_hi, arr in pieces:
                slab = slab.at[piece_lo:piece_hi].set(
                    device_apply.owned_f32(arr))
            per_stripe[stripe] = slab

    # ------------------------------------------- arena slot slab sync
    def _ensure_arena_slots(self, table) -> None:
        """Pack the per-name slot tables into per-stripe slabs for
        ``table``'s epoch (one host concat + one H2D per (kind, stripe);
        missing names pack as zeros — exactly the host seed for every
        rule but Momentum, whose mixed case :meth:`arena_ready`
        excluded).  No-op when the slabs already match the epoch."""
        if (self._arena_table is not None
                and self._arena_table.epoch == table.epoch):
            self._arena_table = table
            return
        import jax.numpy as jnp

        with self._lock:
            if (self._arena_table is not None
                    and self._arena_table.epoch == table.epoch):
                self._arena_table = table
                return
            if self._arena_slots:
                # a REPACK (table epoch moved): spill the old slabs back
                # to per-name entries first so the new layout packs the
                # live values, not stale ones
                self._spill_arena_locked()
            slots: dict[str, dict[int, object]] = {}
            for kind in self._RULE_SLOTS[self.rule]:
                by_name = self._slots[kind]
                if self.rule == "momentum" and not by_name:
                    # unseeded: stripes seed lazily via the copy-seed
                    slots[kind] = {}
                    continue
                per_stripe: dict[int, object] = {}
                for stripe in range(table.stripes):
                    size = table.stripe_sizes[stripe]
                    if not size:
                        continue
                    host = np.zeros(size, np.float32)
                    for name in table.stripe_names[stripe]:
                        arr = by_name.get(name)
                        if arr is not None:
                            e = table.entries[name]
                            host[e.offset:e.offset + e.length] = (
                                np.asarray(np.asarray(arr),
                                           np.float32).reshape(-1))
                    per_stripe[stripe] = jnp.asarray(host)
                slots[kind] = per_stripe
                self._slots[kind] = {}
            self._arena_slots = slots
            self._arena_table = table
            self._arena_scr = {}

    def _spill_arena_locked(self) -> None:
        """Materialize the slot slabs back into the per-name tables
        (one D2H per slab, per-name device re-uploads) and drop them —
        the per-tensor consumers' escape hatch.  Caller holds _lock."""
        import jax.numpy as jnp

        table = self._arena_table
        if table is None or not self._arena_slots:
            self._arena_slots = {}
            self._arena_table = None
            return
        for kind, per_stripe in self._arena_slots.items():
            by_name = self._slots.setdefault(kind, {})
            for stripe, slab in per_stripe.items():
                host = np.asarray(slab)
                for name in table.stripe_names[stripe]:
                    e = table.entries[name]
                    by_name[name] = jnp.asarray(np.ascontiguousarray(
                        host[e.offset:e.offset + e.length])).reshape(
                            e.shape)
        self._arena_slots = {}
        self._arena_table = None
        self._arena_scr = {}

    # ------------------------------------------------------- checkpoint
    def state_dict(self) -> dict:
        with self._lock:
            if self._arena_slots:
                out = self._arena_state_dict_locked()
            else:
                out = {
                    slot: {name: np.array(np.asarray(arr))
                           for name, arr in table.items()}
                    for slot, table in self._slots.items()}
        if self.rule in ("adam", "adamw"):
            out["step"] = self.step
        return out

    def _arena_state_dict_locked(self) -> dict:
        """Per-name snapshot straight from the slot slabs (one D2H per
        slab, per-name np copies of the table views) — the checkpoint
        layout is the host optimizers', bit for bit, so .ckpt files
        round-trip across PSDT_ARENA on/off unchanged."""
        table = self._arena_table
        out: dict = {}
        for kind, per_stripe in self._arena_slots.items():
            by_name: dict = {}
            for stripe, slab in per_stripe.items():
                host = np.asarray(slab)
                for name in table.stripe_names[stripe]:
                    e = table.entries[name]
                    by_name[name] = np.array(
                        host[e.offset:e.offset + e.length],
                        np.float32).reshape(e.shape)
            out[kind] = by_name
        return out

    def load_state_dict(self, state: dict) -> None:
        import jax.numpy as jnp

        state = dict(state or {})
        with self._lock:
            # restored state supersedes any packed slabs (and their
            # scratch): the next arena close repacks from these tables
            self._arena_slots = {}
            self._arena_table = None
            self._arena_scr = {}
            for slot in self._RULE_SLOTS[self.rule]:
                self._slots[slot] = {
                    name: jnp.asarray(
                        np.ascontiguousarray(arr, np.float32))
                    for name, arr in (state.get(slot) or {}).items()}
        if self.rule in ("adam", "adamw"):
            self.step = int(state.get("step", 0))
            self._bc_step = -1


def _zeros_f32(shape):
    import jax.numpy as jnp

    return jnp.zeros(shape, jnp.float32)
