"""Staleness-aware learning-rate damping — the shared policy (ISSUE 13).

A stale gradient was computed against parameters the store has since
moved past; applying it at full strength drags the trajectory backward
(the classic async-SGD divergence mode).  The standard fix is geometric
damping: a contribution ``s`` iterations stale applies at
``lr * beta ** s`` (beta in (0, 1]).  Implemented here as a gradient
pre-scale — scaling the gradient by ``beta ** s`` before the optimizer
sees it is exactly a per-contribution learning-rate damp for every
linear-in-lr optimizer step, without threading per-contribution scales
through the optimizer protocol.

Two consumers, one policy:

- **K-of-N quorum barriers** (``PSDT_QUORUM``, core/ps_core.py): a
  straggler push landing after the seal folds into the NEXT iteration's
  accumulator damped by its staleness (always on there — quorum is
  opt-in, and an undamped stale fold would weight old gradients equal
  to fresh ones).
- **Bounded-staleness async mode** (``staleness_bound > 0``): an
  accepted stale push applies damped.  OFF unless
  ``PSDT_STALENESS_BETA`` is explicitly set, so pre-existing async runs
  stay byte-identical.

``PSDT_STALENESS_BETA`` overrides the beta for both (default 0.5).
"""

from __future__ import annotations

import os
from typing import Mapping

import numpy as np

ENV_BETA = "PSDT_STALENESS_BETA"
DEFAULT_BETA = 0.5


class StalenessDamping:
    """``scale(s) = beta ** s`` with the shared env override."""

    def __init__(self, beta: float | None = None):
        raw = os.environ.get(ENV_BETA, "")
        if beta is not None:
            self.beta = float(beta)
        elif raw:
            self.beta = float(raw)
        else:
            self.beta = DEFAULT_BETA
        if not 0.0 < self.beta <= 1.0:
            raise ValueError(f"staleness damping beta must be in (0, 1], "
                             f"got {self.beta}")

    def scale(self, staleness: int) -> float:
        """The multiplier for a contribution ``staleness`` iterations
        old.  Fresh (staleness <= 0) contributions pass through at 1."""
        if staleness <= 0:
            return 1.0
        return float(self.beta ** int(staleness))

    def damp(self, gradients: Mapping[str, np.ndarray],
             staleness: int) -> dict[str, np.ndarray]:
        """A damped f32 copy of ``gradients`` (never mutates the input —
        a retried push replays the same payload).  The f32 scalar
        multiply matches the fold path's arithmetic exactly, so a
        staleness-0 damp is bit-identical to no damp."""
        s = self.scale(staleness)
        if s == 1.0:
            return {name: np.asarray(g, np.float32)
                    for name, g in gradients.items()}
        f = np.float32(s)
        return {name: np.asarray(g, np.float32) * f
                for name, g in gradients.items()}


def async_damping() -> StalenessDamping | None:
    """The bounded-staleness async-mode instance: armed ONLY by an
    explicit ``PSDT_STALENESS_BETA`` (pre-existing async runs must stay
    byte-identical without it)."""
    if not os.environ.get(ENV_BETA, ""):
        return None
    return StalenessDamping()
