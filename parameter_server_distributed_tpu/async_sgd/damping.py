"""Staleness-aware learning-rate damping — the shared policy (ISSUE 13).

A stale gradient was computed against parameters the store has since
moved past; applying it at full strength drags the trajectory backward
(the classic async-SGD divergence mode).  The standard fix is geometric
damping: a contribution ``s`` iterations stale applies at
``lr * beta ** s`` (beta in (0, 1]).  Implemented here as a gradient
pre-scale — scaling the gradient by ``beta ** s`` before the optimizer
sees it is exactly a per-contribution learning-rate damp for every
linear-in-lr optimizer step, without threading per-contribution scales
through the optimizer protocol.

Two consumers, one policy:

- **K-of-N quorum barriers** (``PSDT_QUORUM``, core/ps_core.py): a
  straggler push landing after the seal folds into the NEXT iteration's
  accumulator damped by its staleness (always on there — quorum is
  opt-in, and an undamped stale fold would weight old gradients equal
  to fresh ones).
- **Bounded-staleness async mode** (``staleness_bound > 0``): an
  accepted stale push applies damped.  OFF unless
  ``PSDT_STALENESS_BETA`` is explicitly set, so pre-existing async runs
  stay byte-identical.

A third consumer arrived with ISSUE 16: **free-running barrier-free
mode** (``PSDT_FREERUN``, freerun/engine.py) damps every apply-on-
arrival by the same policy — fixed ``beta ** s`` by default, or the
adaptive EWMA-normalized schedule (:mod:`.adaptive`) when explicitly
armed.

``PSDT_STALENESS_BETA`` overrides the beta for all (default 0.5).
``PSDT_DAMP_FLOOR`` (default 0 = off) is the observability floor: a
contribution whose damp scale lands below it is effectively dropped —
silent gradient loss — so crossing it records a ``damp.floor`` flight
event (obs/flight.py) the postmortem can attribute.  Staleness inputs
are clamped defensively into ``[0, MAX_STALENESS]``: callers compute
staleness from iteration counters that can run backward transiently
(restore rewinds, racing bootstrap), and a negative or absurd exponent
must damp sanely rather than AMPLIFY the gradient or overflow.
"""

from __future__ import annotations

import os
from typing import Mapping

import numpy as np

from ..obs import flight

ENV_BETA = "PSDT_STALENESS_BETA"
DEFAULT_BETA = 0.5
ENV_FLOOR = "PSDT_DAMP_FLOOR"
# clamp bound for the damp exponent: far past any plausible real
# staleness, small enough that beta ** MAX_STALENESS stays an exact
# float 0.0 underflow rather than an overflow anywhere
MAX_STALENESS = 1 << 20


def clamp_staleness(staleness) -> int:
    """Defensive staleness clamp into ``[0, MAX_STALENESS]`` (non-int
    inputs truncate like the pre-existing ``int(staleness)``)."""
    return min(max(int(staleness), 0), MAX_STALENESS)


class StalenessDamping:
    """``scale(s) = beta ** s`` with the shared env override."""

    def __init__(self, beta: float | None = None,
                 floor: float | None = None):
        raw = os.environ.get(ENV_BETA, "")
        if beta is not None:
            self.beta = float(beta)
        elif raw:
            self.beta = float(raw)
        else:
            self.beta = DEFAULT_BETA
        if not 0.0 < self.beta <= 1.0:
            raise ValueError(f"staleness damping beta must be in (0, 1], "
                             f"got {self.beta}")
        raw_floor = os.environ.get(ENV_FLOOR, "")
        if floor is not None:
            self.floor = float(floor)
        elif raw_floor:
            self.floor = float(raw_floor)
        else:
            self.floor = 0.0
        if not 0.0 <= self.floor < 1.0:
            raise ValueError(f"damp floor must be in [0, 1), "
                             f"got {self.floor}")

    def floored(self, value: float, *, worker: int = -1,
                iteration: int = -1, staleness: int = 0) -> bool:
        """True when ``value`` fell below the armed floor — the
        contribution is effectively dropped.  Records the ``damp.floor``
        flight event so the loss is observable (the satellite fix: a
        scale of 1e-9 is a silently discarded gradient)."""
        if self.floor <= 0.0 or value >= self.floor:
            return False
        flight.record("damp.floor", iteration=iteration, worker=worker,
                      a=clamp_staleness(staleness),
                      b=int(min(value, 1.0) * 1e9))
        return True

    def scale(self, staleness: int, *, worker: int = -1,
              iteration: int = -1) -> float:
        """The multiplier for a contribution ``staleness`` iterations
        old.  Fresh (staleness <= 0) contributions pass through at 1.
        Staleness is clamped defensively (see :func:`clamp_staleness`);
        a result below the armed floor records ``damp.floor``."""
        s = clamp_staleness(staleness)
        if s <= 0:
            return 1.0
        value = float(self.beta ** s)
        self.floored(value, worker=worker, iteration=iteration,
                     staleness=s)
        return value

    def damp(self, gradients: Mapping[str, np.ndarray],
             staleness: int) -> dict[str, np.ndarray]:
        """A damped f32 copy of ``gradients`` (never mutates the input —
        a retried push replays the same payload).  The f32 scalar
        multiply matches the fold path's arithmetic exactly, so a
        staleness-0 damp is bit-identical to no damp."""
        s = self.scale(staleness)
        if s == 1.0:
            return {name: np.asarray(g, np.float32)
                    for name, g in gradients.items()}
        f = np.float32(s)
        return {name: np.asarray(g, np.float32) * f
                for name, g in gradients.items()}


def async_damping() -> StalenessDamping | None:
    """The bounded-staleness async-mode instance: armed ONLY by an
    explicit ``PSDT_STALENESS_BETA`` (pre-existing async runs must stay
    byte-identical without it)."""
    if not os.environ.get(ENV_BETA, ""):
        return None
    return StalenessDamping()
