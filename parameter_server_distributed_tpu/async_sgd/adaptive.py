"""Adaptive staleness damping schedule (free-running mode, ISSUE 16).

The fixed policy (:mod:`.damping`) damps a contribution ``s``
iterations stale by ``beta ** s`` — calibrated implicitly for fleets
whose typical staleness is ~1.  Under free-running barrier-free
training the TYPICAL staleness is a property of the fleet (worker
count, speed heterogeneity, push cadence), not of the algorithm: on a
16-worker fleet where the *median* push is 8 steps stale, ``beta ** 8``
damps the median contribution to noise and the run crawls; on a
2-worker fleet the same beta is fine.

The adaptive schedule normalizes the exponent by the live staleness
EWMA::

    scale(s) = beta ** (s / max(1, ewma))

so a contribution at the fleet's TYPICAL staleness always damps by
exactly ``beta``, and only unusually-stale contributions (relative to
the fleet's own distribution) damp harder.  The fixed-beta path is the
ORACLE: with the EWMA flat at <= 1 — a fleet whose pushes are at most
one step stale, i.e. the regime the fixed policy was calibrated for —
the schedule is ``beta ** s`` exactly, and the unit tests pin that
equivalence (tests/test_freerun.py).

The EWMA can be SEEDED from measured commit-spread data — the
``pst-trace`` straggler table's per-iteration commit spread (the gap,
in iterations, between the fastest and slowest worker's commits) is
exactly an a-priori estimate of typical staleness — via
``PSDT_FREERUN_SPREAD`` or the constructor, so a restarted run starts
at its fleet's known operating point instead of re-learning it.

Armed ONLY by ``PSDT_FREERUN_ADAPTIVE`` (freerun/__init__.py); the
default free-run damp is the fixed-beta oracle.
"""

from __future__ import annotations

import os

from .damping import DEFAULT_BETA, ENV_BETA, clamp_staleness

# EWMA seed: typical staleness measured offline (pst-trace commit
# spread).  Unset = start at 0.0 (the oracle-equivalent regime) and
# learn from live observations.
ENV_SPREAD = "PSDT_FREERUN_SPREAD"
# EWMA smoothing factor: small enough to ride out bursts, large enough
# to track a real fleet-speed change within ~tens of pushes
ALPHA = 0.05


class AdaptiveDamping:
    """``beta ** (s / max(1, ewma))`` with a live staleness EWMA."""

    def __init__(self, beta: float | None = None,
                 alpha: float = ALPHA,
                 seed: float | None = None):
        raw = os.environ.get(ENV_BETA, "")
        if beta is not None:
            self.beta = float(beta)
        elif raw:
            self.beta = float(raw)
        else:
            self.beta = DEFAULT_BETA
        if not 0.0 < self.beta <= 1.0:
            raise ValueError(f"staleness damping beta must be in (0, 1], "
                             f"got {self.beta}")
        self.alpha = float(alpha)
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError(f"EWMA alpha must be in (0, 1], "
                             f"got {self.alpha}")
        raw_seed = os.environ.get(ENV_SPREAD, "")
        if seed is not None:
            self.ewma = float(seed)
        elif raw_seed:
            self.ewma = float(raw_seed)
        else:
            self.ewma = 0.0
        if self.ewma < 0.0:
            raise ValueError(f"staleness EWMA seed must be >= 0, "
                             f"got {self.ewma}")

    def observe(self, staleness: int) -> None:
        """Fold one observed staleness into the EWMA.  Callers observe
        BEFORE scaling (the contribution's own staleness is evidence of
        the fleet's operating point, whether or not it gets damped)."""
        s = clamp_staleness(staleness)
        self.ewma += self.alpha * (s - self.ewma)

    def scale(self, staleness: int) -> float:
        """The damp multiplier — EWMA-normalized exponent, clamped input
        (:func:`.damping.clamp_staleness`).  Equals the fixed oracle's
        ``beta ** s`` whenever the EWMA is <= 1."""
        s = clamp_staleness(staleness)
        if s <= 0:
            return 1.0
        return float(self.beta ** (s / max(1.0, self.ewma)))

    @property
    def effective_beta(self) -> float:
        """The per-unit-staleness damp factor the schedule currently
        applies — ``scale(1)`` — the ``pst-status --watch`` gauge."""
        return float(self.beta ** (1.0 / max(1.0, self.ewma)))
