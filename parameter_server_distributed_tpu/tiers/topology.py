"""Tier topology: env knobs, deterministic grouping, weight provider.

The grouping itself is a pure function so the coordinator-side state
(:class:`~..core.coordinator_core.CoordinatorCore`) stays a thin
registry: tier-registered workers are keyed by their ``host_id`` and a
host with at least ``min_group_size`` UNGROUPED workers forms a group
whose leader (and leaf aggregator) is the lowest worker id that
published a leaf address.  Formed groups are FROZEN: later same-host
joiners become singletons rather than resizing a live leaf barrier, and
a dissolved group (dead leaf) never re-forms for the same leaf address —
the permanent-downgrade discipline, lifted to topology.

:class:`TierContributionProvider` is the PS side: it polls
``GetReductionTopology`` (pure read) and hands
``ParameterServerCore`` the ``{aggregate_id: (weight, member_ids)}``
map its weighted barrier folds consume.  A reference coordinator answers
UNIMPLEMENTED and the provider latches flat (returns None) forever.
"""

from __future__ import annotations

import logging
import os

import grpc

from ..rpc import messages as m
from ..rpc.service import RpcClient
from . import messages as tmsg

log = logging.getLogger("pst.tiers")

ENV_FLAG = "PSDT_TIERS"
ENV_MIN_GROUP = "PSDT_TIER_MIN_GROUP"
ENV_DTYPE = "PSDT_TIER_DTYPE"
ENV_PUSH_DTYPE = "PSDT_TIER_PUSH_DTYPE"


def tiers_enabled(override: bool | None = None) -> bool:
    """Hierarchical aggregation master switch (default OFF: the flat
    topology is the reference behavior).  ``override`` is the
    WorkerConfig tri-state (None = env decides)."""
    if override is not None:
        return bool(override)
    return os.environ.get(ENV_FLAG, "0").lower() in ("1", "true", "on")


def min_group_size() -> int:
    """Same-host workers below this count stay flat singletons — a
    1-worker "group" would only add a hop."""
    return max(2, int(os.environ.get(ENV_MIN_GROUP, "2")))


def tier_wire_dtype() -> int:
    """Leaf→PS upstream encoding (the quantized contribution).  int8 is
    the default (quarter-size, error-feedback corrected); topk and the
    lossless encodings are accepted for A/B runs."""
    name = os.environ.get(ENV_DTYPE, "int8")
    if name not in m.WIRE_DTYPE_NAMES:
        raise ValueError(f"unknown {ENV_DTYPE} {name!r}; "
                         f"options: {sorted(m.WIRE_DTYPE_NAMES)}")
    return m.WIRE_DTYPE_NAMES[name]


def tier_push_dtype() -> int:
    """Worker→leaf encoding.  f32 by default — the leg is same-host
    (shm rings), so bytes are nearly free and the group fold stays
    exact; a lossy choice engages the worker's own per-tier
    error-feedback stage (tiers/ef.py)."""
    name = os.environ.get(ENV_PUSH_DTYPE, "f32")
    if name not in m.WIRE_DTYPE_NAMES:
        raise ValueError(f"unknown {ENV_PUSH_DTYPE} {name!r}; "
                         f"options: {sorted(m.WIRE_DTYPE_NAMES)}")
    return m.WIRE_DTYPE_NAMES[name]


# ------------------------------------------------------------------ grouping

def form_groups(tier_workers: dict[int, tuple[str, str]],
                existing: list[tmsg.TierGroupEntry],
                dissolved_leaves: set[str],
                min_group: int | None = None
                ) -> tuple[list[tmsg.TierGroupEntry], bool]:
    """(groups, changed).  ``tier_workers``: worker_id -> (host_id,
    leaf_address) for every live tier-registered worker.  ``existing``
    groups survive verbatim while every member is still live and their
    leaf is not dissolved; NEW groups form only from ungrouped workers
    (frozen-membership rule, see module docstring).  Deterministic: for
    a given registry the same groups come out on every call."""
    min_group = min_group_size() if min_group is None else min_group
    groups: list[tmsg.TierGroupEntry] = []
    changed = False
    grouped: set[int] = set()
    for entry in existing:
        members = list(entry.member_ids)
        if (entry.leaf_address not in dissolved_leaves
                and all(wid in tier_workers for wid in members)):
            groups.append(entry)
            grouped.update(members)
        else:
            changed = True  # dissolved or shrunk below its frozen roster
    by_host: dict[str, list[int]] = {}
    for wid in sorted(tier_workers):
        if wid in grouped:
            continue
        host_id, _ = tier_workers[wid]
        if host_id:
            by_host.setdefault(host_id, []).append(wid)
    for host_id in sorted(by_host):
        members = by_host[host_id]
        if len(members) < min_group:
            continue
        # leader = lowest id that pre-bound a leaf server; a host where
        # nobody published a leaf address yet stays ungrouped (the next
        # registration retries)
        leaders = [wid for wid in members
                   if tier_workers[wid][1]
                   and tier_workers[wid][1] not in dissolved_leaves]
        if not leaders:
            continue
        leader = leaders[0]
        groups.append(tmsg.TierGroupEntry(
            host_id=host_id, leader_worker_id=leader,
            aggregate_id=tmsg.aggregate_id_for(leader),
            leaf_address=tier_workers[leader][1],
            member_ids=members))
        changed = True
    return groups, changed


def contribution_map(groups) -> dict[int, tuple[int, tuple[int, ...]]]:
    """Topology groups -> the ``{aggregate_id: (weight, member ids)}``
    map ``ParameterServerCore`` folds group contributions with: the
    weight keeps the PS per-name mean a true mean over WORKERS, and the
    member cover marks every grouped worker a barrier contributor (so a
    member's flat re-push after a mid-iteration downgrade dedups as a
    duplicate instead of double-counting)."""
    return {int(g.aggregate_id): (len(g.member_ids),
                                  tuple(int(wid) for wid in g.member_ids))
            for g in groups}


class TierContributionProvider:
    """PS-side topology poll: callable returning the contribution map
    (None = flat / extension unsupported).  The core TTL-caches the
    result (``contributions_ttl_s``), so this issues at most ~1 RPC/s.
    UNIMPLEMENTED latches flat permanently — a reference coordinator is
    never asked twice."""

    def __init__(self, coordinator_address: str,
                 client: RpcClient | None = None):
        self._client = client or RpcClient(
            coordinator_address, m.COORDINATOR_SERVICE,
            {**m.COORDINATOR_METHODS, **tmsg.TIER_COORD_METHODS})
        self._supported: bool | None = None

    def close(self) -> None:
        self._client.close()

    def __call__(self) -> dict[int, tuple[int, tuple[int, ...]]] | None:
        if self._supported is False:
            return None
        try:
            resp = self._client.call(
                "GetReductionTopology",
                tmsg.TierTopologyRequest(worker_id=-1), timeout=2.0)
        except grpc.RpcError as exc:
            code = getattr(exc, "code", None)
            if callable(code) and code() == grpc.StatusCode.UNIMPLEMENTED:
                log.info("coordinator does not speak GetReductionTopology; "
                         "contribution weights stay flat")
                self._supported = False
                return None
            # transient: keep the core's cached map (it passes None
            # through as "no update"; the TTL retries)
            return None
        self._supported = True
        if not resp.enabled:
            return {}
        return contribution_map(resp.groups)
