"""Worker-side tier runtime: registration, election, leaf rounds,
permanent downgrade (ISSUE 9).

One :class:`TierClient` per worker.  At construction it pre-binds a
:class:`~.leaf.LeafAggregator` server (unarmed — see tiers/leaf.py), so
the very first ``GetReductionTopology`` registration already carries the
leaf address this worker would serve if elected; the coordinator can
then form a group in ONE round.  :meth:`maybe_activate` is called at
each iteration start: while ungrouped it re-registers on a rate-limited
cadence (workers join at different times); once the coordinator assigns
a group it arms the own leaf (leader) or connects to the leader's
(member) and the worker's fused rounds ride the tier.

Downgrade discipline (PR-2, lifted to the topology):

- UNIMPLEMENTED from the coordinator (reference peer) → permanent flat,
  never asked again.
- a transport error on the leaf connection (leaf death) → report
  ``dead_leaf`` to the coordinator (the group dissolves, epoch bump, so
  the PS's contribution weights stop covering it) and permanent flat.
- a *soft* miss — the leaf answering ``tier leaf not armed`` (election
  race) or a leaf barrier timeout (a member of this group pushed flat
  for this iteration, e.g. during formation) — pushes flat for THIS
  round only and retries the tier next round; ``_SOFT_FAILURE_LIMIT``
  consecutive misses harden into the permanent downgrade.

Zero failed steps either way: a flat re-push after the group's upstream
contribution landed dedups against the PS's member cover, and one that
never went upstream folds normally.
"""

from __future__ import annotations

import logging
import time

import grpc

from ..analysis.lock_order import checked_lock
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc import shm_transport
from ..rpc.data_plane import PSClient
from ..rpc.service import RpcClient
from . import messages as tmsg
from . import topology
from .ef import ErrorFeedback
from .leaf import LEAF_NOT_ARMED, LEAF_RETRY_FLAT, LeafAggregator

log = logging.getLogger("pst.tiers")

# consecutive soft misses (not-armed / leaf barrier timeout) before the
# tier hardens into the permanent flat downgrade
_SOFT_FAILURE_LIMIT = 3


class TierClient:
    """One worker's view of the reduction topology (see module doc)."""

    def __init__(self, coordinator_address: str, worker_id: int,
                 ps_address: str, *, host_id: str | None = None,
                 init_params_fn=None,
                 topk_density: float = m.TOPK_DEFAULT_DENSITY,
                 poll_s: float = 0.5, enabled: bool | None = None):
        self.worker_id = int(worker_id)
        self.host_id = host_id or shm_transport.host_id()
        self._init_params_fn = init_params_fn
        self._poll_s = float(poll_s)
        self._coord = RpcClient(
            coordinator_address, m.COORDINATOR_SERVICE,
            {**m.COORDINATOR_METHODS, **tmsg.TIER_COORD_METHODS})
        # worker→leaf encoding + its OWN error-feedback stage (tier 1 of
        # the per-tier EF; engaged only when the leg is lossy)
        self.push_dtype = topology.tier_push_dtype()
        self.push_ef = ErrorFeedback()
        self.topk_density = float(topk_density)
        # guards the state machine + connection swaps (never held across
        # an RPC); ranked in analysis/lock_order.py
        self._lock = checked_lock("TierClient._lock")
        self._state = "pending" if topology.tiers_enabled(enabled) \
            else "flat"
        self._next_poll = 0.0
        self._soft_failures = 0
        self._group: tmsg.TierGroupEntry | None = None
        self._client: PSClient | None = None
        # pre-bound, unarmed until elected (tiers/leaf.py lifecycle)
        self._leaf: LeafAggregator | None = None
        if self._state == "pending":
            try:
                self._leaf = LeafAggregator(
                    self.worker_id, ps_address,
                    topk_density=self.topk_density)
            except Exception:  # noqa: BLE001 — leafless workers still tier
                log.warning("worker %d: could not pre-bind a leaf "
                            "aggregator; this worker cannot lead",
                            self.worker_id, exc_info=True)
        self._obs_downgrades = obs_stats.counter("tier.downgrades")
        self._obs_rounds = obs_stats.counter("tier.rounds")

    # ------------------------------------------------------------- properties
    @property
    def active(self) -> bool:
        return self._state == "active"

    @property
    def client(self) -> PSClient | None:
        return self._client

    @property
    def group(self) -> tmsg.TierGroupEntry | None:
        return self._group

    # ------------------------------------------------------------- activation
    def maybe_activate(self) -> bool:
        """True when the worker's fused round should ride the tier.
        While ungrouped, re-registers with the coordinator at most every
        ``poll_s`` seconds."""
        with self._lock:
            if self._state != "pending":
                return self._state == "active"
            if time.monotonic() < self._next_poll:
                return False
            self._next_poll = time.monotonic() + self._poll_s
            leaf_address = self._leaf.address if self._leaf else ""
        try:
            resp = self._coord.call(
                "GetReductionTopology",
                tmsg.TierTopologyRequest(worker_id=self.worker_id,
                                         host_id=self.host_id,
                                         leaf_address=leaf_address),
                timeout=2.0)
        except grpc.RpcError as exc:
            code = getattr(exc, "code", None)
            if callable(code) and code() == grpc.StatusCode.UNIMPLEMENTED:
                log.info("worker %d: coordinator has no reduction "
                         "topology; staying flat", self.worker_id)
                self._go_flat("coordinator UNIMPLEMENTED")
            return False
        if not resp.enabled:
            self._go_flat("tiers disabled at the coordinator")
            return False
        if resp.latched_flat:
            # this worker's former group dissolved (it, or a peer,
            # downgraded): the coordinator will never group it again —
            # stop polling and release the idle leaf server
            self._go_flat("latched permanently flat at the coordinator")
            return False
        mine = next((g for g in resp.groups
                     if self.worker_id in g.member_ids), None)
        if mine is None:
            return False  # ungrouped (yet): poll again later
        return self._adopt_group(mine)

    def _adopt_group(self, group: tmsg.TierGroupEntry) -> bool:
        lead = int(group.leader_worker_id) == self.worker_id
        if lead:
            if self._leaf is None:
                # we were elected but could not bind a leaf: dissolve
                self.downgrade("elected leader has no leaf server")
                return False
            init = {}
            if self._init_params_fn is not None:
                try:
                    init = self._init_params_fn()
                except Exception:  # noqa: BLE001 — seed store is optional
                    log.warning("worker %d: leaf seed store unavailable",
                                self.worker_id, exc_info=True)
            self._leaf.arm(len(group.member_ids), int(group.aggregate_id),
                           init)
        client = PSClient(group.leaf_address)
        with self._lock:
            self._group = group
            self._client = client
            self._state = "active"
        if not lead:
            self._shutdown_own_leaf()  # not elected: free the idle server
            flight.record("tier.elect", worker=self.worker_id,
                          a=len(group.member_ids),
                          b=int(group.aggregate_id),
                          note=f"member of {group.leaf_address}")
        log.info("worker %d: tier active — group of %d via leaf %s (%s)",
                 self.worker_id, len(group.member_ids), group.leaf_address,
                 "leading" if lead else "member")
        return True

    # -------------------------------------------------------------- downgrade
    def note_success(self) -> None:
        self._soft_failures = 0
        self._obs_rounds.add()

    def soft_failure(self, reason: str) -> bool:
        """A recoverable miss: push flat THIS round, keep the tier.
        Returns False (and hard-downgrades) once the misses look
        permanent."""
        self._soft_failures += 1
        if self._soft_failures >= _SOFT_FAILURE_LIMIT:
            self.downgrade(f"{reason} ({self._soft_failures} consecutive)")
            return False
        log.info("worker %d: tier round missed (%s); flat for this round",
                 self.worker_id, reason)
        return True

    @staticmethod
    def is_soft_refusal(message: str) -> bool:
        text = message or ""
        return LEAF_NOT_ARMED in text or LEAF_RETRY_FLAT in text

    def downgrade(self, reason: str) -> None:
        """Permanent flat downgrade; reports the dead leaf so the
        coordinator dissolves the group (the PS's contribution weights
        stop covering it)."""
        with self._lock:
            if self._state == "flat":
                return
            self._state = "flat"
            group, self._group = self._group, None
            client, self._client = self._client, None
        self._obs_downgrades.add()
        flight.record("tier.downgrade", worker=self.worker_id,
                      note=reason[:48])
        log.warning("worker %d: tier downgraded to flat topology: %s",
                    self.worker_id, reason)
        if client is not None:
            client.close()
        self._shutdown_own_leaf()
        if group is not None and group.leaf_address:
            try:
                self._coord.call(
                    "GetReductionTopology",
                    tmsg.TierTopologyRequest(worker_id=self.worker_id,
                                             host_id=self.host_id,
                                             dead_leaf=group.leaf_address),
                    timeout=2.0)
            except grpc.RpcError:
                # best-effort: the PS weight map self-corrects once any
                # member's report lands or the registry reaps the group
                log.warning("worker %d: dead-leaf report failed",
                            self.worker_id)

    def _go_flat(self, reason: str) -> None:
        with self._lock:
            if self._state == "flat":
                return
            self._state = "flat"
        self._shutdown_own_leaf()
        log.info("worker %d: tier inactive (%s)", self.worker_id, reason)

    def _shutdown_own_leaf(self) -> None:
        leaf, self._leaf = self._leaf, None
        if leaf is not None:
            leaf.stop()

    def close(self) -> None:
        with self._lock:
            client, self._client = self._client, None
            self._state = "flat"
        if client is not None:
            client.close()
        self._shutdown_own_leaf()
        self._coord.close()
