"""Per-tier error feedback: the generalized lossy-compression residual.

PR 5 gave the worker an ``_ef_residual`` dict for its int8/topk pushes:
send ``compress(grad + residual)``, carry the un-sent part into the next
push, so quantization bias cancels over time (1-bit-SGD / EF-SGD /
Deep-Gradient-Compression).  The two-tier reduction tree (ISSUE 9) has
TWO compression points — worker→leaf (if lossy) and leaf→PS — and each
must carry its OWN residual: a shared one would mix errors measured
against different reference signals and re-introduce bias.  This class
is that stage, one instance per compression point; the worker's PS-leg
residual and ``_compress_with_feedback`` are now thin wrappers over it
(worker/worker.py), and the leaf aggregator holds one for its upstream
quantized contribution (tiers/leaf.py).

Commit discipline (unchanged from PR 5): the staged residual of a push
is committed only after the receiver ACCEPTS it — a rejected push's
payload was discarded whole, so its quantization error must not leak
into the next push — and a retry replays the same adjusted payload
against the same committed residual, which is what lets the receiver's
per-(worker, tensor) dedup absorb the replay.
"""

from __future__ import annotations

import os
from typing import Mapping

import numpy as np

from ..rpc import messages as m

ENV_FLAG = "PSDT_ERROR_FEEDBACK"


def error_feedback_enabled() -> bool:
    """PSDT_ERROR_FEEDBACK gates every residual carry (default ON: lossy
    wire dtypes without it accumulate quantization bias push over push).
    ``0`` disables — the A/B knob the convergence tests compare."""
    return os.environ.get(ENV_FLAG, "1") not in ("0", "off")


class ErrorFeedback:
    """One compression point's residual stage.

    ``residual`` is the COMMITTED carry (what the receiver has provably
    not seen); ``begin``/``adjust``/``stage`` build the next push's
    pending carry, and ``commit`` promotes it once the push is accepted.
    Not thread-safe by itself — each instance belongs to one serialized
    push path (the worker's step loop, the leaf's relay under its core's
    ``_apply_lock``)."""

    __slots__ = ("residual", "_pending", "enabled")

    def __init__(self, enabled: bool | None = None):
        self.residual: dict[str, np.ndarray] = {}
        self._pending: dict[str, np.ndarray] = {}
        # None = follow the env gate per call (the worker's behavior)
        self.enabled = enabled

    def _on(self) -> bool:
        return error_feedback_enabled() if self.enabled is None \
            else self.enabled

    def on(self) -> bool:
        """Whether the carry is live (instance override or env gate)."""
        return self._on()

    def pending(self) -> dict[str, np.ndarray]:
        """The staged (uncommitted) carry of the push being built — what
        :meth:`commit` would promote.  The worker's two-phase push path
        reads it to commit by assignment after the PS ack."""
        return dict(self._pending)

    def __contains__(self, name: str) -> bool:
        """``name in stage`` — was a residual staged for this tensor in
        the push being built (the residual-box contract callers held
        before the stage object replaced the raw dict)."""
        return name in self._pending

    # -------------------------------------------------------- lazy per-tensor
    def begin(self) -> None:
        """Start (or restart — a retry replays from scratch) one push's
        pending residual."""
        self._pending = {}

    def adjust(self, name: str, grad: np.ndarray) -> np.ndarray:
        """``grad + committed residual`` — what gets compressed."""
        if not self._on():
            return grad
        prev = self.residual.get(name)
        return grad + prev if prev is not None else grad

    def stage(self, name: str, adjusted: np.ndarray, tensor) -> None:
        """Record what the receiver did NOT see: decoding the wire tensor
        gives exactly the receiver's view, so ``adjusted - decode`` is
        the carry."""
        if self._on():
            self._pending[name] = adjusted - tensor.to_array()

    def commit(self) -> None:
        """The push was accepted: the pending carry becomes the committed
        residual (wholesale — names absent from this push drop their
        stale carry, matching the PR-5 worker semantics)."""
        self.residual = dict(self._pending)

    # ----------------------------------------------------------- whole-store
    def compress(self, store: Mapping[str, np.ndarray], wire_dtype: int,
                 topk_density: float = m.TOPK_DEFAULT_DENSITY) -> list:
        """One-shot store compression with the residual carry staged (NOT
        committed — call :meth:`commit` after the receiver accepts).
        Returns the wire tensors.  With feedback disabled (or a lossless
        ``wire_dtype``) this is a plain ``to_wire`` and commit clears the
        carry."""
        from ..core.tensor import to_wire

        self.begin()
        lossy = wire_dtype in (m.WIRE_INT8, m.WIRE_TOPK)
        if not lossy or not self._on():
            return to_wire(store, wire_dtype, topk_density=topk_density)
        adjusted = {name: self.adjust(name, np.asarray(g, np.float32))
                    for name, g in store.items()}
        tensors = to_wire(adjusted, wire_dtype, topk_density=topk_density)
        for t in tensors:
            self.stage(t.name, adjusted[t.name], t)
        return tensors
