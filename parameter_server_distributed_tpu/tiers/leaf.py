"""Leaf aggregator: the intra-host tier of the reduction tree (ISSUE 9).

The elected leader worker of a same-host group runs one of these: a
loopback gRPC server speaking the SAME fused data plane as a parameter
server (``PushPullStream`` + ``NegotiateShm``, so member legs ride the
PR-6 shm rings), backed by a :class:`~..core.ps_core.ParameterServerCore`
whose streaming ``PushSink``/``begin_push`` machinery folds member
pushes on arrival — maximum reuse, zero new aggregation semantics.  The
one divergence is the barrier close: instead of scale + optimizer apply,
the core's **barrier relay** hands the raw per-name SUMS to
:meth:`LeafAggregator._relay`, which sends them upstream as ONE
int8/topk-quantized contribution (error-feedback corrected — its own
:class:`~.ef.ErrorFeedback` stage) pushed under the group's synthetic
``aggregate_id``.  The PS folds it with weight = group size (the mean
over workers is unchanged) and covers every member id on its barrier;
the fused response's fresh parameters become this core's store, so the
parked member handlers fan them back through the ordinary serve path
(encode-once cache included).

Lifecycle: the server BINDS at construction (so the leaf address rides
the worker's very first topology registration — election needs no
publish round) but stays UNARMED until the coordinator elects this
worker: an unarmed leaf answers pushes with a distinct retryable
refusal, because its placeholder barrier width would otherwise close on
the first member.  ``arm()`` installs the real group size, the synthetic
aggregate id, and an initial store (any store — it is replaced by the
first relay; it only exists so the fused plane's empty-store refusal
does not fire).

Failure discipline: a relay failure raises
:class:`TierUpstreamError`, which takes the core's ordinary failed-apply
path — accumulator put back, barrier retryable, and the retry's upstream
re-push is idempotent (PS per-(worker, tensor) dedup + member cover).
Members that give up instead re-push flat with their own ids; the cover
dedups them, so the two recovery paths can never double-count.
"""

from __future__ import annotations

import logging
import os
import time

import grpc

from ..core.ps_core import ParameterServerCore
from ..core.tensor import TensorStore, from_wire
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc import shm_transport
from ..rpc.data_plane import PSClient
from ..rpc.service import make_server, bind_service
from ..server.ps_service import ParameterServerService
from . import topology
from .ef import ErrorFeedback

log = logging.getLogger("pst.tiers")

# Message marker of the unarmed-leaf refusal: the member treats it as
# "push flat this round, retry the tier next round" — NOT a downgrade
# (the election may be one poll away from completing on the leader).
LEAF_NOT_ARMED = "tier leaf not armed"
# Same soft semantics when the leaf's UPSTREAM contribution failed (PS
# unreachable, or the PS rejected an overlapping group sum after a
# member's downgrade recovery): the member replays flat this round and
# keeps the tier — the leaf is alive, its upstream hiccuped.
LEAF_RETRY_FLAT = "tier leaf upstream failed"


class TierUpstreamError(RuntimeError):
    """The leaf's upstream contribution failed; the leaf barrier stays
    retryable (core failed-apply semantics)."""


def leaf_barrier_timeout_s() -> float:
    """Member park cap at the leaf.  Much shorter than the PS's 60 s
    default: the common stall is a formation race (one member still
    pushing flat for this iteration), and the member's recovery — flat
    re-push, cover-dedup'd — is cheap."""
    return float(os.environ.get("PSDT_TIER_BARRIER_TIMEOUT_S", "20"))


class LeafService(ParameterServerService):
    """The PS service surface re-hosted on a leaf core: same fused data
    plane, same shm negotiation; checkpointing is refused (a leaf holds
    no durable state) and pushes before :meth:`LeafAggregator.arm` are
    refused retryably."""

    def __init__(self, core: ParameterServerCore, leaf: "LeafAggregator"):
        super().__init__(core, ckpt=None)
        self._leaf = leaf

    @staticmethod
    def _fused_barrier_timeout_s() -> float:
        return leaf_barrier_timeout_s()

    def _not_armed(self) -> m.PushResponse:
        return m.PushResponse(
            success=False,
            message=f"{LEAF_NOT_ARMED} (election pending; push flat and "
                    f"retry the tier next round)",
            iteration=self.core.current_iteration)

    def PushPullStream(self, request_iterator, context):
        if not self._leaf.armed:
            yield m.PushPullResponse(push=self._not_armed())
            return

        def tap():
            noted = False
            for chunk in request_iterator:
                if not noted:
                    noted = True
                    # the member-edge evidence pst-trace orders group
                    # folds by (sampled class, like fold.reserve)
                    flight.record("tier.fold", iteration=chunk.iteration,
                                  worker=chunk.worker_id,
                                  a=len(chunk.gradients),
                                  b=self._leaf.aggregate_id)
                yield chunk

        try:
            yield from super().PushPullStream(tap(), context)
        except TierUpstreamError as exc:
            # the relay failed on THIS member's thread (it triggered the
            # close, or its barrier wait retried it): answer a SOFT
            # refusal instead of aborting the stream — the member
            # replays flat this round and keeps the tier.  If the push
            # verdict already went out, this extra frame is ignored by
            # the client's first-push-wins assembly and the member sees
            # a barrier miss — the same soft path.
            yield m.PushPullResponse(push=m.PushResponse(
                success=False, message=f"{LEAF_RETRY_FLAT}: {exc}",
                iteration=self.core.current_iteration))

    def PushGradientsStream(self, request_iterator, context):
        if not self._leaf.armed:
            return self._not_armed()
        return super().PushGradientsStream(request_iterator, context)

    def ReceiveGradients(self, request, context):
        if not self._leaf.armed:
            return self._not_armed()
        return super().ReceiveGradients(request, context)

    # a leaf holds no durable state: checkpoint RPCs are refused
    def SaveCheckpoint(self, request, context):
        return m.SaveCheckpointResponse(
            success=False, message="leaf aggregator holds no checkpoints")

    def LoadCheckpoint(self, request, context):
        return m.LoadCheckpointResponse(
            success=False, message="leaf aggregator holds no checkpoints")


class LeafAggregator:
    """One group's intra-host aggregator, hosted by the leader worker."""

    def __init__(self, worker_id: int, upstream_address: str,
                 bind_address: str = "127.0.0.1",
                 wire_dtype: int | None = None,
                 topk_density: float = m.TOPK_DEFAULT_DENSITY,
                 upstream_timeout_s: float = 120.0,
                 upstream: PSClient | None = None):
        self.worker_id = int(worker_id)
        self.aggregate_id = -1
        self.group_size = 0
        self.armed = False
        self._wire_dtype = (topology.tier_wire_dtype() if wire_dtype is None
                            else wire_dtype)
        self._topk_density = float(topk_density)
        self._upstream_timeout_s = float(upstream_timeout_s)
        # the leaf's OWN error-feedback stage (tier 2 of the per-tier EF;
        # serialized by the core's _apply_lock around the relay)
        self._ef = ErrorFeedback()
        self._upstream = upstream or PSClient(upstream_address)
        # stripes=1: groups are a handful of members and the "apply" is a
        # network relay — the striped executor buys nothing at this tier
        self.core = ParameterServerCore(total_workers=1, stripes=1)
        self.core.set_barrier_relay(self._relay)
        self.service = LeafService(self.core, self)
        self._obs_upstream_bytes = obs_stats.counter("tier.upstream_bytes")
        self._obs_relays = obs_stats.counter("tier.relays")
        self._obs_upstream_s = obs_stats.histogram("tier.upstream_s")
        self._obs_group = obs_stats.gauge("tier.group_size")
        self._server = make_server(max_workers=8)
        bind_service(self._server, m.PARAMETER_SERVER_SERVICE,
                     {**m.PARAMETER_SERVER_METHODS,
                      **m.PARAMETER_SERVER_STREAM_METHODS,
                      **shm_transport.SHM_METHODS}, self.service)
        self._port = self._server.add_insecure_port(f"{bind_address}:0")
        if self._port == 0:
            raise RuntimeError(f"leaf aggregator could not bind on "
                               f"{bind_address}")
        self.address = f"{bind_address}:{self._port}"
        self._server.start()

    def arm(self, group_size: int, aggregate_id: int,
            init_params: TensorStore) -> None:
        """Election landed: install the real barrier width, the synthetic
        upstream pusher id, and a seed store (replaced by the first
        relay; it only keeps the fused plane's empty-store refusal from
        firing on the first member push)."""
        self.group_size = int(group_size)
        self.aggregate_id = int(aggregate_id)
        self.core.set_total_workers(self.group_size)
        if init_params and not self.core.has_parameters:
            self.core.initialize_parameters(init_params)
        self._obs_group.set(self.group_size)
        self.armed = True
        flight.record("tier.elect", worker=self.worker_id,
                      a=self.group_size, b=self.aggregate_id,
                      note=self.address)
        log.info("leaf aggregator armed at %s: group of %d, aggregate id "
                 "%d, upstream dtype %s", self.address, self.group_size,
                 self.aggregate_id,
                 {v: k for k, v in m.WIRE_DTYPE_NAMES.items()}.get(
                     self._wire_dtype, self._wire_dtype))

    # ------------------------------------------------------------------ relay
    def _relay(self, iteration: int, sums: TensorStore,
               counts: dict[str, int]) -> TensorStore:
        """The leaf core's barrier close: quantize the group sums (EF
        adjusted), push them upstream as the group's ONE contribution,
        and return the fused response's fresh parameters as the leaf's
        new store.  Runs under the leaf core's _apply_lock
        (BLOCKING_ALLOWED — the same discipline as sync replication)."""
        sealed = max(counts.values(), default=0)
        flight.record("tier.seal", iteration=iteration,
                      worker=self.aggregate_id, a=sealed, b=self.group_size)
        tensors = self._ef.compress(sums, self._wire_dtype,
                                    topk_density=self._topk_density)
        wire_bytes = sum(t.encoded_size() for t in tensors)
        fresh: TensorStore = {}

        def on_chunk(chunk_tensors) -> None:
            fresh.update(from_wire(chunk_tensors))

        # lossless tree (f32/raw upstream) pulls lossless too, so the
        # two-tier arithmetic is the flat topology's exactly (the chaos
        # acceptance compares loss curves); a quantized tree pulls bf16
        # like any lossy-push worker (re-compressing PARAMS every round
        # would compound irrecoverable error — see worker._pull_wire_dtype)
        pull_dtype = (m.WIRE_RAW_F32
                      if self._wire_dtype in (m.WIRE_F32, m.WIRE_RAW_F32)
                      else m.WIRE_BF16)
        t0 = time.perf_counter()
        try:
            push, params = self._upstream.push_pull(
                self.aggregate_id, iteration, lambda: iter(tensors),
                pull_wire_dtype=pull_dtype,
                timeout=self._upstream_timeout_s, on_chunk=on_chunk)
        except grpc.RpcError as exc:
            raise TierUpstreamError(
                f"upstream push failed: {exc}") from exc
        if not push.success:
            raise TierUpstreamError(
                f"upstream push rejected: {push.message}")
        if params is None or not fresh:
            # the PS barrier did not close inside the window (or the
            # server degraded the fused round): the leaf has nothing to
            # serve its parked group — retry the close, idempotently
            raise TierUpstreamError("upstream round delivered no "
                                    "parameters (PS barrier timeout?)")
        self._ef.commit()
        dt = time.perf_counter() - t0
        self._obs_upstream_bytes.add(wire_bytes)
        self._obs_relays.add()
        self._obs_upstream_s.observe(dt)
        flight.record("tier.upstream", iteration=iteration,
                      worker=self.aggregate_id, a=int(1e6 * dt),
                      b=wire_bytes)
        return fresh

    # -------------------------------------------------------------- lifecycle
    def stop(self, grace: float = 0.5) -> None:
        self.armed = False
        self.service.shm_server.close()
        self._server.stop(grace)
        self._upstream.close()
