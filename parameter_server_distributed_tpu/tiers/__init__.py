"""Hierarchical quantized aggregation (ISSUE 9): two-tier reduction tree.

Flat parameter-server topology pushes every worker's full-rate f32
gradients point-to-point at the PS, so PS ingress bytes and barrier-close
latency grow linearly with worker count — the bilinear bottleneck of the
paper's topology.  This package adds a coordinator-assigned TWO-TIER
reduction tree exploiting the same-host bandwidth gap (arXiv:1810.11112)
with per-compression-point error feedback (EQuARX, arXiv:2506.17615):

- the coordinator groups tier-registered workers by same-host identity
  (the ``hostname/boot-id`` ``host_id`` of rpc/shm_transport.py) and
  elects one **leaf aggregator** per group (:mod:`tiers.topology`,
  served via the ``GetReductionTopology`` coordinator extension RPC —
  messages in :mod:`tiers.messages`, OUTSIDE ``rpc/messages.py``, so the
  reference wire manifest stays byte-unchanged);
- group members push their gradients to the leaf over the existing fused
  ``PushPullStream`` (same-host legs ride the PR-6 shm rings); the leaf
  (:mod:`tiers.leaf`) reuses the streaming ``PushSink``/``begin_push``
  fold machinery of ``core/ps_core.py`` to fold-on-arrival, and once its
  group seals sends ONE quantized (int8/topk) upstream contribution whose
  barrier weight is the group size — the PS mean over workers is
  unchanged — then fans the fused parameter response back to its group;
- both compression points (worker→leaf, if lossy, and leaf→PS) carry
  their own error-feedback residual (:mod:`tiers.ef`, the generalization
  of the PR-5 worker-side ``_ef_residual``), keeping convergence at
  flat-f32 quality;
- every leg downgrades PR-2-style: UNIMPLEMENTED / refusal / leaf death
  permanently drops the connection back to the flat topology with zero
  failed steps (:mod:`tiers.group_client`).

Env knobs: ``PSDT_TIERS`` (default off), ``PSDT_TIER_MIN_GROUP`` (group
size threshold, default 2), ``PSDT_TIER_DTYPE`` (leaf→PS quantization,
default int8), ``PSDT_TIER_PUSH_DTYPE`` (worker→leaf encoding, default
f32).  See docs/training.md "Hierarchical aggregation".
"""

from .ef import ErrorFeedback  # noqa: F401 — public
from .messages import TIER_AGGREGATE_ID_BASE, TIER_COORD_METHODS  # noqa: F401
from .topology import (min_group_size, tier_push_dtype,  # noqa: F401
                       tier_wire_dtype, tiers_enabled)
