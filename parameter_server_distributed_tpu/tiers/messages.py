"""Reduction-topology extension RPC messages (ISSUE 9).

Deliberately NOT in ``rpc/messages.py``: the analyzer's wire manifest
pins the reference contract (field tags, method tables) and the tier
subsystem must leave it byte-unchanged.  ``GetReductionTopology`` is an
extra method name on the existing coordinator gRPC service — a reference
coordinator never implements it and answers UNIMPLEMENTED, which the
worker-side :class:`~.group_client.TierClient` treats as a PERMANENT
downgrade to the flat topology (the PR-2/PR-6/PR-7 fallback discipline).

One RPC serves three roles, so group formation needs no extra round
trips:

- **tier registration** — a worker reports its ``host_id`` (the
  hostname+boot-id identity of rpc/shm_transport.py) and the address of
  the leaf-aggregator server it pre-bound, so the coordinator can elect
  it without a publish round;
- **topology query** — the response carries the current epoch-numbered
  group list (the PS's contribution-weight provider polls it with
  ``worker_id = -1`` and an empty ``host_id``, which registers nothing);
- **downgrade report** — ``dead_leaf`` names a leaf address the caller
  observed dead; the coordinator dissolves that group (epoch bump) so
  the PS's contribution weights stop covering it.
"""

from __future__ import annotations

# The synthetic pusher-id namespace is OWNED by the weighted barrier
# (core/ps_core.py — an unknown id at/above it is rejected retryably
# there); re-exported here as the tier protocol constant.  A group's ONE
# upstream contribution pushes as ``TIER_AGGREGATE_ID_BASE + leader
# id``, so the PS can tell a group push (weight = group size, covering
# every member id) from the leader's own flat push (weight 1) without
# any wire change.  Real worker ids must stay below the base (documented
# in docs/training.md); obs/postmortem.py mirrors the value to label
# group lanes without importing this package.
from ..core.ps_core import TIER_AGGREGATE_ID_BASE  # noqa: F401 — re-export
from ..rpc.messages import TRACE_FIELD_NUMBER
from ..rpc.wire import Field, Message


def aggregate_id_for(leader_worker_id: int) -> int:
    return TIER_AGGREGATE_ID_BASE + int(leader_worker_id)


class TierGroupEntry(Message):
    """One same-host reduction group of the epoch-numbered topology."""
    FIELDS = (
        Field(1, "host_id", "string"),
        Field(2, "leader_worker_id", "int32"),
        Field(3, "aggregate_id", "int32"),
        Field(4, "leaf_address", "string"),
        Field(5, "member_ids", "int32", repeated=True),
    )


class TierTopologyRequest(Message):
    """Register-and-query (see module docstring).  ``worker_id = -1``
    with an empty ``host_id`` is a pure read (the PS weight provider)."""
    FIELDS = (
        Field(1, "worker_id", "int32"),
        Field(2, "host_id", "string"),
        Field(3, "leaf_address", "string"),
        Field(4, "dead_leaf", "string"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class TierTopologyResponse(Message):
    """``latched_flat`` answers the REQUESTING worker: its id is in the
    coordinator's permanently-flat set (its former group dissolved), so
    the client must stop polling and release its idle leaf server —
    without it a rebuilt TierClient would poll at 2 Hz forever."""
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "enabled", "bool"),
        Field(3, "min_group_size", "int32"),
        Field(4, "groups", "message", message_type=TierGroupEntry,
              repeated=True),
        Field(5, "latched_flat", "bool"),
    )


TIER_COORD_METHODS = {
    "GetReductionTopology": (TierTopologyRequest, TierTopologyResponse),
}
