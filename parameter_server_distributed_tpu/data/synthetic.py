"""Synthetic in-memory datasets.

The reference has no data pipeline (SURVEY.md §1: "no data pipeline").
The framework's loaders are synthetic-by-default (this image has no
network egress for dataset downloads) but deterministic and structured:
class-conditional clusters so that training measurably reduces loss —
enough signal for convergence tests and benchmarks.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def xy_batch_stream(x: np.ndarray, y: np.ndarray, batch_size: int,
                    seed: int = 0, drop_remainder: bool = True
                    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Endless shuffled (x, y) batches, re-shuffled each epoch.  Epoch rngs
    seed from the (seed, epoch) sequence so worker-id-derived seeds never
    replay a neighbor's epoch order.  Shared by the synthetic datasets and
    the file-backed npz loader (data/files.py)."""
    epoch = 0
    while True:
        rng = np.random.default_rng([seed, epoch])
        order = rng.permutation(len(y))
        end = (len(order) // batch_size) * batch_size if drop_remainder \
            else len(order)
        for start in range(0, end, batch_size):
            idx = order[start:start + batch_size]
            yield x[idx], y[idx]
        epoch += 1


class ClassClusterDataset:
    """Gaussian class-cluster classification data (MNIST/CIFAR stand-in)."""

    def __init__(self, num_features: int, num_classes: int,
                 num_examples: int = 4096, seed: int = 0, scale: float = 2.0):
        rng = np.random.default_rng(seed)
        self.num_features = num_features
        self.num_classes = num_classes
        self.centers = rng.standard_normal((num_classes, num_features)).astype(np.float32)
        labels = rng.integers(0, num_classes, size=num_examples)
        noise = rng.standard_normal((num_examples, num_features)).astype(np.float32)
        self.x = (scale * self.centers[labels] + noise).astype(np.float32)
        self.y = labels.astype(np.int32)

    def __len__(self) -> int:
        return len(self.y)

    def batches(self, batch_size: int, seed: int = 0,
                drop_remainder: bool = True) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """One epoch of shuffled batches."""
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(self.y))
        end = (len(order) // batch_size) * batch_size if drop_remainder else len(order)
        for start in range(0, end, batch_size):
            idx = order[start:start + batch_size]
            yield self.x[idx], self.y[idx]

    def batch_stream(self, batch_size: int, seed: int = 0
                     ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Endless stream of batches (re-shuffles each epoch)."""
        return xy_batch_stream(self.x, self.y, batch_size, seed=seed)


def synthetic_mnist(num_examples: int = 4096, seed: int = 0) -> ClassClusterDataset:
    return ClassClusterDataset(784, 10, num_examples, seed)


def synthetic_cifar10(num_examples: int = 4096, seed: int = 0) -> ClassClusterDataset:
    """Flat 32*32*3 features; image models reshape to NHWC."""
    return ClassClusterDataset(32 * 32 * 3, 10, num_examples, seed)


def synthetic_image_batches(batch_size: int, image_size: int = 32,
                            channels: int = 3, num_classes: int = 10,
                            seed: int = 0, dataset_seed: int = 0
                            ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Endless NHWC image batches for conv models.

    ``seed`` varies the SAMPLING order only; the dataset itself (cluster
    centers = the classification task) comes from ``dataset_seed``, so
    differently-seeded streams (per worker, per host, eval) draw from the
    same task — like differently-shuffled loaders over one fixed MNIST."""
    ds = ClassClusterDataset(image_size * image_size * channels, num_classes,
                             num_examples=64 * batch_size if batch_size < 64 else 4096,
                             seed=dataset_seed)
    for x, y in ds.batch_stream(batch_size, seed=seed):
        yield x.reshape(-1, image_size, image_size, channels), y


def synthetic_tokens(batch_size: int, seq_len: int, vocab: int = 32000,
                     seed: int = 0) -> Iterator[np.ndarray]:
    """Endless [batch, seq_len] int32 token batches for LM training."""
    rng = np.random.default_rng(seed)
    while True:
        yield rng.integers(0, vocab, size=(batch_size, seq_len), dtype=np.int32)
