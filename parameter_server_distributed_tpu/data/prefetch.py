"""Input-pipeline prefetch: overlap host batch preparation and H2D
transfer with device compute.

JAX dispatches steps asynchronously, but the HOST work between steps —
drawing the next batch from the loader (file reads, tokenization,
shuffling) and placing it with `device_put` — runs serially in the loop
unless something overlaps it.  `prefetch_to_device` runs the loader and
placement on a daemon thread, keeping up to ``depth`` batches in flight:
by the time the loop asks for batch i+1, its transfer was started while
step i computed (double buffering at depth 1; the default 2 also hides
loader jitter).

The reference has no data pipeline at all (SURVEY.md §1); this is the
TPU-native analogue of the prefetch stage every production input pipeline
has.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Iterator

_SENTINEL = object()


def prefetch_to_device(batches: Iterator, place: Callable,
                       depth: int = 2) -> Iterator:
    """Wrap ``batches`` so ``place(batch)`` (e.g. ShardedTrainer.put_batch)
    runs on a background thread, ``depth`` batches ahead of the consumer.

    Exceptions from the loader or placement are re-raised at the
    consumer's next() call.  The thread is a daemon and also exits when
    the iterator is garbage-collected or explicitly closed via .close().
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    out: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def worker():
        try:
            for batch in batches:
                placed = place(batch)
                while not stop.is_set():
                    try:
                        out.put(placed, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            out.put(_SENTINEL)
        except BaseException as exc:  # noqa: BLE001 — surface at next()
            out.put(exc)

    thread = threading.Thread(target=worker, daemon=True,
                              name="psdt-prefetch")
    thread.start()

    class _Prefetcher:
        def __init__(self):
            self._done: BaseException | None = None
            self._exhausted = False

        def __iter__(self):
            return self

        def __next__(self):
            # latch terminal states: the sentinel/exception is a one-shot
            # queue item, so re-raising from memory keeps repeated next()
            # calls from blocking forever on an empty queue
            if self._exhausted:
                raise StopIteration
            if self._done is not None:
                raise self._done
            item = out.get()
            if item is _SENTINEL:
                self._exhausted = True
                raise StopIteration
            if isinstance(item, BaseException):
                self._done = item
                raise item
            return item

        def close(self):
            stop.set()
            # Drain already-placed batches so their device buffers are
            # actually released (the queue would otherwise pin up to
            # ``depth`` batches of HBM through the final eval/checkpoint),
            # then give the worker a moment to observe stop and exit.
            for _ in range(2):  # 2nd pass: a worker mid-put can slip one
                while True:     # more batch in after the first drain
                    try:
                        out.get_nowait()
                    except queue.Empty:
                        break
                thread.join(timeout=1.0)
            if thread.is_alive():
                # Producer stuck inside a slow upstream iterator: its one
                # in-flight batch keeps device buffers pinned.  Surface it
                # rather than returning silently.
                logging.getLogger("pst.prefetch").warning(
                    "prefetch: producer thread still alive after close() "
                    "(blocked in upstream iterator?); one in-flight batch "
                    "may keep device buffers pinned")

        def __del__(self):
            stop.set()

    return _Prefetcher()
