"""File-backed datasets: memmap token shards and npz example sets.

The reference has no data pipeline at all (SURVEY.md §1); synthetic.py
covers hermetic tests and benchmarks.  This module is the real-data path:

- **Token shards** (`token_stream`): flat binary files of token ids
  (uint16/uint32 little-endian — the standard GPT-style ``.bin`` layout),
  opened with ``np.memmap`` so multi-GB corpora stream from page cache
  without loading into RAM.  Batches are random [seq_len] crops.
- **Example sets** (`npz_stream`): an ``.npz`` with arrays ``x`` and ``y``
  (any model input/label pair), shuffled each epoch.

Worker sharding: pass a distinct ``seed`` per worker (the CLIs already
default seed to worker_id) so workers draw different crops/orders, the
same contract the synthetic loaders follow.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np


def load_tokens(path: str, dtype: str | None = None) -> np.ndarray:
    """Memmap a flat binary token file.  dtype auto-detection: ``.u16``/
    ``.u32`` extension wins, else uint16 (the common GPT shard format)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"token file {path!r} does not exist")
    if dtype is None:
        dtype = {".u32": "<u4"}.get(os.path.splitext(path)[1], "<u2")
    tokens = np.memmap(path, dtype=dtype, mode="r")
    if tokens.size == 0:
        raise ValueError(f"token file {path!r} is empty")
    return tokens


def token_stream(path: str, batch_size: int, seq_len: int,
                 seed: int = 0, dtype: str | None = None,
                 vocab: int | None = None) -> Iterator[np.ndarray]:
    """Endless [batch, seq_len] int32 batches of random crops from a token
    shard — drop-in for synthetic.synthetic_tokens.  ``vocab`` validates
    every batch's ids: under jit, out-of-range embedding lookups CLAMP
    instead of erroring, so a shard from a different tokenizer would
    otherwise train on silently-mangled data."""
    tokens = load_tokens(path, dtype)
    if tokens.size < seq_len:
        raise ValueError(
            f"token file {path!r} has {tokens.size} tokens, need at least "
            f"seq_len = {seq_len}")
    rng = np.random.default_rng(seed)
    high = tokens.size - seq_len + 1  # inclusive of the final full crop
    # Validate BEFORE the int32 conversion: a corrupt/mismatched shard with
    # ids >= 2^31 would wrap negative under astype and then clamp silently
    # inside the jitted embedding lookup — the exact failure this check
    # exists to catch.
    limit = vocab if vocab is not None else np.int64(1) << 31
    while True:
        starts = rng.integers(0, high, size=batch_size)
        batch = np.stack([tokens[s:s + seq_len] for s in starts])
        if batch.max() >= limit:
            what = (f"model vocab {vocab}" if vocab is not None
                    else "int32 range")
            raise ValueError(
                f"token file {path!r} has id {int(batch.max())} >= {what} "
                f"— wrong tokenizer/shard for this model")
        yield batch.astype(np.int32)


def npz_stream(path: str, batch_size: int, seed: int = 0,
               drop_remainder: bool = True
               ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Endless shuffled (x, y) batches from an npz with arrays 'x' and 'y'
    — drop-in for synthetic.ClassClusterDataset.batch_stream."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"dataset {path!r} does not exist")
    with np.load(path) as data:
        missing = {"x", "y"} - set(data.files)
        if missing:
            raise ValueError(f"{path!r} lacks arrays {sorted(missing)} "
                             f"(has {sorted(data.files)})")
        x, y = np.asarray(data["x"]), np.asarray(data["y"])
    if len(x) != len(y):
        raise ValueError(f"{path!r}: len(x)={len(x)} != len(y)={len(y)}")
    if len(x) < batch_size and drop_remainder:
        raise ValueError(f"{path!r} has {len(x)} examples < batch_size "
                         f"{batch_size}")
    from .synthetic import xy_batch_stream
    return xy_batch_stream(x, y, batch_size, seed=seed,
                           drop_remainder=drop_remainder)
