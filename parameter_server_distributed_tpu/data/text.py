"""Raw text -> token shard pipeline (offline, dependency-free).

Completes the LM data story end to end: a plain text corpus becomes the
flat binary token shard that `files.token_stream` memmaps, with no
network-downloaded tokenizer required.

Two tokenizers:

- **ByteTokenizer** (default): UTF-8 bytes as token ids (vocab 256 + BOS/
  EOS sentinels = 258).  Zero vocabulary to ship, reversible for any
  text, and the scheme used by byte-level LM baselines.
- Any Hugging Face tokenizer object can be passed to
  :func:`encode_file` instead (``transformers`` is an optional install);
  only ``encode(text) -> list[int]`` and ``vocab_size`` are used.

The shard writer streams the corpus in chunks — constant memory for
multi-GB inputs — and picks uint16/uint32 by vocabulary size to match
`files.load_tokens` auto-detection.
"""

from __future__ import annotations

import os
from typing import Iterator, Protocol

import numpy as np


class Tokenizer(Protocol):
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids) -> str: ...


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0-255 are bytes, 256=BOS, 257=EOS."""

    BOS = 256
    EOS = 257
    vocab_size = 258

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        data = bytes(int(i) for i in np.asarray(ids).reshape(-1)
                     if int(i) < 256)
        return data.decode("utf-8", errors="replace")


def require_vocab(model_vocab: int, tokenizer: "Tokenizer") -> None:
    """Raise when a model's vocabulary cannot cover the tokenizer's ids —
    the single guard shared by the registry's .txt path and the
    generation CLI."""
    if model_vocab < tokenizer.vocab_size:
        raise ValueError(
            f"model vocab {model_vocab} < byte tokenizer vocab "
            f"{tokenizer.vocab_size}; use a vocab>={tokenizer.vocab_size} "
            f"LM for text prompts/corpora")


def _shard_dtype(vocab_size: int) -> np.dtype:
    return np.dtype("<u2") if vocab_size <= 1 << 16 else np.dtype("<u4")


def _whitespace_chunks(src, chunk_bytes: int):
    """Yield the corpus in pieces cut only at whitespace: a word never
    spans two pieces, so subword (BPE) tokenizers produce the same ids as
    whole-text encoding.  (Tokenizers that add per-call special tokens
    must be configured not to — e.g. add_special_tokens=False.)"""
    tail = ""
    while True:
        chunk = src.read(chunk_bytes)
        if not chunk:
            if tail:
                yield tail
            return
        text = tail + chunk
        cut = max(text.rfind(" "), text.rfind("\n"))
        if 0 <= cut < len(text) - 1:
            tail = text[cut + 1:]
            text = text[:cut + 1]
        else:
            tail = ""
        if text:
            yield text


def encode_file(text_path: str, shard_path: str,
                tokenizer: Tokenizer | None = None,
                chunk_bytes: int = 1 << 20,
                add_document_tokens: bool = True) -> int:
    """Tokenize ``text_path`` into the flat binary shard ``shard_path``
    (the `files.token_stream` format); returns the token count.

    Streams in ~``chunk_bytes`` pieces cut at whitespace (constant memory,
    subword-tokenizer-safe).  The shard is written to a temp path and
    os.replace()d into place, so a crash mid-encode never leaves a partial
    file that later reads as a valid cache.  With ``add_document_tokens``
    a BOS is written first and an EOS last, when the tokenizer defines
    those ids."""
    tokenizer = tokenizer or ByteTokenizer()
    dtype = _shard_dtype(tokenizer.vocab_size)
    bos = getattr(tokenizer, "BOS", None)
    eos = getattr(tokenizer, "EOS", None)
    total = 0
    os.makedirs(os.path.dirname(os.path.abspath(shard_path)), exist_ok=True)
    tmp = f"{shard_path}.tmp.{os.getpid()}"
    try:
        with open(text_path, "r", encoding="utf-8") as src, \
                open(tmp, "wb") as out:
            if add_document_tokens and bos is not None:
                out.write(np.asarray([bos], dtype).tobytes())
                total += 1
            for text in _whitespace_chunks(src, chunk_bytes):
                # validate BEFORE narrowing to the shard dtype — a uint16
                # conversion of an out-of-range id would wrap or overflow
                # before the check could see it
                ids = np.asarray(tokenizer.encode(text), np.int64)
                if ids.size and (int(ids.max()) >= tokenizer.vocab_size
                                 or int(ids.min()) < 0):
                    raise ValueError(
                        f"tokenizer produced id outside [0, "
                        f"{tokenizer.vocab_size}) = vocab_size range")
                out.write(ids.astype(dtype).tobytes())
                total += ids.size
            if add_document_tokens and eos is not None:
                out.write(np.asarray([eos], dtype).tobytes())
                total += 1
        os.replace(tmp, shard_path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return total


def text_stream(text_path: str, batch_size: int, seq_len: int,
                seed: int = 0, tokenizer: Tokenizer | None = None,
                cache_dir: str | None = None) -> Iterator[np.ndarray]:
    """Endless [batch, seq_len] int32 batches straight from a text file:
    tokenizes to a cached shard next to the source (or in ``cache_dir``)
    on first use, then streams random crops via `files.token_stream`."""
    from .files import token_stream

    tokenizer = tokenizer or ByteTokenizer()
    # cache name carries a tokenizer fingerprint: switching tokenizers
    # must re-encode, never silently reuse another tokenizer's ids
    fingerprint = f"{type(tokenizer).__name__}{tokenizer.vocab_size}"
    suffix = ".bin" if _shard_dtype(tokenizer.vocab_size).itemsize == 2 \
        else ".u32"
    base = f"{os.path.basename(text_path)}.{fingerprint}{suffix}"
    shard = os.path.join(cache_dir or os.path.dirname(
        os.path.abspath(text_path)), base)
    if (not os.path.exists(shard)
            or os.path.getmtime(shard) < os.path.getmtime(text_path)):
        encode_file(text_path, shard, tokenizer)
    return token_stream(shard, batch_size, seq_len, seed=seed,
                        vocab=tokenizer.vocab_size)
