"""Autoregressive generation for the decoder Transformer, KV-cached.

The reference has no inference path at all (it has no model — SURVEY.md §1:
gradient computation is a 0.01-constant stub, reference src/worker.cpp:316-329);
a complete training framework with an LM flagship needs one.  TPU-first
design:

- one jitted **prefill** over the whole prompt (full-sequence forward via
  ``Transformer.apply_collect_kv``, MXU-shaped) that seeds the cache;
- one jitted **decode loop** (`lax.scan` over steps) where each step runs a
  single-token forward against the cache — static shapes throughout: the
  cache is pre-allocated at prompt_len + max_new_tokens and masked by
  position, so nothing retraces as generation proceeds;
- greedy or temperature/top-k sampling via `jax.random.categorical`.

The decode step calls the same layer helpers as the training forward
(``Transformer.qkv`` / ``attn_residual`` / ``ffn_residual`` /
``final_logits`` — the layer math exists exactly once); only the attention
itself differs: a dense dot against the cache, masked to positions <=
current — the cache analogue of models/transformer.py ``causal_attention``.
MoE layers decode drop-free (see ``Transformer.ffn_residual``): training's
capacity dropping is batch-global, so for tokens the training forward
dropped, cached decode legitimately differs; for all kept tokens the paths
are token-exact.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import Transformer

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Per-layer key/value cache.  k/v: [L, B, max_len, H, D]; length is the
    number of valid positions (a traced scalar so decode never retraces)."""
    k: Array
    v: Array
    length: Array

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantKVCache:
    """int8 KV cache: k/v int8 [L, B, max_len, H, D] with a per-(position,
    head) f32 absmax scale [L, B, max_len, H].  Long-context decode is
    cache-bandwidth-bound (the cache bytes streamed per token dwarf the
    weights once B*S is large), so int8 storage nearly halves the HBM
    traffic of every decode step; the int8->compute-dtype convert fuses
    into the attention einsums.  Scale overhead is 4/D bytes/elem (~6% at
    D=64).  Companion to the weight-only path in models/quant.py."""
    k: Array
    v: Array
    k_scale: Array
    v_scale: Array
    length: Array

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def _kv_quantize(x: Array) -> tuple[Array, Array]:
    """Symmetric int8 over the head_dim (last) axis: x [..., D] ->
    (int8 [..., D], f32 scale [...])."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def init_cache(model: Transformer, batch: int, max_len: int,
               cache_dtype: str = "native") -> KVCache | QuantKVCache:
    c = model.config
    if cache_dtype not in ("native", "int8"):
        raise ValueError(
            f"cache_dtype must be 'native' or 'int8', got {cache_dtype!r}")
    # GQA: the cache stores kv_heads (< n_heads) — n_heads/kv_heads x less
    # cache HBM; heads expand to the query count at attention time
    shape = (c.n_layers, batch, max_len, c.kv_heads, c.head_dim)
    if cache_dtype == "int8":
        return QuantKVCache(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.ones(shape[:-1], jnp.float32),
            v_scale=jnp.ones(shape[:-1], jnp.float32),
            length=jnp.zeros((), jnp.int32))
    return KVCache(k=jnp.zeros(shape, c.dtype), v=jnp.zeros(shape, c.dtype),
                   length=jnp.zeros((), jnp.int32))


def check_position_budget(model: Transformer, prompt_len: int,
                          max_new_tokens: int) -> None:
    """Learned-position models have a hard position ceiling (the embed/pos
    table); reject generations that would run past it instead of silently
    reusing the last row's embedding (Transformer.embed clips only for
    speculative slack lanes whose output is discarded)."""
    c = model.config
    if c.pos_emb == "learned" and prompt_len + max_new_tokens > c.max_seq:
        raise ValueError(
            f"prompt {prompt_len} + max_new {max_new_tokens} exceeds the "
            f"learned-position table max_seq={c.max_seq}")


def prefill(model: Transformer, params: Mapping[str, Array], tokens: Array,
            max_len: int, cache_dtype: str = "native",
            ) -> tuple[Array, KVCache | QuantKVCache]:
    """Run the prompt through the full-sequence forward; returns the last
    position's logits [B, vocab] and a cache holding the prompt's K/V
    (int8-quantized on write when ``cache_dtype="int8"``)."""
    batch, prompt_len = tokens.shape
    if prompt_len > max_len:
        raise ValueError(f"prompt {prompt_len} exceeds cache {max_len}")
    logits, kvs = model.apply_collect_kv(params, tokens)
    cache = init_cache(model, batch, max_len, cache_dtype)
    k = jnp.stack([k for k, _ in kvs])        # [L, B, S, H, D]
    v = jnp.stack([v for _, v in kvs])
    at0 = (0, 0, 0, 0, 0)
    if isinstance(cache, QuantKVCache):
        k8, ks = _kv_quantize(k)
        v8, vs = _kv_quantize(v)
        cache = QuantKVCache(
            k=jax.lax.dynamic_update_slice(cache.k, k8, at0),
            v=jax.lax.dynamic_update_slice(cache.v, v8, at0),
            k_scale=jax.lax.dynamic_update_slice(cache.k_scale, ks, at0[:-1]),
            v_scale=jax.lax.dynamic_update_slice(cache.v_scale, vs, at0[:-1]),
            length=jnp.asarray(prompt_len, jnp.int32))
        return logits[:, -1], cache
    cache = KVCache(
        k=jax.lax.dynamic_update_slice(cache.k, k.astype(cache.k.dtype),
                                       at0),
        v=jax.lax.dynamic_update_slice(cache.v, v.astype(cache.v.dtype),
                                       at0),
        length=jnp.asarray(prompt_len, jnp.int32))
    return logits[:, -1], cache


def decode_block(model: Transformer, params: Mapping[str, Array],
                 tokens: Array, cache: KVCache | QuantKVCache,
                 lengths: Array | None = None,
                 ) -> tuple[Array, KVCache | QuantKVCache]:
    """Forward a block of ``tokens`` [B, T] against the cache at positions
    length..length+T-1, causally masked within the block — the verify
    step of speculative decoding (T=1 is ordinary single-token decode).
    Returns (logits [B, T, vocab] f32, cache with length advanced by T;
    rolling ``length`` back later simply re-exposes old positions — stale
    K/V beyond length are masked out and overwritten on the next write).

    ``lengths`` [B] switches to RAGGED mode: row b's block writes at its
    own positions lengths[b]..lengths[b]+T-1 (per-row scatter instead of
    one dynamic_update_slice) and attends within its own valid prefix.
    cache.length is then ignored and returned unchanged — callers track
    the per-row lengths.  This is what batched speculative decoding needs:
    rows accept different numbers of draft tokens, so their caches advance
    at different rates (models/generation.speculative_generate_batched).
    """
    c = model.config
    batch, t = tokens.shape
    ragged = lengths is not None
    offsets = jnp.arange(t, dtype=jnp.int32)
    if ragged:
        positions = lengths[:, None] + offsets[None, :]      # [B, T]
        # row b's query j may attend its cache positions 0..lengths[b]+j
        mask = (jnp.arange(cache.max_len)[None, None, :]
                <= positions[:, :, None])[:, None, None]     # [B,1,1,T,M]
        bidx = jnp.arange(batch, dtype=jnp.int32)[:, None]
    else:
        pos = cache.length                                   # scalar int32
        positions = pos + offsets[None, :].repeat(batch, 0)  # [B, T]
        # query j may attend cache positions 0..pos+j
        mask = (jnp.arange(cache.max_len)[None, :]
                <= (pos + offsets)[:, None])[None, None, None]  # [1,1,1,T,M]
    # shared embed: adds learned positional embeddings at the ragged
    # positions when the config uses them (positions overshooting max_seq
    # for finished speculative rows hit embed's explicit mode="clip" —
    # those lanes' outputs are discarded)
    h = model.embed(params, tokens, positions)               # [B, T, d]
    quant = isinstance(cache, QuantKVCache)
    new_k, new_v = cache.k, cache.v
    new_ks = cache.k_scale if quant else None
    new_vs = cache.v_scale if quant else None
    groups = c.kv_groups
    for i in range(c.n_layers):
        # layer_view resolves either param layout (unrolled layer<i>/* or
        # scan_layers' stacked blocks/*)
        lp, p = model.layer_view(params, i)
        q, k, v = model.qkv(lp, p, h, positions)  # k/v: [B, T, KV, D]
        if quant:
            k, ks = _kv_quantize(k)
            v, vs = _kv_quantize(v)
        if ragged:
            # mode="drop": rows that finished generating keep advancing
            # their lengths each speculative round, so their scatter
            # positions intentionally overshoot cache.max_len — those
            # writes must be dropped, not clamped onto the last slot.
            new_k = new_k.at[i, bidx, positions].set(
                k.astype(new_k.dtype), mode="drop")
            new_v = new_v.at[i, bidx, positions].set(
                v.astype(new_v.dtype), mode="drop")
            if quant:
                new_ks = new_ks.at[i, bidx, positions].set(ks, mode="drop")
                new_vs = new_vs.at[i, bidx, positions].set(vs, mode="drop")
        else:
            new_k = jax.lax.dynamic_update_slice(
                new_k, k[None].astype(new_k.dtype), (i, 0, pos, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                new_v, v[None].astype(new_v.dtype), (i, 0, pos, 0, 0))
            if quant:
                new_ks = jax.lax.dynamic_update_slice(
                    new_ks, ks[None], (i, 0, pos, 0))
                new_vs = jax.lax.dynamic_update_slice(
                    new_vs, vs[None], (i, 0, pos, 0))
        # dense attention against the cache, f32 softmax.  GQA: contract
        # query-head groups directly against the UNexpanded cache — the
        # cache bytes streamed per step stay kv_heads-sized (the point of
        # the smaller cache), no materialized repeat
        b, s_q = q.shape[:2]
        qg = q.reshape(b, s_q, c.kv_heads, groups, c.head_dim)
        # int8 cache: contract against the int8 array (only int8 bytes
        # stream from HBM; the convert fuses into the einsum) and fold the
        # per-(position, head) scale into the product afterwards
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                            new_k[i].astype(c.dtype) if quant else new_k[i],
                            preferred_element_type=jnp.float32)
        if quant:
            # k_scale[i]: [B, M, H] -> [B, H, 1, 1, M] over score axes
            scores = scores * jnp.transpose(
                new_ks[i], (0, 2, 1))[:, :, None, None, :]
        scores = scores / jnp.sqrt(jnp.asarray(c.head_dim, jnp.float32))
        scores = jnp.where(mask, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        if quant:
            # fold v_scale into probs (tiny [.., M] multiply) so the value
            # contraction streams raw int8
            probs = probs * jnp.transpose(
                new_vs[i], (0, 2, 1))[:, :, None, None, :].astype(c.dtype)
        attn = jnp.einsum("bhgqk,bkhd->bqhgd", probs,
                          new_v[i].astype(c.dtype) if quant else new_v[i],
                          preferred_element_type=jnp.float32).astype(c.dtype)
        attn = attn.reshape(b, s_q, c.n_heads, c.head_dim)
        h = model.attn_residual(lp, p, h, attn)
        # MoE-aware, drop-free at decode time; aux loss unused here
        h, _ = model.ffn_residual(params, i, h, decode=True)
    logits = model.final_logits(params, h)
    new_length = cache.length if ragged else pos + t
    if quant:
        return logits, QuantKVCache(k=new_k, v=new_v, k_scale=new_ks,
                                    v_scale=new_vs, length=new_length)
    return logits, KVCache(k=new_k, v=new_v, length=new_length)


def decode_step(model: Transformer, params: Mapping[str, Array],
                token: Array, cache: KVCache | QuantKVCache,
                ) -> tuple[Array, KVCache | QuantKVCache]:
    """One single-token forward against the cache.  token: [B] int32 ->
    (logits [B, vocab] float32, updated cache)."""
    logits, cache = decode_block(model, params, token[:, None], cache)
    return logits[:, 0], cache


def _truncate_logits(logits: Array, top_k: int, top_p: float) -> Array:
    """Top-k and/or nucleus truncation on temperature-scaled logits
    (shared by the scalar and per-row samplers)."""
    top_k = min(top_k, logits.shape[-1])  # top_k > vocab = no truncation
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # keep a token while the cumulative mass BEFORE it is < top_p
        # (the argmax token is always kept); cut logits below the
        # smallest kept one
        keep = (cumulative - probs) < top_p
        kth = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                      keepdims=True)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return logits


def sample_token(logits: Array, rng: Array, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0) -> Array:
    """Greedy when temperature == 0; otherwise temperature softmax
    sampling, optionally truncated to the top_k logits and/or the nucleus
    (smallest set of tokens with cumulative probability >= top_p)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _truncate_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_token_rowwise(logits: Array, rng: Array, temps: Array,
                         top_k: int = 0, top_p: float = 0.0) -> Array:
    """Per-row temperature sampling in ONE traced program: row i is
    greedy when ``temps[i] == 0``, temperature-sampled otherwise
    (top_k/top_p truncation stays static — shared by all rows).  Lets a
    continuous-batching server honor per-request temperatures without a
    recompile per distinct value.  logits: [B, V]; temps: [B]."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    scaled = _truncate_logits(scaled, top_k, top_p)
    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


# Compiled runner cache: one jitted wrapper per (model, generation config),
# keyed on the model's never-reused cache_token (id() can be recycled after
# GC).  jax.jit's own cache then handles distinct prompt shapes.
# Bounded LRU: a long-lived service sweeping generation settings would
# otherwise pin compiled executables (and their models) for process
# lifetime.  Lock-guarded — concurrent generate() calls share the cache.
_RUNNERS: "OrderedDict[tuple, object]" = OrderedDict()
_RUNNERS_MAX = 32
_RUNNERS_LOCK = threading.Lock()


def _model_key(model) -> int:
    # cache_token is assigned in Transformer.__init__; getattr keeps
    # duck-typed model stand-ins (tests) working, accepting id()'s
    # recycling caveat only for those.
    token = getattr(model, "cache_token", None)
    return id(model) if token is None else token


def _cached_runner(key: tuple, build):
    """LRU-cached compiled runner: one lock/evict protocol for every
    runner flavor.  A concurrent miss may build twice (benign — last
    insert wins and the loser is garbage)."""
    with _RUNNERS_LOCK:
        run = _RUNNERS.get(key)
        if run is not None:
            _RUNNERS.move_to_end(key)
            return run
    run = build()
    with _RUNNERS_LOCK:
        _RUNNERS[key] = run
        while len(_RUNNERS) > _RUNNERS_MAX:
            _RUNNERS.popitem(last=False)
    return run


def _runner(model: Transformer, max_new_tokens: int, temperature: float,
            top_k: int, top_p: float, cache_dtype: str = "native"):
    key = (_model_key(model), max_new_tokens, temperature, top_k, top_p,
           cache_dtype)

    def build():
        @jax.jit
        def run(params, prompt, rng):
            max_len = prompt.shape[1] + max_new_tokens
            logits, cache = prefill(model, params, prompt, max_len,
                                    cache_dtype)
            rng0, rng = jax.random.split(rng)
            first = sample_token(logits, rng0, temperature, top_k, top_p)

            def body(carry, _):
                token, cache, rng = carry
                rng, sub = jax.random.split(rng)
                logits, cache = decode_step(model, params, token, cache)
                nxt = sample_token(logits, sub, temperature, top_k, top_p)
                return (nxt, cache, rng), token

            (_, _, _), tokens = jax.lax.scan(
                body, (first, cache, rng), None, length=max_new_tokens)
            return jnp.swapaxes(tokens, 0, 1)      # [B, max_new]

        return run

    return _cached_runner(key, build)


def _beam_runner(model: Transformer, max_new_tokens: int, beam_width: int,
                 eos_id: int | None, length_penalty: float):
    key = (_model_key(model), max_new_tokens, "beam", beam_width, eos_id,
           length_penalty)

    def build():
        @jax.jit
        def run(params, prompt):
            b, s = prompt.shape
            w = beam_width
            max_len = s + max_new_tokens
            logits, cache = prefill(model, params, prompt, max_len)
            logp = jax.nn.log_softmax(logits, axis=-1)        # [B, V]
            vocab = logp.shape[-1]
            scores, first = jax.lax.top_k(logp, w)            # [B, W]
            finished = (jnp.zeros((b, w), bool) if eos_id is None
                        else first == eos_id)
            lengths = jnp.ones((b, w), jnp.int32)

            # beams live interleaved in the cache batch dim: row b*W + j
            def tile(x):
                return jnp.repeat(x, w, axis=1)
            cache = KVCache(k=tile(cache.k), v=tile(cache.v),
                            length=cache.length)
            seqs = jnp.zeros((b, w, max_new_tokens), jnp.int32)
            seqs = seqs.at[:, :, 0].set(first)

            def body(carry, i):
                seqs, scores, finished, lengths, cache = carry
                tok = jax.lax.dynamic_index_in_dim(
                    seqs, i - 1, axis=2, keepdims=False)       # [B, W]
                logits, cache = decode_step(model, params,
                                            tok.reshape(b * w), cache)
                logp = jax.nn.log_softmax(logits, axis=-1).reshape(
                    b, w, vocab)
                if eos_id is not None:
                    # a finished beam may only continue with EOS at logp 0:
                    # its joint score freezes and it stays comparable in
                    # the flat top-k against live beams
                    pad = jnp.full((vocab,), -jnp.inf).at[eos_id].set(0.0)
                    logp = jnp.where(finished[:, :, None],
                                     pad[None, None, :], logp)
                total = scores[:, :, None] + logp
                scores, flat = jax.lax.top_k(
                    total.reshape(b, w * vocab), w)            # [B, W]
                parent = flat // vocab                         # [B, W]
                token = (flat % vocab).astype(jnp.int32)
                # reorder histories and cache rows onto the winning beams
                seqs = jnp.take_along_axis(seqs, parent[:, :, None], axis=1)
                seqs = jax.lax.dynamic_update_slice_in_dim(
                    seqs, token[:, :, None], i, axis=2)
                finished = jnp.take_along_axis(finished, parent, axis=1)
                lengths = jnp.take_along_axis(lengths, parent, axis=1)
                # a beam already finished keeps its length; live beams
                # (including one finishing right now, whose EOS counts)
                # are i+1 tokens long
                lengths = jnp.where(finished, lengths, i + 1)
                if eos_id is not None:
                    finished = finished | (token == eos_id)
                rows = (jnp.arange(b)[:, None] * w + parent).reshape(-1)
                cache = KVCache(k=jnp.take(cache.k, rows, axis=1),
                                v=jnp.take(cache.v, rows, axis=1),
                                length=cache.length)
                return (seqs, scores, finished, lengths, cache), None

            (seqs, scores, _, lengths, _), _ = jax.lax.scan(
                body, (seqs, scores, finished, lengths, cache),
                jnp.arange(1, max_new_tokens))
            if length_penalty:
                # GNMT normalization at final selection only (within-step
                # pruning stays raw-joint-log-prob): score / lp(len) with
                # lp = ((5 + len) / 6) ** alpha
                lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0
                      ) ** length_penalty
                best = jnp.argmax(scores / lp, axis=1)
            else:
                best = jnp.argmax(scores, axis=1)
            out = jnp.take_along_axis(seqs, best[:, None, None],
                                      axis=1)[:, 0]            # [B, max_new]
            return out, jnp.take_along_axis(scores, best[:, None],
                                            axis=1)[:, 0]

        return run

    return _cached_runner(key, build)


def beam_search(model: Transformer, params: Mapping[str, Array],
                prompt: Array, max_new_tokens: int,
                beam_width: int = 4,
                eos_id: int | None = None,
                length_penalty: float = 0.0) -> tuple[Array, Array]:
    """Fixed-length beam search over ``max_new_tokens`` continuations:
    keeps the ``beam_width`` highest joint-log-prob prefixes each step,
    reordering the KV cache rows onto the surviving beams (beams live
    interleaved in the cache batch dim).  Returns (tokens [B, max_new],
    joint log-prob [B]) for each item's best beam.  beam_width=1 is
    greedy decoding.  With ``eos_id`` set, a beam that emits it finishes:
    its score freezes and it pads with EOS while live beams keep
    expanding (the scan still runs the static full length — shapes never
    change; trim at the first EOS on the host).  ``length_penalty``
    alpha > 0 applies GNMT length normalization (score / ((5+len)/6)^a)
    at the FINAL beam selection, countering the short-hypothesis bias
    EOS finishing introduces; 0 selects by raw joint log-prob."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if not 1 <= beam_width <= model.config.vocab:
        raise ValueError(f"beam_width={beam_width} must be in "
                         f"[1, vocab={model.config.vocab}]")
    if eos_id is not None and not 0 <= eos_id < model.config.vocab:
        raise ValueError(f"eos_id={eos_id} outside vocab "
                         f"{model.config.vocab}")
    check_position_budget(model, int(np.asarray(prompt).shape[1]),
                          max_new_tokens)
    return _beam_runner(model, max_new_tokens, beam_width, eos_id,
                        float(length_penalty))(params, prompt)


def _decode_step_runner(model: Transformer):
    key = (_model_key(model), "spec_step")
    return _cached_runner(key, lambda: jax.jit(
        lambda params, tok, cache: decode_step(model, params, tok, cache)))


def _decode_block_runner(model: Transformer, t: int):
    key = (_model_key(model), "spec_block", t)
    return _cached_runner(key, lambda: jax.jit(
        lambda params, toks, cache: decode_block(model, params, toks, cache)))


def accept_or_resample(p: "np.ndarray", q: "np.ndarray", x: int,
                       rng: "np.random.Generator") -> tuple[int, bool]:
    """The speculative-sampling rejection rule (Leviathan/Chen): accept
    draft token ``x`` (drawn from q) with probability min(1, p[x]/q[x]);
    on reject, sample from the residual normalize(max(p - q, 0)).  Over
    the randomness of (x ~ q, this rule), the returned token is EXACTLY
    distributed as p — tested empirically in tests/test_generation.py.
    Returns (token, accepted)."""
    if rng.uniform() < min(1.0, float(p[x]) / max(float(q[x]), 1e-20)):
        return x, True
    residual = np.maximum(p - q, 0.0)
    total = residual.sum()
    if total <= 0.0:   # p == q: acceptance was certain, but guard anyway
        return int(rng.choice(len(p), p=p / p.sum())), False
    return int(rng.choice(len(p), p=residual / total)), False


def speculative_generate(target: Transformer, target_params,
                         draft: Transformer, draft_params,
                         prompt: Array, max_new_tokens: int, *,
                         draft_len: int = 4, temperature: float = 0.0,
                         seed: int = 0) -> tuple[Array, dict]:
    """Greedy speculative decoding: the cheap ``draft`` model proposes
    ``draft_len`` tokens autoregressively, the ``target`` verifies them in
    ONE ``decode_block`` forward, and the longest agreeing prefix plus the
    target's own next token commit — per verify call the output advances
    1..draft_len+1 tokens at one target forward, while remaining
    TOKEN-EXACT vs target-alone greedy decoding (tested).  Rejection
    rollback is free: KVCache.length just moves back, stale entries are
    masked and overwritten.

    ``temperature=0`` is greedy (output token-exact vs target-alone
    greedy decoding); ``temperature>0`` is speculative SAMPLING with the
    rejection rule (:func:`accept_or_resample`), which preserves the
    target's temperature-adjusted sampling distribution exactly.

    Batch 1 (rows would accept different counts and the cache keeps one
    scalar length).  Returns (tokens [1, max_new], stats) where stats
    reports verify calls and acceptance counts — the speedup story on
    real hardware is target-forwards / tokens."""
    if prompt.shape[0] != 1:
        raise ValueError("speculative decoding is batch-1 (per-row "
                         "acceptance lengths diverge)")
    if target.config.vocab != draft.config.vocab:
        raise ValueError(
            f"vocab mismatch: target {target.config.vocab} vs draft "
            f"{draft.config.vocab}")
    if draft_len < 1:
        raise ValueError("draft_len must be >= 1")

    s = prompt.shape[1]
    # + draft_len + 1: a verify block may run past the committed length
    # before rolling back
    check_position_budget(target, s, max_new_tokens + draft_len + 1)
    check_position_budget(draft, s, max_new_tokens + draft_len + 1)
    sampling = temperature > 0.0
    host_rng = np.random.default_rng(seed)

    def host_probs(logits_row) -> "np.ndarray":
        p = np.asarray(jax.nn.softmax(logits_row / temperature, axis=-1),
                       np.float64)
        return p / p.sum()

    # headroom: a verify block may write draft_len+1 entries past the
    # committed length before rolling back
    max_len = s + max_new_tokens + draft_len + 1
    t_logits, t_cache = prefill(target, target_params, prompt, max_len)
    _, d_cache = prefill(draft, draft_params, prompt, max_len)
    d_step = _decode_step_runner(draft)
    t_block = _decode_block_runner(target, draft_len + 1)

    out: list[int] = []
    if sampling:
        p0 = host_probs(t_logits[0])
        cur = int(host_rng.choice(len(p0), p=p0))
    else:
        cur = int(np.asarray(jnp.argmax(t_logits, axis=-1))[0])
    out.append(cur)
    pending: list[int] = []   # committed tokens not yet in the draft cache
    verify_calls = 0
    accepted_total = 0

    while len(out) < max_new_tokens:
        for tok in pending:   # catch the draft cache up to the context
            _, d_cache = d_step(draft_params,
                                jnp.asarray([tok], jnp.int32), d_cache)
        pending = []
        proposals: list[int] = []
        d_probs: list = []
        dtok = cur
        for _ in range(draft_len):
            dl, d_cache = d_step(draft_params,
                                 jnp.asarray([dtok], jnp.int32), d_cache)
            if sampling:
                q = host_probs(dl[0])
                dtok = int(host_rng.choice(len(q), p=q))
                d_probs.append(q)
            else:
                dtok = int(np.asarray(jnp.argmax(dl, axis=-1))[0])
            proposals.append(dtok)
        # target verifies [cur, p1..pk] in one forward: logits[i] scores
        # the target's token after ...cur,p1..p_i
        block = jnp.asarray([[cur] + proposals], jnp.int32)
        base = int(np.asarray(t_cache.length))
        logits, t_cache = t_block(target_params, block, t_cache)
        verify_calls += 1

        if sampling:
            rows = np.asarray(jax.nn.softmax(logits[0] / temperature,
                                             axis=-1), np.float64)
            p_all = [row / row.sum() for row in rows]  # one dispatch
            m = 0
            committed: list[int] = []
            while m < draft_len:
                token, ok = accept_or_resample(
                    p_all[m], d_probs[m], proposals[m], host_rng)
                if not ok:
                    committed.append(token)
                    break
                committed.append(token)
                m += 1
            else:
                # full accept: bonus token from the target's own dist
                committed.append(int(host_rng.choice(
                    len(p_all[draft_len]), p=p_all[draft_len])))
        else:
            greedy = np.asarray(jnp.argmax(logits, axis=-1))[0]  # [k+1]
            m = 0
            while m < draft_len and proposals[m] == int(greedy[m]):
                m += 1
            committed = proposals[:m] + [int(greedy[m])]
        accepted_total += m
        out.extend(committed)
        cur = committed[-1]
        if m == draft_len:
            # full accept + bonus token: every block entry (cur, p1..pk)
            # is committed context; the draft cache is missing p_k
            t_cache = dataclasses.replace(
                t_cache, length=jnp.asarray(base + draft_len + 1,
                                            jnp.int32))
            pending = [proposals[-1]]
        else:
            # cache keeps cur..p_{m-1} (m+1 entries); the draft cache
            # holds the same prefix plus rejected entries — roll both back
            t_cache = dataclasses.replace(
                t_cache, length=jnp.asarray(base + m + 1, jnp.int32))
            d_cache = dataclasses.replace(
                d_cache, length=jnp.asarray(base + m + 1, jnp.int32))

    tokens = np.asarray(out[:max_new_tokens], np.int32)[None]
    stats = {"verify_calls": verify_calls,
             "draft_accept_rate": (accepted_total
                                   / max(1, verify_calls * draft_len)),
             # +1: the prefill forward produced out[0] and also counts
             "tokens_per_target_forward": (tokens.shape[1]
                                           / (verify_calls + 1))}
    return tokens, stats


def _draft_propose(draft: Transformer, dparams, q_logits: Array,
                   d_cache, pc: Array, k_draft: int, temperature: float,
                   keys: list) -> tuple[Array, list, Any]:
    """The draft's k-proposal loop after its catch-up block: sample (or
    argmax) each proposal, collecting the tempered proposal distributions
    the rejection rule needs, stepping the draft cache k-1 times at the
    per-row ragged positions.  Returns (props [B, k], q_rows, d_cache).
    Shared single definition — see :func:`_greedy_accept`."""
    sampling = temperature > 0.0
    proposals = []
    q_rows: list = []
    for i in range(k_draft):
        if sampling:
            tok = jax.random.categorical(
                keys[i], q_logits / temperature, axis=-1).astype(jnp.int32)
            q_rows.append(jax.nn.softmax(q_logits / temperature, axis=-1))
        else:
            tok = jnp.argmax(q_logits, axis=-1).astype(jnp.int32)
        proposals.append(tok)
        if i < k_draft - 1:
            dl, d_cache = decode_block(draft, dparams, tok[:, None],
                                       d_cache, lengths=pc + 1 + i)
            q_logits = dl[:, 0]
    return jnp.stack(proposals, axis=1), q_rows, d_cache


def _greedy_accept(vlogits: Array, props: Array) -> tuple[Array, Array]:
    """Longest-matching-prefix acceptance for a verify block
    [cur, p_1..p_k]: (m accepted counts [B], corr next token [B]).
    Shared by the one-shot batched decoder and the serving round runner
    (models/serving.py) so the acceptance math exists once."""
    k_draft = props.shape[1]
    g = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)     # [B, k+1]
    match = (props == g[:, :k_draft]).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)        # [B]
    corr = jnp.take_along_axis(g, m[:, None], 1)[:, 0]
    return m, corr


def _sampling_accept(vlogits: Array, props: Array, q_rows: list,
                     temperature: float, key_u: Array, key_resample: Array,
                     key_bonus: Array) -> tuple[Array, Array]:
    """Vectorized Leviathan/Chen rejection for a verify block
    [cur, p_1..p_k]: accept each proposal with prob min(1, p/q), resample
    the reject position from the residual (clamped gather; overridden by
    the bonus draw when everything accepted).  Preserves the target's
    temperature-adjusted distribution exactly.  Shared single definition
    — see :func:`_greedy_accept`."""
    k_draft = props.shape[1]
    probs_t = jax.nn.softmax(vlogits / temperature, axis=-1)
    probs_q = jnp.stack(q_rows, axis=1)                    # [B, k, V]
    px = jnp.take_along_axis(
        probs_t[:, :k_draft], props[..., None], 2)[..., 0]
    qx = jnp.take_along_axis(probs_q, props[..., None], 2)[..., 0]
    u = jax.random.uniform(key_u, px.shape)
    acc = u < px / jnp.maximum(qx, 1e-20)
    m = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), 1), 1)
    gather_m = jnp.clip(m, 0, k_draft - 1)[:, None, None]
    p_m = jnp.take_along_axis(probs_t[:, :k_draft], gather_m, 1)[:, 0]
    q_m = jnp.take_along_axis(probs_q, gather_m, 1)[:, 0]
    residual = jnp.maximum(p_m - q_m, 0.0)
    total = jnp.sum(residual, -1, keepdims=True)
    residual = jnp.where(total > 0, residual, p_m)
    resampled = jax.random.categorical(
        key_resample, jnp.log(residual + 1e-30), axis=-1)
    bonus = jax.random.categorical(
        key_bonus, jnp.log(probs_t[:, k_draft] + 1e-30), axis=-1)
    corr = jnp.where(m == k_draft, bonus, resampled).astype(jnp.int32)
    return m, corr


def _init_spec_carry(target, tparams, draft, dparams, prompt, cap: int,
                     max_len: int, temperature: float, seed: int,
                     cache_dtype: str):
    """Prefill both models and build the carry the speculative segment
    runners thread: (n_out, out, cur, y, lt, pc, t_cache, d_cache, rng,
    stats[verifies, accepts, active_rows]) — the single definition of
    the speculative decode state, shared by the fixed-depth and
    adaptive paths."""
    batch, s = prompt.shape
    t_logits, t_cache = prefill(target, tparams, prompt, max_len,
                                cache_dtype)
    _, d_cache = prefill(draft, dparams, prompt, max_len, cache_dtype)
    rng = jax.random.key(seed)
    if temperature > 0.0:
        rng, k0 = jax.random.split(rng)
        cur = jax.random.categorical(k0, t_logits / temperature,
                                     axis=-1).astype(jnp.int32)
    else:
        cur = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    out = jnp.zeros((batch, cap), jnp.int32).at[:, 0].set(cur)
    return (jnp.ones((batch,), jnp.int32), out, cur,
            jnp.asarray(prompt[:, -1], jnp.int32),
            jnp.full((batch,), s, jnp.int32),
            jnp.full((batch,), s, jnp.int32),
            t_cache, d_cache, rng, jnp.zeros((3,), jnp.int32))


def _spec_round_runner(target: Transformer, draft: Transformer,
                       draft_len: int, cache_dtype: str,
                       temperature: float = 0.0):
    """Jitted per (target, draft, k, T): ONE speculative round over ALL
    slots — draft catch-up block + k-1 single proposals, one target
    verify block, vectorized acceptance.  The same math as
    generation._spec_segment_runner's loop body, but one round per call
    so the host can admit/retire requests between rounds (continuous
    batching).  Greedy (T=0, longest matching prefix) is token-exact
    whatever each slot's accept rate; T>0 applies the Leviathan/Chen
    rejection rule, preserving the target's sampling distribution.
    Returns (commit [B, k+1], n_commit [B], cur_new [B], y_new [B],
    t_cache, d_cache, rng)."""
    key = (_model_key(target), _model_key(draft), "serve_spec_round",
           draft_len, cache_dtype, temperature)
    k_draft = draft_len
    sampling = temperature > 0.0

    def build():
        @partial(jax.jit, donate_argnums=(4, 5))
        def run(tparams, dparams, cur, y, t_cache, d_cache, lt, pc, rng):
            batch = cur.shape[0]
            iota_k1 = jnp.arange(k_draft + 1, dtype=jnp.int32)
            # draft: catch-up block [y, cur] (re-writing y's slot is a
            # no-op; writing fresh is the full-accept catch-up), then
            # k-1 single steps
            dl, d_cache = decode_block(
                draft, dparams, jnp.stack([y, cur], axis=1), d_cache,
                lengths=pc - 1)
            rng, *keys = jax.random.split(rng, k_draft + 4)
            props, q_rows, d_cache = _draft_propose(
                draft, dparams, dl[:, 1], d_cache, pc, k_draft,
                temperature, keys)
            # target verifies [cur, p_1..p_k] in one ragged forward
            block = jnp.concatenate([cur[:, None], props], axis=1)
            vlogits, t_cache = decode_block(target, tparams, block,
                                            t_cache, lengths=lt)
            if sampling:
                m, corr = _sampling_accept(
                    vlogits, props, q_rows, temperature, keys[k_draft],
                    keys[k_draft + 1], keys[k_draft + 2])
            else:
                m, corr = _greedy_accept(vlogits, props)
            ext = jnp.concatenate(
                [props, jnp.zeros((batch, 1), jnp.int32)], axis=1)
            commit = jnp.where(iota_k1[None, :] < m[:, None], ext,
                               corr[:, None])             # [B, k+1]
            prev = jnp.take_along_axis(
                props, jnp.clip(m - 1, 0, k_draft - 1)[:, None], 1)[:, 0]
            y_new = jnp.where(m == 0, cur, prev)
            return commit, m + 1, corr, y_new, t_cache, d_cache, rng

        return run

    return _cached_runner(key, build)


def _invert_accept_fraction(f: float, k: int) -> float:
    """Per-proposal agreement p from a measured accept FRACTION
    f = E[m]/k at depth k, under the geometric-acceptance model
    E[m] = sum_{i=1..k} p^i (each proposal agrees independently with
    probability p; the round commits the longest agreeing prefix).
    Monotone in p -> bisection."""
    if f <= 0.0:
        return 0.0
    if f >= 1.0:
        return 1.0
    lo, hi = 0.0, 1.0
    for _ in range(40):
        mid = (lo + hi) / 2
        if sum(mid ** i for i in range(1, k + 1)) / k < f:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2


def optimal_draft_depth(accept_frac: float, k: int, k_max: int,
                        cost_ratio: float,
                        round_overhead: float = 0.25,
                        allow_disable: bool = False) -> int:
    """The depth maximizing expected tokens per round COST: a round at
    depth j commits E(p, j) = (1 - p^(j+1)) / (1 - p) tokens (accepted
    prefix + correction/bonus) and costs ``round_overhead`` + 1 target
    forward + j draft forwards at ``cost_ratio`` target-units each.
    ``round_overhead`` is the spec round's fixed overhead IN EXCESS OF
    a plain greedy step (extra dispatches: draft catch-up block, wider
    verify, commit bookkeeping) — defined that way, plain greedy scores
    exactly 1.0 token/unit, which is what the ``allow_disable``
    threshold compares against; it also breaks the cost_ratio=1.0 tie
    toward deeper drafts (fewer rounds, less excess overhead).
    ``accept_frac`` is the measured fraction at the CURRENT depth k
    (inverted to per-proposal agreement p first — fractions are not
    comparable across depths).  This model reproduces the round-4
    measurements: p=0.57, rho~1/3 -> k* in {1, 2} at ~1.2x, k=4 scoring
    ~0.9x (the observed 0.76x over-speculation loss)."""
    p = _invert_accept_fraction(accept_frac, k)
    best_k, best = 1, -1.0
    for j in range(1, max(1, k_max) + 1):
        expect = (j + 1.0 if p >= 1.0
                  else (1.0 - p ** (j + 1)) / (1.0 - p))
        score = expect / (round_overhead + 1.0 + cost_ratio * j)
        if score > best:
            best, best_k = score, j
    if allow_disable and best < 1.0:
        # even the best depth expects fewer tokens per cost than plain
        # greedy decoding (score 1.0): speculation cannot pay with this
        # draft — k=0 means "decode greedy", the arm that makes adaptive
        # speculation never lose beyond its calibration segment
        return 0
    return best_k


def _spec_segment_runner(target: Transformer, draft: Transformer,
                         cap: int, max_new_tokens: int, draft_len: int,
                         temperature: float, cache_dtype: str):
    """Resumable segment of the whole-loop batched speculative decoder:
    the speculative while_loop body over an explicit carry: the
    carry is an argument/result and the loop runs until every row
    reaches a TRACED ``seg_target`` — so an adaptive driver can run a
    few segments with different depths k (one compiled program per k,
    shared carry shapes sized by ``cap``/k_max) and re-pick k between
    them from the measured accept rate, keeping the decode device-bound
    (host syncs per SEGMENT, not per round)."""
    key_tuple = (_model_key(target), _model_key(draft), "spec_segment",
                 cap, max_new_tokens, draft_len, temperature, cache_dtype)
    k_draft = draft_len
    sampling = temperature > 0.0

    def build():
        @jax.jit
        def run(tparams, dparams, carry, seg_target):
            batch = carry[0].shape[0]
            bidx = jnp.arange(batch, dtype=jnp.int32)[:, None]
            iota_k1 = jnp.arange(k_draft + 1, dtype=jnp.int32)

            def cond(carry):
                return jnp.any(carry[0] < seg_target)

            def body(carry):
                (n_out, out, cur, y, lt, pc, t_cache, d_cache, rng_key,
                 stats) = carry
                active = n_out < max_new_tokens

                dl, d_cache = decode_block(
                    draft, dparams, jnp.stack([y, cur], axis=1), d_cache,
                    lengths=pc - 1)
                rng_key, *keys = jax.random.split(rng_key, k_draft + 3)
                props, q_rows, d_cache = _draft_propose(
                    draft, dparams, dl[:, 1], d_cache, pc, k_draft,
                    temperature, keys)

                block = jnp.concatenate([cur[:, None], props], axis=1)
                vlogits, t_cache = decode_block(target, tparams, block,
                                                t_cache, lengths=lt)

                if sampling:
                    rng_key, kr, kb = jax.random.split(rng_key, 3)
                    m, corr = _sampling_accept(vlogits, props, q_rows,
                                               temperature, keys[k_draft],
                                               kr, kb)
                else:
                    m, corr = _greedy_accept(vlogits, props)

                ext = jnp.concatenate([props, jnp.zeros((batch, 1),
                                                        jnp.int32)], 1)
                commit = jnp.where(iota_k1[None, :] < m[:, None], ext,
                                   corr[:, None])            # [B, k+1]
                n_commit = m + 1
                idx = jnp.clip(n_out[:, None] + iota_k1[None, :], 0,
                               cap - 1)
                out = out.at[bidx, idx].set(commit)
                prev = jnp.take_along_axis(
                    props, jnp.clip(m - 1, 0, k_draft - 1)[:, None],
                    1)[:, 0]
                y_new = jnp.where(m == 0, cur, prev)
                stats = stats + jnp.stack(
                    [jnp.ones((), jnp.int32),
                     jnp.sum(jnp.where(active, m, 0)),
                     jnp.sum(active.astype(jnp.int32))])
                return (n_out + n_commit, out, corr, y_new, lt + n_commit,
                        pc + n_commit, t_cache, d_cache, rng_key, stats)

            return jax.lax.while_loop(cond, body, carry)

        return run

    return _cached_runner(key_tuple, build)


def _greedy_segment_runner(target: Transformer, cap: int,
                           max_new_tokens: int, temperature: float,
                           cache_dtype: str):
    """Plain-greedy segment over the SAME carry as
    :func:`_spec_segment_runner` — the k=0 arm of adaptive speculation:
    when the controller concludes speculation cannot pay (see
    :func:`optimal_draft_depth` ``allow_disable``), remaining tokens
    decode one-per-round with the target alone.  Draft-side carry fields
    (y, pc, d_cache) pass through untouched (stale but unused)."""
    key_tuple = (_model_key(target), "greedy_segment", cap,
                 max_new_tokens, temperature, cache_dtype)
    sampling = temperature > 0.0

    def build():
        @jax.jit
        def run(tparams, carry, seg_target):
            batch = carry[0].shape[0]
            bidx = jnp.arange(batch, dtype=jnp.int32)

            def cond(carry):
                return jnp.any(carry[0] < seg_target)

            def body(carry):
                (n_out, out, cur, y, lt, pc, t_cache, d_cache, rng_key,
                 stats) = carry
                logits, t_cache = decode_block(target, tparams,
                                               cur[:, None], t_cache,
                                               lengths=lt)
                if sampling:
                    rng_key, kk = jax.random.split(rng_key)
                    nxt = jax.random.categorical(
                        kk, logits[:, 0] / temperature,
                        axis=-1).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(logits[:, 0],
                                     axis=-1).astype(jnp.int32)
                out = out.at[bidx, jnp.clip(n_out, 0, cap - 1)].set(nxt)
                stats = stats + jnp.stack(
                    [jnp.ones((), jnp.int32), jnp.zeros((), jnp.int32),
                     jnp.zeros((), jnp.int32)])
                return (n_out + 1, out, nxt, y, lt + 1, pc, t_cache,
                        d_cache, rng_key, stats)

            return jax.lax.while_loop(cond, body, carry)

        return run

    return _cached_runner(key_tuple, build)


def _spec_catchup_runner(draft: Transformer, gap: int, cache_dtype: str):
    """Advance the DRAFT cache over ``gap`` committed tokens the target
    decoded alone (the greedy calibration probe leaves d_cache/pc/y
    untouched).  The spec round's own catch-up block only rewrites the
    last two positions, so without this a k>0 finish segment after the
    greedy probe would condition the draft on a prefix with a
    ``gap``-token hole.  Feeds the committed tokens at sequence positions
    pc-1 .. lt-2 (out columns n_out-gap-2 ..) through one ragged
    decode_block, then restores the segment invariant: pc = lt, y = the
    token at position lt-1."""
    key = (_model_key(draft), "spec_catchup", gap, cache_dtype)

    def build():
        @jax.jit
        def run(dparams, carry):
            (n_out, out, cur, y, lt, pc, t_cache, d_cache, rng_key,
             stats) = carry
            batch = out.shape[0]
            bidx = jnp.arange(batch, dtype=jnp.int32)[:, None]
            cols = ((n_out - gap - 2)[:, None]
                    + jnp.arange(gap, dtype=jnp.int32)[None, :])
            block = out[bidx, jnp.clip(cols, 0, out.shape[1] - 1)]
            _, d_cache = decode_block(draft, dparams, block, d_cache,
                                      lengths=pc - 1)
            y_new = out[jnp.arange(batch, dtype=jnp.int32),
                        jnp.clip(n_out - 2, 0, out.shape[1] - 1)]
            return (n_out, out, cur, y_new, lt, lt, t_cache, d_cache,
                    rng_key, stats)

        return run

    return _cached_runner(key, build)


# Calibrated depths memoized per (target, draft, sampling, cache) pair:
# the first adaptive call pays a segmented calibration run; every later
# call jumps straight to the winning FUSED program (whole-loop spec at
# k*, or plain generate when speculation cannot pay) — steady-state
# adaptive throughput equals the best fixed configuration by
# construction.  Keys use _model_key (the never-reused cache_token, not a
# recyclable id()).  Params are assumed fixed per model object (true for
# serving and benching); retraining under the same object must call
# :func:`clear_depth_memo`, since the calibrated depth is a property of
# the PARAMS (target/draft agreement), not the architecture.  Bounded
# LRU + lock, same protocol as _RUNNERS.
_DEPTH_MEMO: "OrderedDict[tuple, int]" = OrderedDict()
_DEPTH_MEMO_MAX = 64
_DEPTH_MEMO_LOCK = threading.Lock()


def clear_depth_memo(model=None) -> int:
    """Invalidate memoized calibrated draft depths — all of them, or only
    the entries involving ``model`` (as target OR draft).  Returns the
    number of entries dropped.  Call after swapping params under a model
    object you keep reusing (e.g. reloading a checkpoint mid-process):
    the next adaptive call re-calibrates against the new params."""
    with _DEPTH_MEMO_LOCK:
        if model is None:
            n = len(_DEPTH_MEMO)
            _DEPTH_MEMO.clear()
            return n
        mkey = _model_key(model)
        stale = [k for k in _DEPTH_MEMO if mkey in k[:2]]
        for k in stale:
            del _DEPTH_MEMO[k]
        return len(stale)


def _depth_memo_get(key: tuple) -> int | None:
    with _DEPTH_MEMO_LOCK:
        k = _DEPTH_MEMO.get(key)
        if k is not None:
            _DEPTH_MEMO.move_to_end(key)
        return k


def _depth_memo_put(key: tuple, k: int) -> None:
    with _DEPTH_MEMO_LOCK:
        _DEPTH_MEMO[key] = k
        _DEPTH_MEMO.move_to_end(key)
        while len(_DEPTH_MEMO) > _DEPTH_MEMO_MAX:
            _DEPTH_MEMO.popitem(last=False)


def _speculative_adaptive(target, tparams, draft, dparams, prompt,
                          max_new_tokens: int, k_max: int,
                          temperature: float, seed: int, cache_dtype: str,
                          cost_ratio: float,
                          calibration: str = "measured"
                          ) -> tuple[Array, dict]:
    """Adaptive-depth speculative decoding (see
    :func:`speculative_generate_batched` ``adaptive=True``).

    The generation runs as a handful of on-device SEGMENTS of the
    whole-loop decoder (:func:`_spec_segment_runner` — carry threaded
    through, one compiled program per depth), and between segments the
    controller re-picks the depth k via :func:`optimal_draft_depth`:
    invert the segment's accept fraction to per-proposal agreement p,
    then argmax expected-tokens/round-cost over 1..k_max with the
    caller-measured draft/target ``cost_ratio``.  Fixed k=4 at accept
    0.36 measured 0.76x vs greedy (round 4): this controller lands on
    the profitable depth instead, at ~4 host syncs per generation.
    Token-exact for greedy at ANY depth sequence."""
    sampling = temperature > 0.0
    if calibration not in ("measured", "model"):
        raise ValueError(f"calibration must be 'measured' or 'model', "
                         f"got {calibration!r}")
    memo_key = (_model_key(target), _model_key(draft), k_max,
                temperature, cache_dtype, cost_ratio, calibration)
    k_known = _depth_memo_get(memo_key)
    if k_known == 0:
        # calibration concluded speculation cannot pay: steady state IS
        # plain fused decoding (token-exact for greedy; for temperature
        # sampling the speculative path preserves the same distribution)
        out = generate(target, tparams, prompt, max_new_tokens,
                       temperature=temperature, rng=seed,
                       cache_dtype=cache_dtype)
        return np.asarray(out), {
            "verify_calls": max_new_tokens,
            "draft_accept_rate": 0.0,
            "tokens_per_target_forward": 1.0,
            "draft_depth": 0, "draft_depths": ["memo"],
        }
    if k_known is not None:
        # steady state at the calibrated depth: one full-length compiled
        # segment (no calibration boundaries, no extra host syncs)
        out, stats = _run_fixed_spec(
            target, tparams, draft, dparams, prompt, max_new_tokens,
            k_known, temperature, seed, cache_dtype)
        stats["draft_depth"] = k_known
        stats["draft_depths"] = ["memo"]
        return out, stats

    # ---- first call for this pair: MEASURED calibration.  Two timed
    # probes on this host — a spec segment at k0 and a greedy segment —
    # decide empirically (wall-clock tokens/sec), with the analytic model
    # only extrapolating the spec rate across depths.  Each probe runs
    # twice from the same carry (pure function): the first run absorbs
    # compilation, the second is the measurement.
    import time as _time

    prompt = jnp.asarray(prompt, jnp.int32)
    batch, s = prompt.shape
    cap = max_new_tokens + k_max + 1
    max_len = s + cap + k_max + 2
    carry = _init_spec_carry(target, tparams, draft, dparams, prompt,
                             cap, max_len, float(temperature), seed,
                             cache_dtype)
    k0 = min(2, k_max)
    seg = max(8, min(24, max_new_tokens // 4))
    t1 = min(max_new_tokens, seg)
    t2 = min(max_new_tokens, 3 * seg)
    spec_runner = _spec_segment_runner(target, draft, cap,
                                       max_new_tokens, k0,
                                       float(temperature), cache_dtype)
    greedy_runner = _greedy_segment_runner(target, cap, max_new_tokens,
                                           float(temperature),
                                           cache_dtype)

    def timed(runner, args, carry, target_n):
        tgt = jnp.asarray(target_n, jnp.int32)
        warm = runner(*args, carry, tgt)
        np.asarray(warm[0])                     # compile + drain
        t0 = _time.perf_counter()
        res = runner(*args, carry, tgt)
        np.asarray(res[0])
        return res, _time.perf_counter() - t0

    tokens_before = int(np.asarray(carry[0], np.int64).sum())
    carry, dt_spec = timed(spec_runner, (tparams, dparams), carry, t1)
    stats1 = np.asarray(carry[9], np.int64)
    spec_tokens = int(np.asarray(carry[0], np.int64).sum()) - tokens_before
    rate_spec = spec_tokens / max(dt_spec, 1e-9)
    frac = float(stats1[1]) / max(1, int(stats1[2]) * k0)
    proposed_total = int(stats1[2]) * k0
    depths: list[int] = [k0]

    p = _invert_accept_fraction(frac, k0)
    rate_greedy = float("nan")
    if calibration == "measured":
        # greedy probe, then extrapolate the measured spec rate across
        # depths with the model's RELATIVE scores and compare measured
        # against measured
        tokens_before = int(np.asarray(carry[0], np.int64).sum())
        carry, dt_greedy = timed(greedy_runner, (tparams,), carry, t2)
        greedy_tokens = (int(np.asarray(carry[0], np.int64).sum())
                         - tokens_before)
        rate_greedy = greedy_tokens / max(dt_greedy, 1e-9)
        depths.append(0)

        def score(j):
            expect = (j + 1.0 if p >= 1.0
                      else (1.0 - p ** (j + 1)) / (1.0 - p))
            return expect / (0.25 + 1.0 + cost_ratio * j)

        best_j = max(range(1, max(1, k_max) + 1), key=score)
        est_best = rate_spec * score(best_j) / score(k0)
        k = best_j if est_best > rate_greedy * 1.02 else 0
    else:
        # "model": deterministic, timing-free decision (tests; hosts
        # where two short probes cannot be timed meaningfully)
        k = optimal_draft_depth(frac, k0, k_max, cost_ratio,
                                allow_disable=True)
    _depth_memo_put(memo_key, k)

    # ---- finish the remaining tokens at the decided configuration
    if k == 0:
        carry = greedy_runner(tparams, carry,
                              jnp.asarray(max_new_tokens, jnp.int32))
        depths.append(0)
    else:
        gap = int(np.asarray(carry[4])[0] - np.asarray(carry[5])[0])
        if gap > 0:
            # measured calibration ran a greedy probe: catch the draft up
            # over the probe's committed tokens before speculating again
            carry = _spec_catchup_runner(draft, gap, cache_dtype)(
                dparams, carry)
        runner = (_spec_segment_runner(target, draft, cap,
                                       max_new_tokens, k,
                                       float(temperature), cache_dtype)
                  if k != k0 else spec_runner)
        pre = np.asarray(carry[9], np.int64)
        carry = runner(tparams, dparams, carry,
                       jnp.asarray(max_new_tokens, jnp.int32))
        post = np.asarray(carry[9], np.int64)
        proposed_total += int(post[2] - pre[2]) * k
        depths.append(k)
    final = np.asarray(carry[9], np.int64)
    verifies, accepted = int(final[0]), int(final[1])
    tokens = np.asarray(carry[1])[:, :max_new_tokens]
    return tokens, {
        "verify_calls": verifies,
        "draft_accept_rate": accepted / max(1, proposed_total),
        "tokens_per_target_forward": tokens.size / max(
            1, batch * (verifies + 1)),
        "draft_depth": k,            # depth the controller settled on
        "draft_depths": depths,      # [probe_k, 0(greedy probe), chosen]
        "calibration": {"rate_spec": rate_spec,
                        "rate_greedy": rate_greedy, "p": p},
    }


def _run_fixed_spec(target, tparams, draft, dparams, prompt,
                    max_new_tokens: int, k: int, temperature: float,
                    seed: int, cache_dtype: str) -> tuple[Array, dict]:
    """One fixed-depth run (shared by the non-adaptive path and the
    adaptive steady state): init the carry, run ONE full-length segment
    of the compiled while_loop, convert stats."""
    prompt = jnp.asarray(prompt, jnp.int32)
    batch, s = prompt.shape
    cap = max_new_tokens + k + 1
    max_len = s + cap + k + 2
    carry = _init_spec_carry(target, tparams, draft, dparams, prompt,
                             cap, max_len, float(temperature), seed,
                             cache_dtype)
    runner = _spec_segment_runner(target, draft, cap, max_new_tokens, k,
                                  float(temperature), cache_dtype)
    carry = runner(tparams, dparams, carry,
                   jnp.asarray(max_new_tokens, jnp.int32))
    verifies, accepted, active_rows = (
        int(x) for x in np.asarray(carry[9]))
    return np.asarray(carry[1])[:, :max_new_tokens], {
        "verify_calls": verifies,
        "draft_accept_rate": accepted / max(1, active_rows * k),
        # +1: the prefill forward produced each row's first token
        "tokens_per_target_forward": batch * max_new_tokens / max(
            1, batch * (verifies + 1)),
    }


def speculative_generate_batched(
        target: Transformer, target_params, draft: Transformer,
        draft_params, prompt: Array, max_new_tokens: int, *,
        draft_len: int = 4, temperature: float = 0.0,
        seed: int = 0, cache_dtype: str = "native",
        adaptive: bool = False, draft_cost_ratio: float = 0.5,
        calibration: str = "measured") -> tuple[Array, dict]:
    """Batched speculative decoding with the WHOLE loop on device.

    Unlike :func:`speculative_generate` (batch-1, host accept loop — kept
    as the readable reference implementation its tests cross-check), this
    runs prefill + a ``lax.while_loop`` of draft-propose / verify /
    vectorized accept-or-resample inside one jit: no per-token host
    round-trips, so decode throughput is device-bound — the serving path.

    Batch > 1 works because rows accept DIFFERENT numbers of draft tokens
    per round: each row's KV caches advance at their own rate via ragged
    ``decode_block`` (per-row lengths), committed tokens scatter into a
    per-row output frontier, and rows that reach ``max_new_tokens`` keep
    verifying into slack slots until the slowest row finishes (their
    stats are masked out).

    ``temperature=0`` is greedy and token-exact vs target-alone greedy
    decoding (tested per row); ``temperature>0`` applies the
    Leviathan/Chen rejection rule vectorized on device, preserving the
    target's sampling distribution exactly (tested empirically).

    ``cache_dtype="int8"`` stores BOTH models' KV caches quantized
    (QuantKVCache; the ragged per-row scatter paths quantize on write) —
    K/V depend only on (token, position, params), so block-verify and
    single-step writes quantize identically and the greedy token-exactness
    vs an int8-cache target-alone decode is preserved (tested).

    Returns (tokens [B, max_new_tokens], stats).
    """
    if target.config.vocab != draft.config.vocab:
        raise ValueError(
            f"vocab mismatch: target {target.config.vocab} vs draft "
            f"{draft.config.vocab}")
    if draft_len < 1:
        raise ValueError("draft_len must be >= 1")
    prompt_len = int(np.asarray(prompt).shape[1])
    # + draft_len: the last verify round may write a full draft block
    # before the loop notices every row is done (active lanes only —
    # finished rows clip into discarded slack)
    check_position_budget(target, prompt_len, max_new_tokens + draft_len)
    check_position_budget(draft, prompt_len, max_new_tokens + draft_len)
    if adaptive:
        # draft_len becomes the depth CAP; the controller re-picks k
        # between on-device segments from the measured accept rate and
        # the caller's draft/target cost ratio (_speculative_adaptive)
        return _speculative_adaptive(
            target, target_params, draft, draft_params, prompt,
            max_new_tokens, draft_len, float(temperature), seed,
            cache_dtype, float(draft_cost_ratio), calibration)
    return _run_fixed_spec(target, target_params, draft, draft_params,
                           prompt, max_new_tokens, draft_len,
                           float(temperature), seed, cache_dtype)


def generate(model: Transformer, params: Mapping[str, Array],
             prompt: Array, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
             rng: Array | int = 0, cache_dtype: str = "native") -> Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [B, S] int32.
    Returns [B, max_new_tokens].  Prefill and the whole decode scan are
    jitted with static shapes; the compiled runner is cached per
    (model, max_new_tokens, temperature, top_k, top_p, cache_dtype), so
    repeated calls with the same shapes do not retrace.
    ``cache_dtype="int8"`` stores the KV cache quantized (QuantKVCache) —
    composes with a models/quant.py weight-quantized ``params`` for the
    fully int8-bandwidth serving path."""
    check_position_budget(model, int(prompt.shape[1]), max_new_tokens)
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    return _runner(model, max_new_tokens, temperature, top_k, top_p,
                   cache_dtype)(params, prompt, rng)
