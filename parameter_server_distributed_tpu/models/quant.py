"""Weight-only int8 quantization for the serving path.

Decode is weight-bandwidth-bound: every generated token streams the full
parameter set from HBM while the MXU sits mostly idle, so halving the
weight bytes (bf16 -> int8) is worth up to 2x tokens/s before any compute
speedup.  This module quantizes a trained parameter store offline
(:func:`quantize_params`) into :class:`QTensor` leaves — symmetric int8
with a per-output-channel f32 scale — that flow through the existing
model code transparently:

- ``QTensor`` is a registered JAX pytree, so quantized stores pass through
  ``jit``/``lax.scan`` (the ``scan_layers`` stacked layout) unchanged, and
  ``layer_view``'s per-layer ``value[layer]`` slicing works via
  ``__getitem__``.
- The transformer's matmul sites call :func:`wdot`, which contracts
  activations against the int8 matrix (the int8->bf16 convert fuses into
  the matmul, so only int8 bytes leave HBM) and applies the channel scale
  to the product.

Scope: the dense transformer serving path (attention + MLP + LM head).
Embeddings stay bf16 (a gather, not a matmul: int8 would add a dequant
pass without saving matmul bandwidth), norms/biases stay f32, and MoE
expert banks are out of scope for now (their einsum paths live in
models/moe.py; the router is a tiny f32 matmul either way).  Training on
quantized weights is deliberately unsupported — this is a post-training
serving transform.

The reference has no quantized path (its tensors are ``repeated float``
f32 end to end — reference proto/parameter_server.proto:19-24); this is
TPU-native added capability, measured by ``PSDT_BENCH_MODE=generate``
``PSDT_BENCH_QUANT=int8`` as an A/B against the bf16 decoder.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp

Array = Any

# Matmul-weight key suffixes eligible for quantization, in both layouts
# (unrolled "layer<i>/attn/wq" and scan_layers' stacked "blocks/attn/wq").
_WEIGHT_SUFFIXES = ("/attn/wq", "/attn/wk", "/attn/wv", "/attn/wo",
                    "/mlp/w1", "/mlp/w2", "/mlp/w3")


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Symmetric weight-only int8 matrix.

    ``q``: int8, shape [..., d_in, d_out] (leading axes = stacked layers).
    ``scale``: f32, shape [..., d_out] — per-output-channel absmax/127 over
    the contracted (d_in) axis, so dequant is ``q * scale`` broadcast over
    d_in and a matmul against q can apply the scale to its product instead.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q: Array, scale: Array):
        self.q = q
        self.scale = scale

    @property
    def shape(self) -> tuple:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    def __getitem__(self, idx) -> "QTensor":
        # layer_view slices stacked [L, ...] params per layer; slice the
        # scale with the same leading index.
        return QTensor(self.q[idx], self.scale[idx])

    def dequant(self, dtype=jnp.float32) -> Array:
        return (self.q.astype(dtype)
                * self.scale[..., None, :].astype(dtype))

    # --- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self) -> str:
        return f"QTensor(int8 {tuple(self.q.shape)})"


def quantize(w: Array) -> QTensor:
    """Symmetric per-output-channel int8 quantization of a weight matrix
    [..., d_in, d_out] (absmax over the contracted d_in axis)."""
    w32 = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2)              # [..., d_out]
    scale = absmax / 127.0
    scale = jnp.where(scale == 0.0, 1.0, scale)          # all-zero channel
    q = jnp.round(w32 / scale[..., None, :])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return QTensor(q, scale.astype(jnp.float32))


def wdot(x: Array, w: Array | QTensor, *,
         preferred_element_type=jnp.float32) -> Array:
    """``jnp.dot`` that understands QTensor weights: contracts against the
    int8 matrix (the convert-to-activation-dtype fuses into the matmul, so
    HBM streams int8 bytes) and scales the f32 product per channel."""
    if isinstance(w, QTensor):
        y = jnp.dot(x, w.q.astype(x.dtype),
                    preferred_element_type=preferred_element_type)
        return y * w.scale.astype(y.dtype)
    return jnp.dot(x, w, preferred_element_type=preferred_element_type)


def _eligible(name: str, value: Array) -> bool:
    if name == "lm_head/w":
        return True
    return (any(name.endswith(suffix) for suffix in _WEIGHT_SUFFIXES)
            and getattr(value, "ndim", 0) >= 2)


def quantize_params(params: Mapping[str, Array]) -> dict[str, Array]:
    """Quantize a trained store for serving: matmul weights (attention,
    MLP, LM head — both layer layouts) become QTensor; embeddings, norm
    scales, and MoE tensors pass through unchanged."""
    return {name: quantize(value) if _eligible(name, value) else value
            for name, value in params.items()}


def store_bytes(params: Mapping[str, Array],
                unquantized_itemsize: int = 2) -> tuple[int, int]:
    """(bytes_as_is, bytes_had_nothing_been_quantized) for a store that may
    hold QTensor leaves — the decode-bandwidth story in one pair of
    numbers.  ``unquantized_itemsize`` is what a QTensor's weight would
    have weighed per element unquantized (2 = bf16 serving weights)."""
    as_is = dense = 0
    for value in params.values():
        if isinstance(value, QTensor):
            nq = int(value.q.size)
            as_is += nq + int(value.scale.size) * 4
            dense += nq * unquantized_itemsize
        else:
            b = int(value.size) * value.dtype.itemsize
            as_is += b
            dense += b
    return as_is, dense
