"""Token-level radix tree over cached K/V rows (ISSUE 20).

The PR 14 prompt cache keyed on the EXACT full prompt and scanned it
linearly for the longest cached whole-prompt prefix — so the motivating
fleet workload (one system prompt shared by thousands of requests with
different suffixes) re-prefilled the shared tokens on every miss whose
prefix was cached only as the interior of some longer prompt.  This
module is the replacement index: a radix (compressed trie) over token
sequences where

- **lookup** walks edges in O(prompt length) and matches PARTIALLY into
  an edge, so ANY shared prefix anywhere in the cache — not just a
  complete previously-admitted prompt — seeds the suffix-only extension
  forward (serving._extend_runner);
- **insertion** splits an edge at the divergence point, so future
  requests share at the deepest common token;
- **eviction** is byte-accounted LRU over tree nodes (the
  ``PSDT_PREFIX_CACHE_BYTES`` budget replaces the PR 14 entry count),
  with a touch bumping the WHOLE ancestor path — a hot shared prefix is
  never evicted out from under its live descendants;
- every tree path is summarised into a compact **fingerprint** (chained
  CRC32 at block boundaries) the decode fleet heartbeats to the
  coordinator, so the router can score cached-prefix overlap.

Deliberately jax-free: rows are opaque handles (:class:`RowRef`) whose
byte size the caller computes, and :mod:`..fleet.router` imports the
fingerprint helpers without pulling the model stack.

Why handle INHERITANCE is sound: a cached row's K/V at positions
``[:L]`` is exactly the prefill of its first ``L`` tokens (causal
attention — later positions never influence earlier K/V), so a node
created by splitting an edge at depth ``L`` simply shares its
descendant's row handle instead of copying device memory; the extension
forward masks positions ``>= L`` (ragged decode_block) and overwrites
``[L:L+suffix]``, the same argument that makes prefill pad positions
harmless.  One physical row can therefore back several nodes; byte
accounting is per unique handle via refcounts.

Thread model: mutation is single-threaded (the decode loop is the only
thread that touches a DecodeServer); cross-thread readers (the
heartbeat loop) read only :attr:`PrefixTree.fingerprint`, an immutable
``bytes`` snapshot rebuilt after every mutation and swapped in with one
GIL-atomic store.
"""

from __future__ import annotations

import os
import zlib
from typing import Any, Iterator

__all__ = [
    "RowRef", "RadixNode", "PrefixTree", "fp_block", "fp_max",
    "block_hashes", "pack_fp", "unpack_fp", "overlap_blocks",
]


def fp_block() -> int:
    """Fingerprint block size in tokens: a path hash is emitted every
    this-many tokens.  Smaller = finer overlap resolution, more hashes."""
    return max(1, int(os.environ.get("PSDT_PREFIX_FP_BLOCK", "16")))


def fp_max() -> int:
    """Cap on fingerprint hashes heartbeated per server (4 bytes each).
    Shallow (shared-system-prompt) blocks are kept first."""
    return max(1, int(os.environ.get("PSDT_PREFIX_FP_MAX", "64")))


def _crc_tokens(tokens, crc: int = 0) -> int:
    """Fold tokens into a running CRC32.  Position-chained: the hash at
    block boundary ``k`` commits to ALL tokens before it, so a match
    implies the whole prefix matches (modulo CRC collisions — fine for a
    routing score, never for correctness)."""
    for t in tokens:
        crc = zlib.crc32(int(t).to_bytes(4, "little", signed=True), crc)
    return crc & 0xFFFFFFFF


def block_hashes(tokens, block: int | None = None) -> list[int]:
    """Chained CRC32 at every ``block``-token boundary of ``tokens`` —
    the router applies this to an incoming prompt and counts how many
    leading boundary hashes a backend's fingerprint holds."""
    block = block or fp_block()
    out: list[int] = []
    crc = 0
    for i, t in enumerate(tokens):
        crc = zlib.crc32(int(t).to_bytes(4, "little", signed=True), crc)
        if (i + 1) % block == 0:
            out.append(crc & 0xFFFFFFFF)
    return out


def pack_fp(hashes) -> bytes:
    """Pack boundary hashes into the wire form (4 LE bytes each)."""
    return b"".join(int(h).to_bytes(4, "little") for h in hashes)


def unpack_fp(blob: bytes) -> frozenset:
    """Wire form back to a membership set (truncated tail bytes from a
    foreign writer are ignored rather than misparsed)."""
    n = len(blob) // 4
    return frozenset(int.from_bytes(blob[4 * i:4 * i + 4], "little")
                     for i in range(n))


def overlap_blocks(prompt_hashes, fp: frozenset) -> int:
    """How many LEADING block boundaries of a prompt a backend already
    holds.  Consecutive-from-the-start because the chained CRC makes a
    boundary hash commit to everything before it: the first missing
    boundary ends the reusable prefix."""
    n = 0
    for h in prompt_hashes:
        if h not in fp:
            break
        n += 1
    return n


class RowRef:
    """One physical cached row (opaque device payload) shared by one or
    more tree nodes; ``nbytes`` is charged to the tree's budget once,
    while ``refs`` nodes point at it."""

    __slots__ = ("row", "nbytes", "refs")

    def __init__(self, row: Any, nbytes: int):
        self.row = row
        self.nbytes = int(nbytes)
        self.refs = 0


class RadixNode:
    """One tree node: ``edge`` tokens from the parent, a target-row
    handle whose first ``depth`` positions are this path's prefill K/V
    (see module docstring on inheritance), optionally a draft-model
    handle (speculative admissions) and the final-position logits
    (``last`` — only nodes admitted as COMPLETE prompts; split-created
    interior nodes have ``last is None`` and exact matches on them
    extend one token instead of replaying)."""

    __slots__ = ("edge", "parent", "children", "handle", "dhandle",
                 "last", "depth", "tick")

    def __init__(self, edge: tuple, parent: "RadixNode | None"):
        self.edge = edge
        self.parent = parent
        self.children: dict[int, RadixNode] = {}
        self.handle: RowRef | None = None
        self.dhandle: RowRef | None = None
        self.last: Any = None
        self.depth = (0 if parent is None else parent.depth) + len(edge)
        self.tick = 0


class PrefixTree:
    """See module docstring.  ``budget_bytes`` bounds the summed size of
    UNIQUE row handles; inserts over budget evict least-recently-touched
    leaves (path-compressing parents left with a single child and no
    complete-prompt payload)."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self.root = RadixNode((), None)
        self.bytes = 0          # unique handle bytes currently pinned
        self._tick = 0
        self.nodes = 0          # nodes excluding root
        self.splits = 0         # edge splits performed (obs)
        self.evictions = 0      # nodes evicted (obs)
        self.fingerprint = b""  # immutable snapshot, cross-thread read

    # ------------------------------------------------------------ refcounts
    def _incref(self, ref: RowRef | None) -> None:
        if ref is None:
            return
        if ref.refs == 0:
            self.bytes += ref.nbytes
        ref.refs += 1

    def _decref(self, ref: RowRef | None) -> None:
        if ref is None:
            return
        ref.refs -= 1
        if ref.refs == 0:
            self.bytes -= ref.nbytes

    # -------------------------------------------------------------- lookup
    def lookup(self, tokens) -> tuple[RadixNode, int, bool]:
        """Walk ``tokens`` as deep as the tree matches.  Returns
        ``(node, matched, partial)``: ``matched`` tokens of the prompt
        are covered, and ``node`` is the node whose row handle covers
        them — the exactly-reached node (``partial=False``) or, when the
        walk ended ``matched - node.parent.depth`` tokens INTO an edge,
        the partially-entered child (``partial=True``; its handle's
        first ``matched`` positions are still the prefix K/V, which is
        the whole point of a token-level tree)."""
        node = self.root
        matched = 0
        n = len(tokens)
        while matched < n:
            child = node.children.get(int(tokens[matched]))
            if child is None:
                return node, matched, False
            edge = child.edge
            limit = min(len(edge), n - matched)
            j = 0
            while j < limit and edge[j] == int(tokens[matched + j]):
                j += 1
            matched += j
            if j < len(edge):
                return child, matched, True
            node = child
        return node, matched, False

    def touch(self, node: RadixNode) -> None:
        """LRU-touch ``node`` AND every ancestor: a hit through a deep
        descendant is evidence the whole shared path is hot (the PR 14
        cache touched only the one source entry — ISSUE 20 satellite)."""
        self._tick += 1
        while node is not None and node is not self.root:
            node.tick = self._tick
            node = node.parent

    # -------------------------------------------------------------- insert
    def insert(self, tokens, last: Any, handle: RowRef,
               dhandle: RowRef | None = None) -> RadixNode:
        """Admit a COMPLETE prompt: split the partially-matched edge at
        the divergence point (the split node inherits the descendant's
        row handles — no device copy) and attach the remainder as a new
        leaf owning ``handle``/``dhandle``.  Re-admitting an existing
        path fills in its ``last``/missing handles in place.  Caller
        evicts afterwards (:meth:`evict_over_budget`) so the freshly
        admitted row participates in — and by recency survives — the
        LRU pass."""
        tokens = tuple(int(t) for t in tokens)
        node, matched, partial = self.lookup(tokens)
        if partial:
            node = self._split(node, matched - node.parent.depth)
        if matched == len(tokens):
            # existing path re-admitted as a complete prompt (an interior
            # split node, or a k==0-era node gaining its draft row)
            node.last = last
            if node.handle is None:
                self._incref(handle)
                node.handle = handle
            if node.dhandle is None and dhandle is not None:
                self._incref(dhandle)
                node.dhandle = dhandle
        else:
            leaf = RadixNode(tokens[matched:], node)
            leaf.last = last
            self._incref(handle)
            leaf.handle = handle
            if dhandle is not None:
                self._incref(dhandle)
                leaf.dhandle = dhandle
            node.children[leaf.edge[0]] = leaf
            self.nodes += 1
            node = leaf
        self.touch(node)
        self._refingerprint()
        return node

    def _split(self, child: RadixNode, at: int) -> RadixNode:
        """Split ``child``'s edge ``at`` tokens in: the new interior
        node takes the edge head and SHARES the child's row handles
        (first ``depth`` positions of any descendant row are this
        prefix's K/V — causal attention, module docstring)."""
        parent = child.parent
        mid = RadixNode(child.edge[:at], parent)
        self._incref(child.handle)
        mid.handle = child.handle
        self._incref(child.dhandle)
        mid.dhandle = child.dhandle
        mid.tick = child.tick
        parent.children[mid.edge[0]] = mid
        child.edge = child.edge[at:]
        child.parent = mid
        mid.children[child.edge[0]] = child
        self.nodes += 1
        self.splits += 1
        return mid

    # ------------------------------------------------------------ eviction
    def evict_over_budget(self) -> int:
        """Pop least-recently-touched LEAVES until the unique-handle
        byte total fits the budget; returns nodes evicted.  Removing a
        leaf may leave its parent with one child and no complete-prompt
        payload — such parents merge back into their child (path
        compression), shedding their handle references."""
        evicted = 0
        while self.bytes > self.budget_bytes and self.nodes:
            leaf = min(
                (n for n in self._walk() if not n.children),
                key=lambda n: n.tick)
            self._remove_leaf(leaf)
            evicted += 1
        if evicted:
            self.evictions += evicted
            self._refingerprint()
        return evicted

    def _remove_leaf(self, leaf: RadixNode) -> None:
        parent = leaf.parent
        del parent.children[leaf.edge[0]]
        self._decref(leaf.handle)
        self._decref(leaf.dhandle)
        leaf.handle = leaf.dhandle = None
        self.nodes -= 1
        # path-compress: a split-created interior parent that now has a
        # single child and was never admitted as a complete prompt only
        # duplicates its child's handle — merge them
        if (parent is not self.root and parent.last is None
                and len(parent.children) == 1):
            (only,) = parent.children.values()
            only.edge = parent.edge + only.edge
            only.parent = parent.parent
            parent.parent.children[only.edge[0]] = only
            self._decref(parent.handle)
            self._decref(parent.dhandle)
            parent.handle = parent.dhandle = None
            parent.children.clear()
            self.nodes -= 1

    def clear(self) -> None:
        """Drop everything (weight swap: every cached row is stale)."""
        self.root = RadixNode((), None)
        self.bytes = 0
        self.nodes = 0
        self.fingerprint = b""

    # --------------------------------------------------------- fingerprint
    def _walk(self) -> Iterator[RadixNode]:
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _refingerprint(self) -> None:
        """Rebuild the fingerprint snapshot: chained CRC32 of every
        root-to-position path at block boundaries, breadth-first so the
        shallow (shared-system-prompt) blocks survive the cap."""
        block = fp_block()
        cap = fp_max()
        hashes: list[int] = []
        seen: set[int] = set()
        # BFS over (node, crc at parent boundary, tokens into parent)
        queue: list[tuple[RadixNode, int, int]] = [
            (c, 0, 0) for c in self.root.children.values()]
        while queue and len(hashes) < cap:
            nxt: list[tuple[RadixNode, int, int]] = []
            for node, crc, pos in queue:
                # pos/crc are at the node's parent boundary; fold this
                # edge, emitting at block boundaries
                for t in node.edge:
                    crc = zlib.crc32(
                        int(t).to_bytes(4, "little", signed=True), crc)
                    pos += 1
                    if pos % block == 0:
                        h = crc & 0xFFFFFFFF
                        if h not in seen:
                            seen.add(h)
                            hashes.append(h)
                            if len(hashes) >= cap:
                                break
                else:
                    nxt.extend((c, crc, pos)
                               for c in node.children.values())
                    continue
                break
            queue = nxt
        self.fingerprint = pack_fp(hashes)
