"""Model registry: name -> (model factory, data factory).

Gives every CLI/benchmark entry point a single switch for the BASELINE
configs: MNIST MLP (config 1), CIFAR ResNet-18 (config 2), 1B MLP
(configs 3/5), ResNet-50 (config 4), plus the transformer LM flagship.
"""

from __future__ import annotations

from typing import Callable, Iterator

from ..data.synthetic import (synthetic_image_batches, synthetic_mnist,
                              synthetic_tokens)
from .mlp import MLP, billion_param_mlp, mnist_mlp
from .resnet import resnet18, resnet50
from .transformer import moe_lm, small_lm


def _mnist_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_mnist(seed=seed).batch_stream(batch_size, seed=seed)


def _cifar_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_image_batches(batch_size, image_size=32, seed=seed)


def _imagenet_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_image_batches(batch_size, image_size=224,
                                   num_classes=1000, seed=seed)


def _lm_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_tokens(batch_size, seq_len=256, vocab=1024, seed=seed)


def _mlp_1b_batches(batch_size: int, seed: int) -> Iterator:
    import numpy as np
    rng = np.random.default_rng(seed)
    hidden = 16384
    while True:
        x = rng.standard_normal((batch_size, hidden)).astype(np.float32)
        y = rng.integers(0, hidden, batch_size).astype(np.int32)
        yield x, y


REGISTRY: dict[str, tuple[Callable, Callable[[int, int], Iterator]]] = {
    "mnist_mlp": (mnist_mlp, _mnist_batches),
    "resnet18_cifar": (lambda: resnet18(num_classes=10), _cifar_batches),
    "resnet50_imagenet": (lambda: resnet50(num_classes=1000), _imagenet_batches),
    "small_lm": (lambda: small_lm(vocab=1024, seq=256), _lm_batches),
    "moe_lm": (lambda: moe_lm(vocab=1024, seq=256), _lm_batches),
    "mlp_1b": (billion_param_mlp, _mlp_1b_batches),
}


def get_model_and_batches(name: str, batch_size: int, seed: int = 0):
    if name not in REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(REGISTRY)}")
    model_fn, data_fn = REGISTRY[name]
    return model_fn(), data_fn(batch_size, seed)
