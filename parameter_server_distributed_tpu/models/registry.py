"""Model registry: name -> (model factory, data factory).

Gives every CLI/benchmark entry point a single switch for the BASELINE
configs: MNIST MLP (config 1), CIFAR ResNet-18 (config 2), 1B MLP
(configs 3/5), ResNet-50 (config 4), plus the transformer LM flagship.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Iterator

from ..data.synthetic import (synthetic_image_batches, synthetic_mnist,
                              synthetic_tokens)
from .mlp import MLP, billion_param_mlp, mnist_mlp
from .resnet import resnet18, resnet50
from .transformer import (llama_350m, lm_350m, moe_350m, moe_lm, small_lm,
                          switch_lm, tiny_lm)
from .vit import vit_s16, vit_tiny


# xy loaders: the registry seed varies the SAMPLING stream only — the
# generated dataset (the task) is fixed, like real MNIST.  Seeding the
# dataset itself would hand differently-seeded consumers (PS workers,
# --per-process-data hosts, the eval stream) unrelated tasks.
def _mnist_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_mnist(seed=0).batch_stream(batch_size, seed=seed)


def _cifar_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_image_batches(batch_size, image_size=32, seed=seed)


def _imagenet_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_image_batches(batch_size, image_size=224,
                                   num_classes=1000, seed=seed)


def _lm_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_tokens(batch_size, seq_len=256, vocab=1024, seed=seed)


def _lm_350m_batches(batch_size: int, seed: int) -> Iterator:
    return synthetic_tokens(batch_size, seq_len=1024, vocab=32000, seed=seed)


def _mlp_1b_batches(batch_size: int, seed: int) -> Iterator:
    import numpy as np
    rng = np.random.default_rng(seed)
    hidden = 16384
    while True:
        x = rng.standard_normal((batch_size, hidden)).astype(np.float32)
        y = rng.integers(0, hidden, batch_size).astype(np.int32)
        yield x, y


# name -> (model factory, synthetic data factory, file-data kind)
# file-data kind: "tokens" (memmap .bin shard, data/files.token_stream) or
# "xy" (npz with x/y arrays, data/files.npz_stream)
# Factories may accept dtype=/remat= keywords; get_model_and_batches passes
# only what each signature supports.
REGISTRY: dict[str, tuple[Callable, Callable[[int, int], Iterator], str]] = {
    "mnist_mlp": (mnist_mlp, _mnist_batches, "xy"),
    "resnet18_cifar": (partial(resnet18, num_classes=10),
                       _cifar_batches, "xy"),
    "resnet50_imagenet": (partial(resnet50, num_classes=1000),
                          _imagenet_batches, "xy"),
    "small_lm": (partial(small_lm, vocab=1024, seq=256),
                 _lm_batches, "tokens"),
    "tiny_lm": (partial(tiny_lm, vocab=1024, seq=256),
                _lm_batches, "tokens"),
    "small_lm4": (partial(small_lm, vocab=1024, seq=256, n_layers=4),
                  _lm_batches, "tokens"),
    "moe_lm": (partial(moe_lm, vocab=1024, seq=256),
               _lm_batches, "tokens"),
    "moe_lm_top2": (partial(moe_lm, vocab=1024, seq=256, top_k=2),
                    _lm_batches, "tokens"),
    "switch_lm": (partial(switch_lm, vocab=1024, seq=256),
                  _lm_batches, "tokens"),
    "mlp_1b": (billion_param_mlp, _mlp_1b_batches, "xy"),
    "lm_350m": (lm_350m, _lm_350m_batches, "tokens"),
    "lm_350m_gqa": (partial(lm_350m, kv_heads=4), _lm_350m_batches,
                    "tokens"),
    # head_dim-128 flagship: 8 heads x 128 — a full MXU tile per
    # attention matmul (the flash kernel's preferred shape)
    "lm_350m_hd128": (partial(lm_350m, n_heads=8), _lm_350m_batches,
                      "tokens"),
    # LLaMA-architecture flagship (SwiGLU + GQA): the shape from_hf_llama
    # conversions have, so its bench rows transfer to real checkpoints
    "llama_350m": (llama_350m, _lm_350m_batches, "tokens"),
    # flagship-scale sparse MoE: lm_350m's trunk, every 2nd FFN routed
    # over 8 experts (~350M active / ~1.07B total)
    "moe_350m": (moe_350m, _lm_350m_batches, "tokens"),
    # vision transformers (models/vit.py): CIFAR-scale and ImageNet-scale
    "vit_tiny_cifar": (partial(vit_tiny, num_classes=10, image_size=32),
                       _cifar_batches, "xy"),
    "vit_s16_imagenet": (partial(vit_s16, num_classes=1000,
                                 image_size=224),
                         _imagenet_batches, "xy"),
}

DTYPE_NAMES = {"f32": "float32", "float32": "float32",
               "bf16": "bfloat16", "bfloat16": "bfloat16"}


def resolve_dtype(name: str):
    """Flag string -> jnp dtype; single owner of the alias table and its
    error (cli/generate_main's --hf-gpt2 path reuses it)."""
    if name not in DTYPE_NAMES:
        raise ValueError(f"unknown dtype {name!r}; "
                         f"options {sorted(set(DTYPE_NAMES))}")
    import jax.numpy as jnp

    return getattr(jnp, DTYPE_NAMES[name])


def _model_kwargs(model_fn: Callable, name: str, dtype: str,
                  remat: bool | None, scan: bool | None = None,
                  seq_len: int = 0, remat_policy: str = "") -> dict:
    """The subset of {dtype, remat} this factory supports; error (rather
    than silently ignore) when the user asked for one it doesn't."""
    import inspect

    sig = inspect.signature(model_fn)
    has_var_kw = any(p.kind is p.VAR_KEYWORD for p in sig.parameters.values())
    kwargs: dict = {}
    if dtype:
        resolved = resolve_dtype(dtype)
        if not (has_var_kw or "dtype" in sig.parameters):
            raise ValueError(f"model {name!r} does not take a dtype")
        kwargs["dtype"] = resolved
    if remat is not None:
        if has_var_kw or "remat" in sig.parameters:
            kwargs["remat"] = remat
        elif remat:
            # asking for remat on a model that can't honor the memory
            # saving is an error; forcing it OFF on a model that never
            # remats is a no-op (lets --no-remat / PSDT_BENCH_REMAT=0
            # sweep across the whole registry)
            raise ValueError(f"model {name!r} does not support remat "
                             f"(transformer LMs only)")
    if scan is not None:
        if has_var_kw or "scan_layers" in sig.parameters:
            kwargs["scan_layers"] = scan
        elif scan:
            raise ValueError(f"model {name!r} does not support scan_layers "
                             f"(dense transformer LMs only)")
    if seq_len:
        if not (has_var_kw or "seq" in sig.parameters):
            raise ValueError(f"model {name!r} has no sequence length "
                             f"(transformer LMs only)")
        kwargs["seq"] = seq_len
    if remat_policy:
        if not (has_var_kw or "remat_policy" in sig.parameters):
            raise ValueError(f"model {name!r} does not support remat_policy "
                             f"(flagship transformer LMs only)")
        kwargs["remat_policy"] = remat_policy
    return kwargs


def get_model_and_batches(name: str, batch_size: int, seed: int = 0,
                          data_path: str = "", dtype: str = "",
                          remat: bool | None = None,
                          scan: bool | None = None,
                          seq_len: int = 0, remat_policy: str = ""):
    """Build (model, batch iterator).  ``data_path`` switches from the
    synthetic loaders to file-backed data (data/files.py), dispatched by
    the registry entry's declared file-data kind.  ``dtype`` ("f32"/"bf16"),
    ``remat``, and ``scan`` (lax.scan over stacked layers) forward to
    factories that support them; remat/scan are tri-state — None keeps the
    factory's default (e.g. lm_350m defaults remat on), True/False force
    it for factories that take the keyword.  ``seq_len`` overrides the
    sequence length for transformer LMs (long-context runs, e.g.
    lm_350m at 4096); the synthetic token stream follows the model."""
    if name not in REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {sorted(REGISTRY)}")
    model_fn, data_fn, file_kind = REGISTRY[name]
    model = model_fn(**_model_kwargs(model_fn, name, dtype, remat, scan,
                                     seq_len, remat_policy))
    if not data_path:
        if seq_len and file_kind == "tokens":
            # the factory's synthetic stream bakes in the default seq; at
            # an overridden length, stream crops matching the model
            from ..data.synthetic import synthetic_tokens
            return model, synthetic_tokens(
                batch_size, seq_len=model.config.max_seq,
                vocab=model.config.vocab, seed=seed)
        return model, data_fn(batch_size, seed)
    if file_kind == "tokens":
        batches = lm_batches(model, batch_size, seed=seed,
                             data_path=data_path)
    else:
        from ..data.files import npz_stream
        batches = npz_stream(data_path, batch_size, seed=seed)
    return model, batches


def lm_batches(model, batch_size: int, seed: int = 0, data_path: str = ""):
    """Token batches for an arbitrary transformer LM — the registry's
    "tokens" data branch exposed for models built OUTSIDE the registry
    (HF conversions, hand-constructed configs): file-backed data when
    ``data_path`` is set (.txt byte-tokenized via data/text.py, else a
    token memmap via data/files.py), synthetic (vocab, max_seq) crops
    otherwise."""
    if not data_path:
        from ..data.synthetic import synthetic_tokens
        return synthetic_tokens(batch_size, seq_len=model.config.max_seq,
                                vocab=model.config.vocab, seed=seed)
    if data_path.endswith(".txt"):
        # raw text corpus: byte-tokenize to a cached shard on first use;
        # the model's vocab must cover the byte tokenizer's 258 ids
        from ..data.text import ByteTokenizer, require_vocab, text_stream
        tok = ByteTokenizer()
        require_vocab(model.config.vocab, tok)
        return text_stream(data_path, batch_size,
                           seq_len=model.config.max_seq, seed=seed,
                           tokenizer=tok)
    from ..data.files import token_stream
    return token_stream(data_path, batch_size,
                        seq_len=model.config.max_seq, seed=seed,
                        vocab=model.config.vocab)
