"""ResNet family (ResNet-18 / ResNet-50) in plain JAX, NHWC.

Targets BASELINE configs 2 (CIFAR-10 ResNet-18 async-SGD) and 4 (sync
all-reduce ResNet-50).  The reference has no model layer (its gradients are
a 0.01 stub — reference: src/worker.cpp:316-329); these models give the
framework real conv workloads that map onto the MXU (convs lower to large
matmuls under XLA:TPU; float32 accumulation via preferred_element_type).

Design notes:
- Parameters are a flat named store (dict[str, Array]) like every model in
  this framework, so ResNets flow through the PS wire protocol, checkpoint
  codec, and ShardedTrainer unchanged.
- Normalization is batch-statistics normalization in train mode without
  running-average state (scale/bias are learned parameters).  This keeps
  the training step pure (no mutable batch_stats side-channel) — the right
  trade for a distributed-training framework whose benchmarks measure
  training; eval reuses batch stats.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _conv(x: Array, w: Array, stride: int = 1) -> Array:
    """NHWC x HWIO -> NHWC, SAME padding.

    Inputs are cast to the weight dtype (MXU compute precision — bf16 for
    ResNet-50).  XLA:TPU accumulates convs in f32 internally regardless of
    the storage dtype, and `_norm` lifts back to f32, so the only bf16
    rounding is at conv boundaries."""
    return jax.lax.conv_general_dilated(
        x.astype(w.dtype), w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _norm(x: Array, scale: Array, bias: Array, eps: float = 1e-5) -> Array:
    """Per-channel batch-statistics normalization (train-mode BN), in f32."""
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x, axis=(0, 1, 2), keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    return ((x - mean) * inv * scale.astype(jnp.float32)
            + bias.astype(jnp.float32))


class ResNet:
    """Configurable ResNet.  stages: blocks per stage; bottleneck: False for
    ResNet-18/34 basic blocks, True for ResNet-50-style 1-3-1 bottlenecks."""

    def __init__(self, stages: tuple[int, ...] = (2, 2, 2, 2),
                 bottleneck: bool = False, num_classes: int = 10,
                 width: int = 64, input_channels: int = 3,
                 small_inputs: bool = True, dtype=jnp.float32):
        self.stages = stages
        self.bottleneck = bottleneck
        self.num_classes = num_classes
        self.width = width
        self.input_channels = input_channels
        # small_inputs: CIFAR-style 3x3 stem, no initial pool (vs 7x7/s2 stem)
        self.small_inputs = small_inputs
        self.dtype = dtype
        self._shapes = self._build_shapes()

    # ------------------------------------------------------------ structure
    def _block_names(self) -> list[tuple[str, int, int, int, bool]]:
        """(block_prefix, in_ch, out_ch, stride, has_projection) per block."""
        blocks = []
        expansion = 4 if self.bottleneck else 1
        in_ch = self.width
        for stage_idx, num_blocks in enumerate(self.stages):
            base = self.width * (2 ** stage_idx)
            out_ch = base * expansion
            for block_idx in range(num_blocks):
                stride = 2 if (block_idx == 0 and stage_idx > 0) else 1
                needs_proj = (in_ch != out_ch) or stride != 1
                blocks.append((f"stage{stage_idx}/block{block_idx}",
                               in_ch, base, stride, needs_proj))
                in_ch = out_ch
        return blocks

    def _build_shapes(self) -> dict[str, tuple[int, ...]]:
        shapes: dict[str, tuple[int, ...]] = {}
        stem_k = 3 if self.small_inputs else 7
        shapes["stem/conv/w"] = (stem_k, stem_k, self.input_channels, self.width)
        shapes["stem/norm/scale"] = (self.width,)
        shapes["stem/norm/bias"] = (self.width,)
        expansion = 4 if self.bottleneck else 1
        for prefix, in_ch, base, stride, needs_proj in self._block_names():
            out_ch = base * expansion
            if self.bottleneck:
                shapes[f"{prefix}/conv1/w"] = (1, 1, in_ch, base)
                shapes[f"{prefix}/conv2/w"] = (3, 3, base, base)
                shapes[f"{prefix}/conv3/w"] = (1, 1, base, out_ch)
                for i, ch in ((1, base), (2, base), (3, out_ch)):
                    shapes[f"{prefix}/norm{i}/scale"] = (ch,)
                    shapes[f"{prefix}/norm{i}/bias"] = (ch,)
            else:
                shapes[f"{prefix}/conv1/w"] = (3, 3, in_ch, base)
                shapes[f"{prefix}/conv2/w"] = (3, 3, base, base)
                for i in (1, 2):
                    shapes[f"{prefix}/norm{i}/scale"] = (base,)
                    shapes[f"{prefix}/norm{i}/bias"] = (base,)
            if needs_proj:
                shapes[f"{prefix}/proj/w"] = (1, 1, in_ch, out_ch)
                shapes[f"{prefix}/proj_norm/scale"] = (out_ch,)
                shapes[f"{prefix}/proj_norm/bias"] = (out_ch,)
        final_ch = self.width * (2 ** (len(self.stages) - 1)) * expansion
        shapes["head/w"] = (final_ch, self.num_classes)
        shapes["head/b"] = (self.num_classes,)
        return shapes

    def param_shapes(self) -> dict[str, tuple[int, ...]]:
        return dict(self._shapes)

    def num_params(self) -> int:
        return sum(math.prod(s) for s in self._shapes.values())

    # ----------------------------------------------------------------- init
    def init_params(self, rng: jax.Array | int = 0) -> dict[str, Array]:
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        params: dict[str, Array] = {}
        for name, shape in self._shapes.items():
            rng, sub = jax.random.split(rng)
            if name.endswith("conv/w") or "/conv" in name or "/proj/w" in name:
                fan_in = math.prod(shape[:-1])
                params[name] = (math.sqrt(2.0 / fan_in) *
                                jax.random.normal(sub, shape, self.dtype))
            elif name.endswith("/scale"):
                params[name] = jnp.ones(shape, self.dtype)
            elif name.endswith("/bias") or name.endswith("/b"):
                params[name] = jnp.zeros(shape, self.dtype)
            elif name == "head/w":
                params[name] = (math.sqrt(1.0 / shape[0]) *
                                jax.random.normal(sub, shape, self.dtype))
            else:
                raise AssertionError(f"unhandled param {name}")
        return params

    # -------------------------------------------------------------- forward
    def apply(self, params: Mapping[str, Array], x: Array) -> Array:
        p = params
        h = _conv(x, p["stem/conv/w"],
                  stride=1 if self.small_inputs else 2)
        h = _norm(h, p["stem/norm/scale"], p["stem/norm/bias"])
        h = jax.nn.relu(h)
        if not self.small_inputs:
            h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max,
                                      (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        for prefix, in_ch, base, stride, needs_proj in self._block_names():
            shortcut = h
            if self.bottleneck:
                out = _conv(h, p[f"{prefix}/conv1/w"])
                out = jax.nn.relu(_norm(out, p[f"{prefix}/norm1/scale"],
                                        p[f"{prefix}/norm1/bias"]))
                out = _conv(out, p[f"{prefix}/conv2/w"], stride=stride)
                out = jax.nn.relu(_norm(out, p[f"{prefix}/norm2/scale"],
                                        p[f"{prefix}/norm2/bias"]))
                out = _conv(out, p[f"{prefix}/conv3/w"])
                out = _norm(out, p[f"{prefix}/norm3/scale"],
                            p[f"{prefix}/norm3/bias"])
            else:
                out = _conv(h, p[f"{prefix}/conv1/w"], stride=stride)
                out = jax.nn.relu(_norm(out, p[f"{prefix}/norm1/scale"],
                                        p[f"{prefix}/norm1/bias"]))
                out = _conv(out, p[f"{prefix}/conv2/w"])
                out = _norm(out, p[f"{prefix}/norm2/scale"],
                            p[f"{prefix}/norm2/bias"])
            if needs_proj:
                shortcut = _conv(h, p[f"{prefix}/proj/w"], stride=stride)
                shortcut = _norm(shortcut, p[f"{prefix}/proj_norm/scale"],
                                 p[f"{prefix}/proj_norm/bias"])
            h = jax.nn.relu(out + shortcut)
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        return jnp.dot(h, p["head/w"],
                       preferred_element_type=jnp.float32) + p["head/b"]

    def loss(self, params: Mapping[str, Array], batch: tuple) -> Array:
        x, y = batch
        logits = self.apply(params, x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=-1)
        return jnp.mean(nll)

    def accuracy(self, params: Mapping[str, Array], batch: tuple) -> Array:
        x, y = batch
        return jnp.mean((jnp.argmax(self.apply(params, x), -1) == y)
                        .astype(jnp.float32))


def resnet18(num_classes: int = 10, small_inputs: bool = True,
             dtype=jnp.float32) -> ResNet:
    return ResNet((2, 2, 2, 2), bottleneck=False, num_classes=num_classes,
                  small_inputs=small_inputs, dtype=dtype)


def resnet50(num_classes: int = 1000, small_inputs: bool = False,
             dtype=jnp.bfloat16) -> ResNet:
    return ResNet((3, 4, 6, 3), bottleneck=True, num_classes=num_classes,
                  small_inputs=small_inputs, dtype=dtype)
