"""HuggingFace interop: convert GPT-2-family checkpoints into this
framework's Transformer.

A user migrating to this framework should be able to bring a pretrained
torch checkpoint with them and serve it on TPU through the native stack
(KV-cached generate, continuous batching, int8 quantization, speculative
decoding).  GPT-2 is the canonical test family: its architecture needs
exactly the three compatibility knobs TransformerConfig exposes
(``pos_emb="learned"``, ``norm="layernorm"``, ``bias=True``) plus weight
re-layout:

- HF ``Conv1D`` stores weights [in, out] — the same x @ W convention as
  this package, so attention/MLP matrices copy through without transpose;
  the fused ``c_attn`` [d, 3d] splits into wq/wk/wv columns.
- ``wte`` is tied to the LM head: ``lm_head/w = wte.T``.
- GELU: HF ``gelu_new`` is the tanh approximation — ``jax.nn.gelu``'s
  default, so activations match.
- LayerNorm eps 1e-5 (``config.layer_norm_epsilon``) -> ``norm_eps``.

Verified by logits parity against the torch forward (tests/test_hf.py)
on random-init models — no network needed; the same code path loads real
published weights where a checkout of them exists.

The reference has no model zoo or interop at all (its "gradient" is a
0.01-constant stub — reference src/worker.cpp:316-329); this is added
capability for the serving/fine-tuning story.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from .transformer import Transformer, TransformerConfig


def _state_dict_np(hf_model: Any) -> dict:
    """torch state_dict -> numpy.  Upcasts through float32 first: torch
    bf16 tensors (the dtype real checkpoints ship in, and the standard
    torch_dtype=bfloat16 loading path) do not support .numpy()."""
    return {name: t.detach().cpu().float().numpy()
            for name, t in hf_model.state_dict().items()}


def config_from_hf_gpt2(hf_config: Any, *,
                        dtype=jnp.float32,
                        scan_layers: bool = False) -> TransformerConfig:
    """Map a ``transformers.GPT2Config`` onto TransformerConfig.

    Rejects configurations whose math this framework would silently get
    wrong: only the tanh-approximation GELU family is supported (the
    ``jax.nn.gelu`` default); ``n_inner`` is honored when set."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(
            f"unsupported activation_function {act!r}: this framework "
            "applies the tanh-approximate GELU (jax.nn.gelu default), "
            "which matches HF 'gelu_new'/'gelu_pytorch_tanh' only")
    for variant in ("scale_attn_by_inverse_layer_idx",
                    "reorder_and_upcast_attn"):
        if getattr(hf_config, variant, False):
            raise ValueError(
                f"unsupported GPT2Config.{variant}=True: this framework "
                "scales attention scores by 1/sqrt(head_dim) only — "
                "converting would produce silently wrong logits")
    n_inner = getattr(hf_config, "n_inner", None)
    return TransformerConfig(
        vocab=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_heads=hf_config.n_head,
        n_layers=hf_config.n_layer,
        d_ff=n_inner if n_inner else 4 * hf_config.n_embd,
        max_seq=hf_config.n_positions,
        dtype=dtype,
        pos_emb="learned",
        norm="layernorm",
        bias=True,
        norm_eps=float(hf_config.layer_norm_epsilon),
        scan_layers=scan_layers,
    )


def from_hf_gpt2(hf_model: Any, *, dtype=jnp.float32,
                 scan_layers: bool = False,
                 ) -> tuple[Transformer, dict[str, jnp.ndarray]]:
    """Convert a ``transformers.GPT2LMHeadModel`` (torch) into
    (Transformer, params).  Pure weight re-layout — no renormalization —
    so logits match the torch forward to float tolerance."""
    cfg = config_from_hf_gpt2(hf_model.config, dtype=dtype,
                              scan_layers=scan_layers)
    model = Transformer(cfg)
    sd = _state_dict_np(hf_model)
    d = cfg.d_model

    def arr(x):
        return jnp.asarray(x, dtype)

    params: dict[str, jnp.ndarray] = {
        "embed/tok": arr(sd["transformer.wte.weight"]),
        "embed/pos": arr(sd["transformer.wpe.weight"]),
        "final_ln/scale": arr(sd["transformer.ln_f.weight"]),
        "final_ln/bias": arr(sd["transformer.ln_f.bias"]),
        # weight tying: the LM head is wte transposed
        "lm_head/w": arr(sd["transformer.wte.weight"].T),
    }
    per_layer: list[dict[str, np.ndarray]] = []
    for i in range(cfg.n_layers):
        hf = f"transformer.h.{i}"
        w_attn = sd[f"{hf}.attn.c_attn.weight"]      # [d, 3d], x @ W layout
        b_attn = sd[f"{hf}.attn.c_attn.bias"]        # [3d]
        layer = {
            "ln1/scale": sd[f"{hf}.ln_1.weight"],
            "ln1/bias": sd[f"{hf}.ln_1.bias"],
            "attn/wq": w_attn[:, :d],
            "attn/wk": w_attn[:, d:2 * d],
            "attn/wv": w_attn[:, 2 * d:],
            "attn/bq": b_attn[:d],
            "attn/bk": b_attn[d:2 * d],
            "attn/bv": b_attn[2 * d:],
            "attn/wo": sd[f"{hf}.attn.c_proj.weight"],
            "attn/bo": sd[f"{hf}.attn.c_proj.bias"],
            "ln2/scale": sd[f"{hf}.ln_2.weight"],
            "ln2/bias": sd[f"{hf}.ln_2.bias"],
            "mlp/w1": sd[f"{hf}.mlp.c_fc.weight"],
            "mlp/b1": sd[f"{hf}.mlp.c_fc.bias"],
            "mlp/w2": sd[f"{hf}.mlp.c_proj.weight"],
            "mlp/b2": sd[f"{hf}.mlp.c_proj.bias"],
        }
        per_layer.append(layer)
    if scan_layers:
        for suffix in per_layer[0]:
            params[f"blocks/{suffix}"] = arr(
                np.stack([layer[suffix] for layer in per_layer]))
    else:
        for i, layer in enumerate(per_layer):
            for suffix, value in layer.items():
                params[f"layer{i}/{suffix}"] = arr(value)

    _check_shapes(model, params)
    return model, params


def _check_shapes(model: Transformer, params: dict) -> None:
    """Shape contract: exactly the parameters the config says exist."""
    expected = model.param_shapes()
    got = {name: tuple(v.shape) for name, v in params.items()}
    if got != expected:
        missing = expected.keys() - got.keys()
        extra = got.keys() - expected.keys()
        wrong = {n for n in expected.keys() & got.keys()
                 if expected[n] != got[n]}
        raise ValueError(
            f"converted store mismatch: missing={sorted(missing)} "
            f"extra={sorted(extra)} wrong_shape={sorted(wrong)}")


def _require_dense(params: Mapping[str, Any]) -> None:
    from .quant import QTensor
    if any(isinstance(v, QTensor) for v in params.values()):
        raise ValueError("cannot export an int8-quantized store; export "
                         "the pre-quantization parameters")


def _layer_view(params: Mapping[str, Any], i: int) -> dict:
    """Per-layer suffix -> numpy array, for either layer layout."""
    if any(name.startswith("blocks/") for name in params):
        return {name[len("blocks/"):]: np.asarray(v[i], np.float32)
                for name, v in params.items() if name.startswith("blocks/")}
    prefix = f"layer{i}/"
    return {name[len(prefix):]: np.asarray(v, np.float32)
            for name, v in params.items() if name.startswith(prefix)}


def to_hf_gpt2(model: Transformer, params: Mapping[str, Any]) -> dict:
    """Export a (possibly fine-tuned here) GPT-2-architecture store back
    to a ``transformers.GPT2LMHeadModel`` state_dict (torch tensors) —
    the round-trip of :func:`from_hf_gpt2`, so checkpoints trained on
    this framework load straight into the torch ecosystem.  Weight tying
    is restored from ``embed/tok`` (GPT-2's lm_head IS wte)."""
    import torch

    _require_dense(params)
    cfg = model.config
    if (cfg.pos_emb, cfg.norm, cfg.bias) != ("learned", "layernorm", True):
        raise ValueError("to_hf_gpt2 exports the GPT-2 architecture "
                         "(pos_emb='learned', norm='layernorm', bias=True)")
    t = lambda x: torch.from_numpy(  # noqa: E731 — copy: a zero-copy
        # view of the live JAX buffer would be non-writable (torch UB on
        # in-place writes / assign=True training)
        np.array(x, np.float32, copy=True))
    # HF GPT-2 ARCHITECTURALLY ties lm_head to wte.  This framework
    # trains them as separate parameters, so a fine-tuned store whose
    # head diverged from embed.T cannot be represented — reject instead
    # of silently dropping the tuned head on export.
    head = np.asarray(params["lm_head/w"], np.float32)
    tok = np.asarray(params["embed/tok"], np.float32)
    if not np.allclose(head, tok.T, rtol=1e-4, atol=1e-5):
        raise ValueError(
            "GPT-2 ties lm_head to wte but this store's lm_head/w has "
            "diverged from embed/tok.T (fine-tuning here unties them); "
            "re-tie (params['lm_head/w'] = params['embed/tok'].T) or "
            "export a LLaMA-architecture model, whose head is untied")
    sd = {
        "transformer.wte.weight": t(params["embed/tok"]),
        "transformer.wpe.weight": t(params["embed/pos"]),
        "transformer.ln_f.weight": t(params["final_ln/scale"]),
        "transformer.ln_f.bias": t(params["final_ln/bias"]),
        "lm_head.weight": t(params["embed/tok"]),     # tied
    }
    for i in range(cfg.n_layers):
        layer = _layer_view(params, i)
        hf = f"transformer.h.{i}"
        sd[f"{hf}.ln_1.weight"] = t(layer["ln1/scale"])
        sd[f"{hf}.ln_1.bias"] = t(layer["ln1/bias"])
        sd[f"{hf}.attn.c_attn.weight"] = t(np.concatenate(
            [layer["attn/wq"], layer["attn/wk"], layer["attn/wv"]], axis=1))
        sd[f"{hf}.attn.c_attn.bias"] = t(np.concatenate(
            [layer["attn/bq"], layer["attn/bk"], layer["attn/bv"]]))
        sd[f"{hf}.attn.c_proj.weight"] = t(layer["attn/wo"])
        sd[f"{hf}.attn.c_proj.bias"] = t(layer["attn/bo"])
        sd[f"{hf}.ln_2.weight"] = t(layer["ln2/scale"])
        sd[f"{hf}.ln_2.bias"] = t(layer["ln2/bias"])
        sd[f"{hf}.mlp.c_fc.weight"] = t(layer["mlp/w1"])
        sd[f"{hf}.mlp.c_fc.bias"] = t(layer["mlp/b1"])
        sd[f"{hf}.mlp.c_proj.weight"] = t(layer["mlp/w2"])
        sd[f"{hf}.mlp.c_proj.bias"] = t(layer["mlp/b2"])
    return sd


def to_hf_llama(model: Transformer, params: Mapping[str, Any], *,
                tie_word_embeddings: bool = False) -> dict:
    """Export a LLaMA-architecture store to a
    ``transformers.LlamaForCausalLM`` state_dict — the round-trip of
    :func:`from_hf_llama` (torch Linear stores [out, in]: transpose
    back).  Set ``tie_word_embeddings=True`` when the DESTINATION model
    ties lm_head to embed_tokens (TinyLlama/Llama-3.2 style): the export
    then verifies the tie still holds and omits the lm_head key —
    emitting it would silently stomp the shared embedding on load (last
    copy into the shared Parameter wins)."""
    import torch

    _require_dense(params)
    cfg = model.config
    if (cfg.pos_emb, cfg.norm, cfg.bias, cfg.mlp_act) != (
            "rope", "rms", False, "swiglu"):
        raise ValueError("to_hf_llama exports the LLaMA architecture "
                         "(rope/rms/bias-free/swiglu)")
    t = lambda x: torch.from_numpy(  # noqa: E731 — copy, as in to_hf_gpt2
        np.array(x, np.float32, copy=True))
    sd = {
        "model.embed_tokens.weight": t(params["embed/tok"]),
        "model.norm.weight": t(params["final_ln/scale"]),
    }
    if tie_word_embeddings:
        head = np.asarray(params["lm_head/w"], np.float32)
        tok = np.asarray(params["embed/tok"], np.float32)
        if not np.allclose(head, tok.T, rtol=1e-4, atol=1e-5):
            raise ValueError(
                "tie_word_embeddings=True but this store's lm_head/w has "
                "diverged from embed/tok.T (fine-tuning unties them); "
                "re-tie or export for an untied destination model")
    else:
        sd["lm_head.weight"] = t(np.asarray(params["lm_head/w"],
                                            np.float32).T)
    for i in range(cfg.n_layers):
        layer = _layer_view(params, i)
        hf = f"model.layers.{i}"
        sd[f"{hf}.input_layernorm.weight"] = t(layer["ln1/scale"])
        sd[f"{hf}.self_attn.q_proj.weight"] = t(layer["attn/wq"].T)
        sd[f"{hf}.self_attn.k_proj.weight"] = t(layer["attn/wk"].T)
        sd[f"{hf}.self_attn.v_proj.weight"] = t(layer["attn/wv"].T)
        sd[f"{hf}.self_attn.o_proj.weight"] = t(layer["attn/wo"].T)
        sd[f"{hf}.post_attention_layernorm.weight"] = t(layer["ln2/scale"])
        sd[f"{hf}.mlp.gate_proj.weight"] = t(layer["mlp/w1"].T)
        sd[f"{hf}.mlp.up_proj.weight"] = t(layer["mlp/w3"].T)
        sd[f"{hf}.mlp.down_proj.weight"] = t(layer["mlp/w2"].T)
    return sd


def config_from_hf_llama(hf_config: Any, *, dtype=jnp.bfloat16,
                         scan_layers: bool = False) -> TransformerConfig:
    """Map a ``transformers.LlamaConfig`` onto TransformerConfig.  The
    LLaMA family IS this framework's native architecture (RoPE in the
    rotate-half convention, RMSNorm, GQA, no biases) plus the SwiGLU MLP
    knob — so the mapping is direct.  Rejects rope_scaling and attention
    bias, whose math this framework does not implement."""
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError("unsupported rope_scaling: this framework "
                         "implements plain RoPE only")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("unsupported attention_bias=True for the "
                         "LLaMA-family conversion (bias-free attention)")
    act = getattr(hf_config, "hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"unsupported hidden_act {act!r}: the SwiGLU "
                         "path applies silu gating only")
    return TransformerConfig(
        vocab=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=(hf_config.num_key_value_heads
                    if hf_config.num_key_value_heads
                    != hf_config.num_attention_heads else 0),
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        dtype=dtype,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
        mlp_act="swiglu",
        scan_layers=scan_layers,
    )


def from_hf_llama(hf_model: Any, *, dtype=jnp.bfloat16,
                  scan_layers: bool = False,
                  ) -> tuple[Transformer, dict[str, jnp.ndarray]]:
    """Convert a ``transformers.LlamaForCausalLM`` (torch) into
    (Transformer, params).  torch ``nn.Linear`` stores [out, in], so every
    projection transposes into this package's x @ W layout; gate_proj ->
    mlp/w1, up_proj -> mlp/w3, down_proj -> mlp/w2.  RoPE conventions
    already agree (both rotate-half), so no head permutation is needed."""
    cfg = config_from_hf_llama(hf_model.config, dtype=dtype,
                               scan_layers=scan_layers)
    model = Transformer(cfg)
    sd = _state_dict_np(hf_model)

    def arr(x):
        return jnp.asarray(x, dtype)

    embed = sd["model.embed_tokens.weight"]
    params: dict[str, jnp.ndarray] = {
        "embed/tok": arr(embed),
        "final_ln/scale": arr(sd["model.norm.weight"]),
        "lm_head/w": arr(sd["lm_head.weight"].T
                         if "lm_head.weight" in sd else embed.T),
    }
    per_layer: list[dict[str, np.ndarray]] = []
    for i in range(cfg.n_layers):
        hf = f"model.layers.{i}"
        per_layer.append({
            "ln1/scale": sd[f"{hf}.input_layernorm.weight"],
            "attn/wq": sd[f"{hf}.self_attn.q_proj.weight"].T,
            "attn/wk": sd[f"{hf}.self_attn.k_proj.weight"].T,
            "attn/wv": sd[f"{hf}.self_attn.v_proj.weight"].T,
            "attn/wo": sd[f"{hf}.self_attn.o_proj.weight"].T,
            "ln2/scale": sd[f"{hf}.post_attention_layernorm.weight"],
            "mlp/w1": sd[f"{hf}.mlp.gate_proj.weight"].T,
            "mlp/w3": sd[f"{hf}.mlp.up_proj.weight"].T,
            "mlp/w2": sd[f"{hf}.mlp.down_proj.weight"].T,
        })
    if scan_layers:
        for suffix in per_layer[0]:
            params[f"blocks/{suffix}"] = arr(
                np.stack([layer[suffix] for layer in per_layer]))
    else:
        for i, layer in enumerate(per_layer):
            for suffix, value in layer.items():
                params[f"layer{i}/{suffix}"] = arr(value)
    _check_shapes(model, params)
    return model, params
