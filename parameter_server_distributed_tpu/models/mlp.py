"""MLP model family.

The reference has no model layer at all — its gradient computation is a
stub filling every element with 0.01 (reference: src/worker.cpp:316-329).
This framework replaces the stub with real jitted forward/backward.  The
MLP family spans the MNIST-scale config (BASELINE config 1) up to the
1B-parameter MLP used by the MFU target (BASELINE configs 3 and 5).

Parameters live in a flat named store (dict[str, Array]) so they flow
directly through the PS wire protocol, the checkpoint codec, and jitted
steps without conversion.  Matmuls accumulate in float32 on the MXU via
``preferred_element_type``; activations can be bfloat16.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np


class MLP:
    """Plain MLP with ReLU hidden layers and softmax cross-entropy loss."""

    def __init__(self, layer_sizes: tuple[int, ...] = (784, 256, 10),
                 dtype=jnp.float32):
        if len(layer_sizes) < 2:
            raise ValueError("need at least input and output sizes")
        self.layer_sizes = tuple(int(s) for s in layer_sizes)
        self.dtype = dtype

    @property
    def num_layers(self) -> int:
        return len(self.layer_sizes) - 1

    def param_shapes(self) -> dict[str, tuple[int, ...]]:
        shapes: dict[str, tuple[int, ...]] = {}
        for i, (fan_in, fan_out) in enumerate(
                zip(self.layer_sizes[:-1], self.layer_sizes[1:])):
            shapes[f"layer{i}/w"] = (fan_in, fan_out)
            shapes[f"layer{i}/b"] = (fan_out,)
        return shapes

    def num_params(self) -> int:
        return sum(math.prod(s) for s in self.param_shapes().values())

    def init_params(self, rng: jax.Array | int = 0) -> dict[str, jax.Array]:
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        params: dict[str, jax.Array] = {}
        for name, shape in self.param_shapes().items():
            rng, sub = jax.random.split(rng)
            if name.endswith("/w"):
                scale = math.sqrt(2.0 / shape[0])  # He init for ReLU
                params[name] = (scale *
                                jax.random.normal(sub, shape, self.dtype))
            else:
                params[name] = jnp.zeros(shape, self.dtype)
        return params

    def apply(self, params: Mapping[str, jax.Array], x: jax.Array) -> jax.Array:
        """Forward pass -> logits.  x: [batch, features]."""
        h = x.astype(self.dtype)
        for i in range(self.num_layers):
            w = params[f"layer{i}/w"]
            b = params[f"layer{i}/b"]
            h = jnp.dot(h, w, preferred_element_type=jnp.float32) + b.astype(jnp.float32)
            if i < self.num_layers - 1:
                h = jax.nn.relu(h).astype(self.dtype)
        return h  # float32 logits

    def loss(self, params: Mapping[str, jax.Array], batch: tuple) -> jax.Array:
        """Mean softmax cross-entropy over the batch."""
        x, y = batch
        logits = self.apply(params, x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=-1)
        return jnp.mean(nll)

    def accuracy(self, params: Mapping[str, jax.Array], batch: tuple) -> jax.Array:
        x, y = batch
        return jnp.mean((jnp.argmax(self.apply(params, x), -1) == y)
                        .astype(jnp.float32))


def mnist_mlp() -> MLP:
    """BASELINE config 1 model: 784-256-10 MNIST MLP."""
    return MLP((784, 256, 10))


def billion_param_mlp(hidden: int = 16384, layers: int = 4,
                      dtype=jnp.bfloat16) -> MLP:
    """~1B-parameter MLP for the MFU target (BASELINE configs 3/5).

    4 hidden layers of 16384 units: 4 * 16384^2 + edges ≈ 1.1e9 params.
    bfloat16 activations/weights with float32 MXU accumulation.
    """
    sizes = (hidden,) + (hidden,) * layers + (hidden,)
    return MLP(sizes, dtype=dtype)


MODEL_REGISTRY = {
    "mnist_mlp": mnist_mlp,
    "mlp_1b": billion_param_mlp,
}
