"""Decoder-only Transformer LM, TPU-first.

The framework's flagship long-context model.  The reference has no model or
sequence dimension at all (SURVEY.md §5) — this model is what makes the
mesh's ``tensor`` and ``seq`` axes real:

- tensor parallelism: Megatron-style column-parallel wq/wk/wv/w1 and
  row-parallel wo/w2 (one all-reduce per residual branch, inserted by XLA
  from the shardings);
- sequence parallelism: activations sharded [batch, seq, d] with seq on the
  ``seq`` axis; attention either all-gathers K/V (default GSPMD path) or
  runs ring attention (ops/ring_attention.py) with K/V blocks rotating over
  the ring — O(seq/N) memory per device;
- RoPE positions (no learned position table) so sequence shards are
  position-exact regardless of placement;
- bfloat16 weights/activations, float32 MXU accumulation, float32 softmax.

Parameters are a flat named store like every model here, so the same
transformer flows through the PS protocol, checkpointing, and ShardedTrainer.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from functools import partial
from typing import Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .moe import moe_expert_weight_spec
from .quant import wdot

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    # Grouped-query attention: number of K/V heads (0 = n_heads, i.e. MHA;
    # 1 = multi-query).  Shrinks wk/wv and the decode KV cache by
    # n_heads/n_kv_heads; each K/V head serves a group of query heads.
    n_kv_heads: int = 0
    n_layers: int = 6
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: object = jnp.bfloat16
    rope_theta: float = 10000.0
    # Rematerialization: recompute each layer's activations in the backward
    # pass instead of saving them (jax.checkpoint) — O(1) layers of
    # residuals instead of O(L), the standard long-context memory/FLOPs
    # trade on TPU (HBM is the bottleneck, MXU FLOPs are cheap).
    remat: bool = False
    # What remat may keep: "full" recomputes everything (O(1) residuals,
    # ~33% extra FLOPs — the whole forward again); "dots" applies
    # jax.checkpoint_policies.dots_with_no_batch_dims_saveable — the
    # projection/MLP matmul outputs (dot_generals with no batch dims) are
    # SAVED and only the attention score/value einsums (batch dims B, H —
    # the O(S^2) memory hogs) plus elementwise ops are recomputed: the
    # recompute overhead drops from a whole extra forward (~33% of the
    # fwd+bwd budget) to the attention einsums alone (~5% at S=d=1024 —
    # 4·S·d² vs the 72·S·d² + 12·S²·d fwd+bwd per-layer matmul total),
    # for O(L·S·d) saved activations instead of O(1) residuals.
    remat_policy: str = "full"
    # Chunked cross-entropy: compute the LM head + softmax in sequence
    # chunks of this many positions (0 = whole sequence at once).  Peak
    # logits memory drops from O(S * vocab) to O(chunk * vocab) — at
    # vocab 32k, seq 1024, batch 8 that is ~1 GB -> ~32 MB of f32 logits —
    # with the chunk recomputed in the backward pass (jax.checkpoint).
    # Must divide max_seq.
    loss_chunk: int = 0
    # Mixture-of-experts: every ``moe_every``-th layer (1-based; 0 = dense
    # everywhere) swaps its FFN for a Switch-routed MoE (models/moe.py) with
    # ``moe_experts`` experts; the load-balancing aux loss is added to the
    # LM loss scaled by ``moe_aux_coef``.
    moe_every: int = 0
    moe_experts: int = 8
    # experts per token: 1 = Switch (default), 2 = Mixtral-style top-2
    moe_top_k: int = 1
    # Scan over layers: store block weights stacked with a leading [L]
    # axis (``blocks/<suffix>``) and run the layer loop as one
    # ``lax.scan`` body traced ONCE, instead of n_layers Python-unrolled
    # copies.  Compile time and HLO size stop growing with depth (the
    # 24-layer flagship's jit drops from minutes to one layer's worth);
    # the trade is that XLA cannot specialize or fuse across layer
    # boundaries.  Requires homogeneous layers (no MoE interleaving).
    scan_layers: bool = False
    moe_capacity: float = 1.25
    moe_aux_coef: float = 0.01
    # --- GPT-2-family compatibility knobs (models/hf.py interop).  The
    # defaults are the native architecture (RoPE + RMSNorm, no biases);
    # the flags exist so pretrained-checkpoint families with learned
    # positions / LayerNorm / biased projections convert losslessly.
    pos_emb: str = "rope"         # rope | learned ("embed/pos" table)
    norm: str = "rms"             # rms | layernorm (mean-centering + bias)
    bias: bool = False            # biases on attn/mlp projections
    norm_eps: float = 1e-6
    # gelu: w2(gelu(w1 x)); swiglu: w2(silu(w1 x) * (w3 x)) — the
    # LLaMA-family gated MLP (w1 = gate_proj, w3 = up_proj)
    mlp_act: str = "gelu"

    def __post_init__(self):
        if self.pos_emb not in ("rope", "learned"):
            raise ValueError(
                f"pos_emb must be 'rope' or 'learned', got {self.pos_emb!r}")
        if self.norm not in ("rms", "layernorm"):
            raise ValueError(
                f"norm must be 'rms' or 'layernorm', got {self.norm!r}")
        if self.mlp_act not in ("gelu", "swiglu"):
            raise ValueError(
                f"mlp_act must be 'gelu' or 'swiglu', got {self.mlp_act!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(f"remat_policy must be 'full' or 'dots', "
                             f"got {self.remat_policy!r}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def kv_groups(self) -> int:
        """Query heads per K/V head."""
        return self.n_heads // self.kv_heads

    def is_moe_layer(self, i: int) -> bool:
        return self.moe_every > 0 and (i + 1) % self.moe_every == 0


def next_token_nll(logits: Array, tokens: Array) -> Array:
    """Mean next-token cross-entropy from full-sequence logits.  The single
    definition shared by Transformer.loss and the pipelined LM
    (parallel/pipeline.py) so the two training modes can never diverge."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32),
                               axis=-1)
    return jnp.mean(nll)


def rms_norm(x: Array, scale: Array, eps: float = 1e-6) -> Array:
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv * scale.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x: Array, scale: Array, bias: Array,
               eps: float = 1e-5) -> Array:
    """Mean-centering LayerNorm with bias (the GPT-2-family norm)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def rope(x: Array, positions: Array, theta: float = 10000.0) -> Array:
    """Rotary position embedding.  x: [..., seq, heads, head_dim]."""
    head_dim = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, head_dim // 2, dtype=jnp.float32)
                      / (head_dim // 2))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., S, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def flash_attention_auto(q: Array, k: Array, v: Array) -> Array:
    """Causal attention that uses the pallas flash kernels
    (ops/pallas/flash_attention.py — blockwise fwd+bwd, O(S) residual
    memory) when the sequence is block-divisible, falling back to the dense
    einsum otherwise.  GQA K/V stay UNexpanded: the grouped-query kernel
    folds query groups into the block batch, so K/V HBM stays
    kv_heads-sized end to end (fwd blocks and dK/dV alike).  On non-TPU
    backends the kernels run in interpret mode, so this is only worth
    selecting on TPU; pass it explicitly as
    ``Transformer(config, attention_fn=flash_attention_auto)`` or set
    ``PSDT_FLASH_ATTENTION=1`` to make it the model default.

    ``PSDT_FLASH_BLOCK_Q`` / ``PSDT_FLASH_BLOCK_K`` (default 128) tune
    the kernel tile sizes without a code change — larger K blocks raise
    arithmetic intensity per HBM fetch at O(block_q*block_k) VMEM cost;
    the sequence must divide by both."""
    import os

    from ..ops.pallas.flash_attention import flash_attention_gqa

    # `or "128"`: an EMPTY env value means unset (shell idiom VAR= ),
    # matching the package's other PSDT_ flags; non-numeric fails loudly
    block_q = int(os.environ.get("PSDT_FLASH_BLOCK_Q") or "128")
    block_k = int(os.environ.get("PSDT_FLASH_BLOCK_K") or "128")
    seq = q.shape[1]
    if seq % block_q == 0 and seq % block_k == 0:
        return flash_attention_gqa(q, k, v, block_q=block_q,
                                   block_k=block_k)
    return causal_attention(q, k, v)


def make_sharded_flash_attention(mesh: Mesh,
                                 batch_axes: tuple[str, ...] = ("data", "fsdp"),
                                 head_axis: str = "tensor") -> Callable:
    """Pallas flash attention composed with a mesh: shard_map over the
    batch and head axes, each device running the single-shard flash kernel
    on its full-sequence [B/n, S, H/n, D] block.  Causal attention is
    independent across batch and heads, so this is exact.

    The sequence axis must NOT be sharded here — XLA all-gathers seq-sharded
    activations to satisfy the in_specs; for a real ``seq`` axis use ring or
    Ulysses attention (ops/ring_attention.py) instead.  Heads must divide by
    the ``tensor`` axis when that axis is >1 (shard_map divisibility)."""
    from functools import partial as _partial

    from jax import shard_map

    heads_spec = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    spec = PartitionSpec(batch_axes, None, heads_spec, None)

    @_partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
              out_specs=spec, check_vma=False)
    def sharded_flash(q, k, v):
        return flash_attention_auto(q, k, v)

    n_tp = mesh.shape.get(head_axis, 1)

    def sharded_flash_gqa(q, k, v):
        k, v = prepare_gqa_kv(q, k, v, n_tp)
        return sharded_flash(q, k, v)

    return sharded_flash_gqa


ATTENTION_CHOICES = ("dense", "flash", "xla_flash", "ring", "ulysses",
                     "ulysses_flash", "ulysses_xla_flash")


def select_attention(name: str, mesh: Mesh | None) -> Callable | None:
    """Attention implementation by name (the ``--attention`` CLI switch).

    dense   — einsum causal attention (GSPMD partitions it over the mesh)
    flash   — pallas flash kernels; with a mesh, shard_mapped over
              batch/head shards (seq must be unsharded)
    xla_flash — the same blockwise online-softmax recurrence in plain
              lax.scan (ops/xla_flash.py): compiled natively on every
              backend, O(S) residuals via per-block remat; the long-
              context path where pallas is unavailable, and the pallas
              kernels' A/B contender on TPU
    ring    — ring attention over the mesh's ``seq`` axis (K/V ppermute)
    ulysses — all-to-all seq<->heads swap, dense attention per head shard
    ulysses_flash — same swap, pallas flash kernel on the gathered
              full sequence (seq parallelism + O(block^2) VMEM)
    ulysses_xla_flash — same swap, the lax.scan flash recurrence on the
              gathered sequence (compiled on every backend)

    Returns None for dense (the Transformer default), letting the model
    pick its own fallback logic."""
    if name == "dense":
        return None
    if name == "flash":
        if mesh is None:
            return flash_attention_auto
        return make_sharded_flash_attention(mesh)
    if name == "xla_flash":
        from ..ops.xla_flash import make_xla_flash_attention
        # plain einsums + scan: with a mesh, GSPMD partitions it over the
        # batch/head axes exactly like dense — no shard_map needed
        return make_xla_flash_attention()
    if name in ("ring", "ulysses", "ulysses_flash", "ulysses_xla_flash"):
        if mesh is None:
            raise ValueError(f"--attention={name} needs a mesh with a seq axis")
        from ..ops.ring_attention import (make_ring_attention,
                                          make_ulysses_attention)
        if name == "ring":
            return make_ring_attention(mesh)
        if name == "ulysses_flash":
            # pallas flash on each device's gathered full sequence
            return make_ulysses_attention(mesh, inner=flash_attention_auto)
        if name == "ulysses_xla_flash":
            # the lax.scan flash recurrence on the gathered sequence —
            # compiled on every backend (ops/xla_flash.py)
            from ..ops.xla_flash import make_xla_flash_attention
            return make_ulysses_attention(mesh,
                                          inner=make_xla_flash_attention())
        return make_ulysses_attention(mesh)
    raise ValueError(f"unknown attention {name!r}; options {ATTENTION_CHOICES}")


def _default_attention() -> Callable:
    """PSDT_FLASH_ATTENTION=1 opts the model default into the pallas flash
    path — on TPU only: on other backends the kernels run in interpret mode
    (orders of magnitude slower than the einsum), which is for tests to opt
    into explicitly, never a shared launch env flag."""
    import os

    if (os.environ.get("PSDT_FLASH_ATTENTION", "") not in ("", "0")
            and jax.default_backend() == "tpu"):
        return flash_attention_auto
    return causal_attention


def repeat_kv(x: Array, groups: int) -> Array:
    """Expand GQA K/V heads to the query head count: [B, S, KV, D] ->
    [B, S, KV*groups, D], each K/V head repeated for its query group."""
    if groups == 1:
        return x
    return jnp.repeat(x, groups, axis=2)


def expand_gqa(q: Array, k: Array, v: Array) -> tuple[Array, Array]:
    """Repeat grouped-query K/V heads up to the query head count, inferring
    the group size from the shapes.  Attention implementations call this
    THEMSELVES (rather than receiving pre-expanded K/V) so that comm-bound
    paths — ring's ppermute rotation, Ulysses' all-to-all — move the small
    kv_heads-sized tensors and expand only at the math."""
    groups = q.shape[2] // k.shape[2]
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"query heads {q.shape[2]} must divide by "
                         f"kv heads {k.shape[2]}")
    return repeat_kv(k, groups), repeat_kv(v, groups)


def prepare_gqa_kv(q: Array, k: Array, v: Array,
                   n_tp: int) -> tuple[Array, Array]:
    """Validate GQA head grouping and, when the unexpanded kv_heads axis
    cannot be sharded by the ``tensor`` axis (kv_heads % n_tp != 0),
    pre-expand K/V to the query head count so shard_map head specs stay
    satisfiable (MQA + tensor parallelism); all other configs keep the
    small kv_heads-sized transfers.  The single home for this rule,
    shared by the ring/Ulysses/sharded-flash wrappers."""
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"query heads {q.shape[2]} must divide by "
                         f"kv heads {k.shape[2]}")
    if n_tp > 1 and k.shape[2] % n_tp:
        k, v = expand_gqa(q, k, v)
    return k, v


def causal_attention(q: Array, k: Array, v: Array) -> Array:
    """Reference einsum attention.  q: [B, S, H, D], k/v: [B, S, H, D] or
    the GQA [B, S, KV, D] (expanded here) -> [B, S, H, D].  float32
    logits/softmax for stability."""
    k, v = expand_gqa(q, k, v)
    head_dim = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(head_dim)
    s_q, s_k = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_))
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


_INSTANCE_COUNTER = itertools.count()


class Transformer:
    def __init__(self, config: TransformerConfig,
                 attention_fn: Callable | None = None,
                 mesh: Mesh | None = None):
        if config.d_model % config.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if config.n_heads % config.kv_heads:
            raise ValueError(
                f"n_heads={config.n_heads} must divide by "
                f"n_kv_heads={config.kv_heads}")
        if config.scan_layers and config.moe_every > 0:
            raise ValueError(
                "scan_layers needs homogeneous layers; MoE interleaving "
                "(moe_every > 0) makes the scan body layer-dependent")
        self.config = config
        if config.moe_every > 0:
            from .moe import MoEConfig, MoELayer
            self._moe = MoELayer(MoEConfig(
                d_model=config.d_model, d_ff=config.d_ff,
                num_experts=config.moe_experts, top_k=config.moe_top_k,
                capacity_factor=config.moe_capacity, dtype=config.dtype))
        else:
            self._moe = None
        # Default with a mesh is the GSPMD einsum path (XLA partitions it);
        # pass make_sharded_flash_attention(mesh) / make_ring_attention /
        # make_ulysses_attention — or use select_attention(name, mesh) — to
        # combine a mesh with the pallas flash kernel or seq parallelism.
        self.attention_fn = attention_fn or (
            _default_attention() if mesh is None else causal_attention)
        self.mesh = mesh  # when set, activations get sharding constraints
        # Never-reused identity for compiled-runner caches (generation.py):
        # id(self) can be recycled after GC, a counter token cannot.
        self.cache_token = next(_INSTANCE_COUNTER)

    # ------------------------------------------------------------- shapes
    def param_shapes(self) -> dict[str, tuple[int, ...]]:
        c = self.config
        shapes: dict[str, tuple[int, ...]] = {"embed/tok": (c.vocab, c.d_model)}
        if c.pos_emb == "learned":
            shapes["embed/pos"] = (c.max_seq, c.d_model)
        kv_dim = c.kv_heads * c.head_dim
        block = {"ln1/scale": (c.d_model,),
                 "attn/wq": (c.d_model, c.d_model),
                 "attn/wk": (c.d_model, kv_dim),
                 "attn/wv": (c.d_model, kv_dim),
                 "attn/wo": (c.d_model, c.d_model),
                 "ln2/scale": (c.d_model,)}
        if c.norm == "layernorm":
            block["ln1/bias"] = (c.d_model,)
            block["ln2/bias"] = (c.d_model,)
        if c.bias:
            block.update({"attn/bq": (c.d_model,), "attn/bk": (kv_dim,),
                          "attn/bv": (kv_dim,), "attn/bo": (c.d_model,)})
        mlp = {"mlp/w1": (c.d_model, c.d_ff), "mlp/w2": (c.d_ff, c.d_model)}
        if c.mlp_act == "swiglu":
            mlp["mlp/w3"] = (c.d_model, c.d_ff)   # up_proj of the gate pair
        if c.bias:
            mlp.update({"mlp/b1": (c.d_ff,), "mlp/b2": (c.d_model,)})
        if c.scan_layers:
            # stacked layout: one [L, ...] array per block weight, scanned
            for suffix, shape in {**block, **mlp}.items():
                shapes[f"blocks/{suffix}"] = (c.n_layers, *shape)
        else:
            for i in range(c.n_layers):
                p = f"layer{i}"
                for suffix, shape in block.items():
                    shapes[f"{p}/{suffix}"] = shape
                if c.is_moe_layer(i):
                    shapes[f"{p}/moe/router/w"] = (c.d_model, c.moe_experts)
                    shapes[f"{p}/moe/w1"] = (c.moe_experts, c.d_model, c.d_ff)
                    shapes[f"{p}/moe/w2"] = (c.moe_experts, c.d_ff, c.d_model)
                else:
                    for suffix, shape in mlp.items():
                        shapes[f"{p}/{suffix}"] = shape
        shapes["final_ln/scale"] = (c.d_model,)
        if c.norm == "layernorm":
            shapes["final_ln/bias"] = (c.d_model,)
        shapes["lm_head/w"] = (c.d_model, c.vocab)
        return shapes

    def num_params(self) -> int:
        return sum(math.prod(s) for s in self.param_shapes().values())

    def flops_per_sample(self, remat_credited: bool = False) -> float | None:
        """Training (fwd+bwd) FLOPs for one max_seq-length sample:
        6*P per token for the parameter matmuls plus 12*L*d_model*S per
        token for the attention score/value matmuls (PaLM-appendix
        convention, full-S accounting).

        MoE configs count ACTIVE-expert FLOPs: each token's FFN runs
        ``moe_top_k`` of the ``moe_experts`` experts, so the parameter
        term uses P_active = P - n_moe_layers * (E - top_k) * 2*d*d_ff
        (the standard sparse-MoE MFU numerator; an upper bound when
        expert-capacity dropping skips some tokens' experts — callers
        reporting MoE MFU must say "active-expert accounting", bench.py
        does).

        ``remat_credited=True`` counts the extra forward the hardware
        actually executes under ``config.remat``: hardware-utilization
        accounting for rematerialized runs.  Under the "full" policy that
        is the whole forward again (+2*P and +4*L*d*S per token); under
        "dots" the projection/MLP matmuls are saved and only the attention
        einsums re-run (+4*L*d*S only)."""
        c = self.config
        seq = c.max_seq
        n_params = self.num_params()
        if c.moe_every > 0:
            n_moe = sum(1 for i in range(c.n_layers) if c.is_moe_layer(i))
            inactive = max(0, c.moe_experts - c.moe_top_k)
            n_params -= n_moe * inactive * 2 * c.d_model * c.d_ff
        params_mult, attn_mult = 6.0, 12.0
        if remat_credited:
            attn_mult = 16.0
            if c.remat_policy == "full":
                params_mult = 8.0
        return (params_mult * n_params * seq
                + attn_mult * c.n_layers * c.d_model * seq * seq)

    def _remat_policy(self):
        """config.remat_policy -> jax.checkpoint policy (None = save
        nothing, i.e. full recompute)."""
        if self.config.remat_policy == "dots":
            return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        return None

    def init_params(self, rng: jax.Array | int = 0) -> dict[str, Array]:
        c = self.config
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        params: dict[str, Array] = {}
        for name, shape in self.param_shapes().items():
            rng, sub = jax.random.split(rng)
            if name.endswith("/scale"):
                params[name] = jnp.ones(shape, c.dtype)
            elif (name.endswith(("/bias", "/b1", "/b2", "/bq", "/bk",
                                 "/bv", "/bo"))):
                params[name] = jnp.zeros(shape, c.dtype)
            elif name in ("embed/tok", "embed/pos"):
                params[name] = jax.random.normal(sub, shape, c.dtype) * 0.02
            else:
                # fan-in: leading dim for 2D weights, middle dim for the
                # per-expert [E, in, out] MoE weights
                fan_in = shape[-2] if len(shape) == 3 else shape[0]
                scale = 1.0 / math.sqrt(fan_in)
                # residual-output projections get depth-scaled init
                if name.endswith(("attn/wo", "mlp/w2", "moe/w2")):
                    scale /= math.sqrt(2.0 * c.n_layers)
                params[name] = jax.random.normal(sub, shape, c.dtype) * scale
        return params

    # ------------------------------------------------------------ forward
    def _constrain(self, x: Array, *spec) -> Array:
        if self.mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, PartitionSpec(*spec)))

    def apply(self, params: Mapping[str, Array], tokens: Array) -> Array:
        """tokens [B, S] int32 -> logits [B, S, vocab] float32."""
        h, _, _ = self._forward(params, tokens, collect_kv=False)
        return self.final_logits(params, h)

    def apply_collect_kv(self, params: Mapping[str, Array],
                         tokens: Array) -> tuple[Array, list]:
        """Forward that also returns each layer's post-rope (k, v) — the
        prefill half of KV-cached generation (models/generation.py)."""
        h, kvs, _ = self._forward(params, tokens, collect_kv=True)
        return self.final_logits(params, h), kvs

    # --- shared layer pieces (used by _forward AND generation.decode_step,
    # so the layer math exists exactly once) -----------------------------
    def _norm(self, params: Mapping[str, Array], key: str, x: Array) -> Array:
        """rms_norm or layer_norm per config — ``key`` is the ln prefix
        (e.g. "layer0/ln1")."""
        c = self.config
        if c.norm == "layernorm":
            return layer_norm(x, params[f"{key}/scale"],
                              params[f"{key}/bias"], c.norm_eps)
        return rms_norm(x, params[f"{key}/scale"], c.norm_eps)

    def qkv(self, params: Mapping[str, Array], prefix: str, h: Array,
            positions: Array) -> tuple[Array, Array, Array]:
        """ln1 -> q/k/v projections (+ biases) -> head split -> rope (or
        pass-through under learned positions).  h: [B, S, d].
        K/V come back with ``kv_heads`` heads (UNexpanded under GQA — the
        cache-friendly form); expand to the query head count with
        :func:`repeat_kv` before a plain attention kernel."""
        c = self.config
        batch, seq = h.shape[:2]
        x = self._norm(params, f"{prefix}/ln1", h)
        # wdot: contracts against int8 QTensor weights too (serving quant)
        dot = partial(wdot, preferred_element_type=jnp.float32)
        q = dot(x, params[f"{prefix}/attn/wq"])
        k = dot(x, params[f"{prefix}/attn/wk"])
        v = dot(x, params[f"{prefix}/attn/wv"])
        if c.bias:
            q = q + params[f"{prefix}/attn/bq"].astype(jnp.float32)
            k = k + params[f"{prefix}/attn/bk"].astype(jnp.float32)
            v = v + params[f"{prefix}/attn/bv"].astype(jnp.float32)
        q = q.astype(c.dtype).reshape(batch, seq, c.n_heads, c.head_dim)
        k = k.astype(c.dtype).reshape(batch, seq, c.kv_heads, c.head_dim)
        v = v.astype(c.dtype).reshape(batch, seq, c.kv_heads, c.head_dim)
        if c.pos_emb == "learned":
            # learned positions live in the residual stream (embed/pos,
            # added at embedding time) — K/V need no positional transform
            return q, k, v
        return (rope(q, positions, c.rope_theta),
                rope(k, positions, c.rope_theta), v)

    def attn_residual(self, params: Mapping[str, Array], prefix: str,
                      h: Array, attn: Array) -> Array:
        """h + wo(attn) (+ bias).  attn: [B, S, H, D]."""
        c = self.config
        batch, seq = h.shape[:2]
        out = wdot(attn.reshape(batch, seq, c.d_model),
                   params[f"{prefix}/attn/wo"],
                   preferred_element_type=jnp.float32)
        if c.bias:
            out = out + params[f"{prefix}/attn/bo"].astype(jnp.float32)
        return h + out.astype(c.dtype)

    def mlp_residual(self, params: Mapping[str, Array], prefix: str,
                     h: Array) -> Array:
        """h + w2(gelu(w1(ln2(h)))) (+ biases), or the SwiGLU gated form
        h + w2(silu(w1 x) * (w3 x)) under ``mlp_act="swiglu"``."""
        c = self.config
        dot = partial(wdot, preferred_element_type=jnp.float32)
        x = self._norm(params, f"{prefix}/ln2", h)
        ff = dot(x, params[f"{prefix}/mlp/w1"])
        if c.bias:
            ff = ff + params[f"{prefix}/mlp/b1"].astype(jnp.float32)
        if c.mlp_act == "swiglu":
            up = dot(x, params[f"{prefix}/mlp/w3"]).astype(c.dtype)
            ff = jax.nn.silu(ff.astype(c.dtype)) * up
        else:
            ff = jax.nn.gelu(ff.astype(c.dtype))
        out = dot(ff, params[f"{prefix}/mlp/w2"])
        if c.bias:
            out = out + params[f"{prefix}/mlp/b2"].astype(jnp.float32)
        return h + out.astype(c.dtype)

    def layer_view(self, params: Mapping[str, Array],
                   layer: int) -> tuple[Mapping[str, Array], str]:
        """(param view, key prefix) for one layer in either layout: the
        store itself with prefix ``layer<i>`` when unrolled, or a sliced
        ``blk/*`` view of the stacked ``blocks/*`` arrays under
        ``scan_layers`` — so per-layer consumers (generation's decode
        loop) work on both layouts."""
        if self.config.scan_layers:
            return ({f"blk/{name[len('blocks/'):]}": value[layer]
                     for name, value in params.items()
                     if name.startswith("blocks/")}, "blk")
        return params, f"layer{layer}"

    def ffn_residual(self, params: Mapping[str, Array], layer: int,
                     h: Array, decode: bool = False) -> tuple[Array, Array]:
        """The layer's FFN branch: dense MLP or Switch MoE per the config.
        Returns (new_h, aux_loss) — aux is 0 for dense layers.  ``decode``
        runs MoE drop-free (capacity = token count): capacity dropping is a
        batch-global training mechanism and cannot be reproduced causally
        during KV-cached decoding."""
        if not self.config.is_moe_layer(layer):
            lp, p = self.layer_view(params, layer)
            return self.mlp_residual(lp, p, h), jnp.zeros((), jnp.float32)
        p = f"layer{layer}"
        x = self._norm(params, f"{p}/ln2", h)
        cap = h.shape[0] * h.shape[1] if decode else None
        moe_out, aux = self._moe.apply(params, x, prefix=f"{p}/",
                                       capacity_override=cap)
        return h + moe_out.astype(self.config.dtype), aux

    def final_logits(self, params: Mapping[str, Array], h: Array) -> Array:
        h = self._norm(params, "final_ln", h)
        return wdot(h, params["lm_head/w"],
                    preferred_element_type=jnp.float32)

    def embed(self, params: Mapping[str, Array], tokens: Array,
              positions: Array) -> Array:
        """Token (+ learned positional) embedding — the single definition
        shared by the training forward and cached decode, so the two can
        never disagree about where position information enters.

        mode="clip" on the positional gather: batched speculative
        decoding's finished rows intentionally overshoot max_seq (their
        outputs land in discarded slack lanes) and jnp.take's default
        would fill NaN there, poisoning the row's whole forward.  The
        REAL out-of-range case (a user decoding past max_seq) is rejected
        loudly at the entry points (generate / DecodeServer.submit /
        speculative_generate_batched), not silently clamped here."""
        h = jnp.take(params["embed/tok"], tokens, axis=0)
        if self.config.pos_emb == "learned":
            h = h + jnp.take(params["embed/pos"], positions, axis=0,
                             mode="clip").astype(h.dtype)
        return h

    def _forward(self, params: Mapping[str, Array], tokens: Array,
                 collect_kv: bool) -> tuple[Array, list, Array]:
        c = self.config
        batch, seq = tokens.shape
        if c.pos_emb == "learned" and seq > c.max_seq:
            # static shapes: this fires at trace time, before any compute.
            # Without it, embed's clip would silently reuse the last
            # position row for every overflow position — wrong logits AND
            # gradients (HF torch raises IndexError on the same input)
            raise ValueError(
                f"sequence length {seq} exceeds the learned-position "
                f"table max_seq={c.max_seq}")
        positions = jnp.arange(seq, dtype=jnp.int32)[None, :].repeat(batch, 0)
        h = self.embed(params, tokens, positions)
        h = self._constrain(h, ("data", "fsdp"), "seq", None)
        kvs: list = []
        aux_total = jnp.zeros((), jnp.float32)

        def layer_body(layer_params, i, h, p=None):
            p = f"layer{i}" if p is None else p
            q, k, v = self.qkv(layer_params, p, h, positions)
            # K/V go to the attention fn UNexpanded (kv_heads-sized);
            # each implementation expands at the math (expand_gqa), so
            # ring/Ulysses communicate the small tensors
            attn = self.attention_fn(q, k, v)
            h = self.attn_residual(layer_params, p, h, attn)
            h = self._constrain(h, ("data", "fsdp"), "seq", None)
            if i is None:  # scan body: homogeneous dense layers
                h = self.mlp_residual(layer_params, p, h)
                aux = jnp.zeros((), jnp.float32)
            else:
                h, aux = self.ffn_residual(layer_params, i, h)
            h = self._constrain(h, ("data", "fsdp"), "seq", None)
            return h, aux, (k, v)

        if c.scan_layers:
            # one scan body traced once, block weights streamed from their
            # stacked [L, ...] arrays — compile cost is depth-independent
            blocks = {name[len("blocks/"):]: value
                      for name, value in params.items()
                      if name.startswith("blocks/")}

            def scan_body(h, blk):
                view = {f"blk/{suffix}": value
                        for suffix, value in blk.items()}
                h, aux, kv = layer_body(view, None, h, p="blk")
                return h, (kv if collect_kv else aux)

            if c.remat and not collect_kv:
                # scan's internals already rule out the CSE hazard that
                # jax.checkpoint's default prevent_cse=True guards against;
                # the default would insert optimization barriers per step
                scan_body = jax.checkpoint(scan_body, prevent_cse=False,
                                           policy=self._remat_policy())
            h, ys = jax.lax.scan(scan_body, h, blocks)
            if collect_kv:
                k_stack, v_stack = ys  # [L, B, S, H, D] each
                kvs = [(k_stack[i], v_stack[i]) for i in range(c.n_layers)]
            else:
                aux_total = jnp.sum(ys)
            return h, kvs, aux_total

        # remat recomputes layer activations in the backward pass (O(1)
        # layers of residuals); never combined with collect_kv, which
        # exists to SAVE per-layer tensors (generation prefill)
        if c.remat and not collect_kv:
            body = jax.checkpoint(
                lambda lp, i, h: layer_body(lp, i, h)[:2],
                static_argnums=(1,), policy=self._remat_policy())
        else:
            body = None
        for i in range(c.n_layers):
            if body is not None:
                h, aux = body(params, i, h)
            else:
                h, aux, kv = layer_body(params, i, h)
                if collect_kv:
                    kvs.append(kv)
            aux_total = aux_total + aux
        return h, kvs, aux_total

    def loss(self, params: Mapping[str, Array], batch) -> Array:
        """Next-token cross-entropy (+ MoE load-balance aux when
        configured).  batch: [B, S] int32 tokens (or a (tokens,) tuple)."""
        tokens = batch[0] if isinstance(batch, (tuple, list)) else batch
        # run the full sequence (keeps the seq length shard-divisible for
        # sequence parallelism) and drop the last position's logits
        h, _, aux = self._forward(params, tokens, collect_kv=False)
        if self.config.loss_chunk:
            nll = self._chunked_next_token_nll(params, h, tokens)
        else:
            nll = next_token_nll(self.final_logits(params, h), tokens)
        return nll + self.config.moe_aux_coef * aux

    def _chunked_next_token_nll(self, params: Mapping[str, Array],
                                h: Array, tokens: Array) -> Array:
        """Mean next-token NLL with the LM head computed in seq chunks of
        ``config.loss_chunk`` positions under jax.checkpoint: peak logits
        memory is O(chunk * vocab) instead of O(S * vocab), with the chunk
        recomputed in the backward pass.  Numerically identical to the
        unchunked loss (tested)."""
        c = self.config
        batch, seq = tokens.shape
        chunk = c.loss_chunk
        if seq % chunk:
            raise ValueError(
                f"loss_chunk={chunk} must divide seq len {seq}")
        n_chunks = seq // chunk
        # shift targets; the final position has no target (masked out)
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((batch, 1), tokens.dtype)], axis=1)
        valid = (jnp.arange(seq) < seq - 1).astype(jnp.float32)
        h_chunks = jnp.moveaxis(
            h.reshape(batch, n_chunks, chunk, h.shape[-1]), 1, 0)
        t_chunks = jnp.moveaxis(targets.reshape(batch, n_chunks, chunk), 1, 0)
        v_chunks = valid.reshape(n_chunks, chunk)

        @jax.checkpoint
        def chunk_nll_sum(h_c, t_c, v_c):
            logp = jax.nn.log_softmax(self.final_logits(params, h_c), axis=-1)
            nll = -jnp.take_along_axis(
                logp, t_c[..., None].astype(jnp.int32), axis=-1)[..., 0]
            return jnp.sum(nll * v_c[None, :])

        def body(carry, xs):
            return carry + chunk_nll_sum(*xs), None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                (h_chunks, t_chunks, v_chunks))
        return total / (batch * (seq - 1))


def stack_layers(params: Mapping[str, Array], n_layers: int) -> dict:
    """Convert an unrolled store (``layer<i>/<suffix>``) to the stacked
    ``scan_layers`` layout (``blocks/<suffix>`` with leading [L]) — e.g.
    to load a checkpoint trained unrolled into a scanned model.  Dense
    layers only (stacking requires homogeneous blocks)."""
    out: dict = {}
    by_suffix: dict[str, list] = {}
    for i in range(n_layers):
        prefix = f"layer{i}/"
        for name, value in params.items():
            if name.startswith(prefix):
                by_suffix.setdefault(name[len(prefix):], []).append(value)
    for suffix, values in by_suffix.items():
        if len(values) != n_layers:
            raise ValueError(
                f"suffix {suffix!r} present in {len(values)}/{n_layers} "
                f"layers — stacking requires homogeneous blocks")
        out[f"blocks/{suffix}"] = jnp.stack(values)
    for name, value in params.items():
        if not name.startswith("layer"):
            out[name] = value
    return out


def unstack_layers(params: Mapping[str, Array]) -> dict:
    """Inverse of :func:`stack_layers`: stacked ``blocks/*`` arrays back
    to per-layer ``layer<i>/*`` entries."""
    out: dict = {}
    for name, value in params.items():
        if name.startswith("blocks/"):
            suffix = name[len("blocks/"):]
            for i in range(value.shape[0]):
                out[f"layer{i}/{suffix}"] = value[i]
        else:
            out[name] = value
    return out


def transformer_rule(mesh: Mesh):
    """Sharding rule for transformer stores: Megatron TP + fsdp (+ EP).

    column-parallel (tensor on output dim): wq wk wv w1 lm_head
    row-parallel  (tensor on input dim):    wo w2
    vocab-sharded embedding; norm scales replicated (fsdp if divisible);
    MoE expert weights sharded over the ``expert`` axis (router replicated).
    """
    n_fsdp = mesh.shape["fsdp"]
    n_tp = mesh.shape["tensor"]
    n_exp = mesh.shape.get("expert", 1)

    def rule(name: str, shape: tuple[int, ...]) -> PartitionSpec:
        if "/moe/router/" in name:
            return PartitionSpec()
        if "/moe/w" in name:
            return moe_expert_weight_spec(name, shape, n_exp, n_tp, n_fsdp)
        def fsdp_on(axis: int, taken: int | None) -> list:
            spec: list = [None] * len(shape)
            if taken is not None:
                spec[taken] = "tensor"
            if n_fsdp > 1 and shape[axis] % n_fsdp == 0 and axis != taken:
                spec[axis] = "fsdp"
            return spec

        # in/out weight dims are the trailing two; stacked scan-layer
        # weights (blocks/*, [L, in, out]) keep their leading layer dim
        # unsharded — it is the scan axis, and sharding it would gather
        # one shard's slice every scan step
        if name.endswith(("attn/wq", "attn/wk", "attn/wv", "mlp/w1",
                          "mlp/w3", "lm_head/w")):
            taken = len(shape) - 1 if n_tp > 1 and shape[-1] % n_tp == 0 else None
            return PartitionSpec(*fsdp_on(len(shape) - 2, taken))
        if name.endswith(("attn/wo", "mlp/w2")):
            taken = (len(shape) - 2
                     if n_tp > 1 and shape[-2] % n_tp == 0 else None)
            return PartitionSpec(*fsdp_on(len(shape) - 1, taken))
        if name == "embed/tok":
            # TP goes d_model-wise, never vocab(row)-wise: a TENSOR-sharded
            # vocab axis makes GSPMD fall back to "involuntary full
            # rematerialization" (replicate + repartition) on every lookup,
            # because the gather output wants a different sharding.  fsdp on
            # the vocab axis is fine — ZeRO storage sharding costs one
            # params all-gather per step (verified: 0 remat warnings vs 4
            # for tensor-on-vocab on a 2x2x2 mesh).
            taken = (len(shape) - 1
                     if n_tp > 1 and shape[-1] % n_tp == 0 else None)
            return PartitionSpec(*fsdp_on(0, taken))
        if name.endswith(("/scale", "/bias", "/bq", "/bk", "/bv", "/bo",
                          "/b1", "/b2")):
            # norm scales and all biases: tiny 1-D vectors, replicated like
            # their paired scales (an fsdp-sharded bias would force a
            # per-use all-gather against its tensor-sharded activation)
            return PartitionSpec()
        if name == "embed/pos":
            # small [max_seq, d_model] table gathered per position —
            # replicate rather than reshard every lookup
            return PartitionSpec()
        # fallback: fsdp on largest divisible dim
        spec: list = [None] * len(shape)
        for axis in sorted(range(len(shape)), key=lambda a: -shape[a]):
            if n_fsdp > 1 and shape[axis] % n_fsdp == 0:
                spec[axis] = "fsdp"
                break
        return PartitionSpec(*spec)

    return rule


def small_lm(vocab: int = 1024, seq: int = 256, dtype=jnp.float32,
             remat: bool = False, scan_layers: bool = False,
             n_layers: int = 2) -> Transformer:
    """Test-scale LM (``small_lm4`` in the registry is the 4-layer variant
    — deep enough for pipe x virtual-stage factorizations)."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=128, n_heads=4, n_layers=n_layers, d_ff=512,
        max_seq=seq, dtype=dtype, remat=remat, scan_layers=scan_layers))


def tiny_lm(vocab: int = 1024, seq: int = 256, dtype=jnp.float32,
            remat: bool = False, scan_layers: bool = False) -> Transformer:
    """1-layer draft-scale LM (same default vocab as small_lm, so the pair
    works as a speculative-decoding target/draft out of the box)."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=64, n_heads=2, n_layers=1, d_ff=256,
        max_seq=seq, dtype=dtype, remat=remat, scan_layers=scan_layers))


def lm_350m(vocab: int = 32000, seq: int = 1024, dtype=jnp.bfloat16,
            remat: bool = True, scan_layers: bool = False,
            kv_heads: int = 0, n_heads: int = 16,
            remat_policy: str = "full") -> Transformer:
    """~370M-param GPT-style flagship for the LM MFU benchmark: 24 layers,
    d_model 1024, seq 1024, bf16 weights/activations with f32 MXU
    accumulation, per-layer remat by default (activation memory, not HBM
    capacity, should bound the batch), chunked cross-entropy (peak f32
    logits ~1 GB -> ~32 MB at batch 8).  ``scan_layers`` stores blocks
    stacked and scans the layer loop — depth-independent compile time.
    ``kv_heads`` in {1, 2, 4, 8} switches to GQA (0, the default, keeps
    all 16; the `lm_350m_gqa` registry entry uses 4): kv_heads/16 the
    KV-cache HBM and ring/Ulysses ICI bytes, and the GQA-folded flash
    kernel keeps K/V unexpanded end to end."""
    # n_heads=8 gives head_dim 128 — a full MXU tile per attention
    # matmul (head_dim 64 halves MXU utilization; the r02 on-chip flash
    # measurement showed it) — same parameter count either way
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=1024, n_heads=n_heads, n_layers=24, d_ff=4096,
        n_kv_heads=kv_heads, remat_policy=remat_policy,
        max_seq=seq, dtype=dtype, remat=remat, scan_layers=scan_layers,
        # largest chunk <= 128 dividing seq, so every seq stays valid
        loss_chunk=math.gcd(128, seq)))


def llama_350m(vocab: int = 32000, seq: int = 1024, dtype=jnp.bfloat16,
               remat: bool = True, scan_layers: bool = False,
               kv_heads: int = 4,
               remat_policy: str = "full") -> Transformer:
    """LLaMA-architecture sibling of :func:`lm_350m` (~350M params):
    SwiGLU gated MLP (d_ff scaled to 8/3·d keeping the parameter count
    near the GELU flagship), GQA kv_heads=4, RoPE/RMSNorm — exactly the
    shape :func:`models.hf.from_hf_llama` produces, so benches on this
    entry transfer to converted checkpoints."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=1024, n_heads=16, n_layers=24,
        d_ff=2816,  # ~8/3 * 1024, rounded to a 128-multiple for the MXU
        n_kv_heads=kv_heads, mlp_act="swiglu", remat_policy=remat_policy,
        max_seq=seq, dtype=dtype, remat=remat, scan_layers=scan_layers,
        loss_chunk=math.gcd(128, seq)))


def moe_lm(vocab: int = 1024, seq: int = 256, dtype=jnp.float32,
           remat: bool = False, top_k: int = 1) -> Transformer:
    """Test-scale MoE LM: every 2nd layer is an expert-routed FFN
    (``top_k=1`` Switch, ``top_k=2`` Mixtral-style)."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=128, n_heads=4, n_layers=4, d_ff=512,
        max_seq=seq, dtype=dtype, moe_every=2, moe_experts=4, remat=remat,
        moe_top_k=top_k))


def moe_350m(vocab: int = 32000, seq: int = 1024, dtype=jnp.bfloat16,
             remat: bool = True, top_k: int = 1,
             experts: int = 8) -> Transformer:
    """Flagship-scale MoE: the :func:`lm_350m` trunk (24L / d1024 /
    seq 1024) with every 2nd FFN expert-routed — ~350M ACTIVE params
    per token (Switch top-1) over ~1.07B total.  The sparse-scaling
    shape: serve-time compute of the dense flagship, ~3x its capacity.
    Pair with a mesh ``expert`` axis to shard the expert stacks
    (``--mesh=expert:4,data:2``); MFU is not reported for MoE configs
    (6*P overcounts inactive experts — flops_per_sample returns None),
    bench rows report samples/s."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=1024, n_heads=16, n_layers=24, d_ff=4096,
        max_seq=seq, dtype=dtype, remat=remat, moe_every=2,
        moe_experts=experts, moe_top_k=top_k,
        loss_chunk=math.gcd(128, seq)))


def switch_lm(vocab: int = 1024, seq: int = 256, dtype=jnp.float32,
              remat: bool = False, top_k: int = 1) -> Transformer:
    """Test-scale ALL-MoE LM (moe_every=1, the Switch/Mixtral layout):
    homogeneous expert blocks, so it composes with pipeline parallelism
    (parallel/pipeline.py requires uniform per-layer param sets)."""
    return Transformer(TransformerConfig(
        vocab=vocab, d_model=128, n_heads=4, n_layers=4, d_ff=512,
        max_seq=seq, dtype=dtype, moe_every=1, moe_experts=4, remat=remat,
        moe_top_k=top_k))
