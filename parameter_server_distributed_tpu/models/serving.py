"""Continuous-batching decode server: the serving runtime over the ragged
KV-cache machinery.

Static-shape TPU serving has a classic tension: the device wants one fixed
[B, ...] decode program compiled once, but requests arrive and finish at
arbitrary times.  The resolution (the pattern behind production LLM
servers) is **slot-based continuous batching**:

- the KV cache is allocated once with B slots;
- every device step decodes ALL B slots in one ragged ``decode_block``
  (per-row lengths — rows sit at different positions), one compiled
  program, no retraces;
- a request occupies a slot from submit to EOS/limit; a finished slot is
  immediately refillable by the next request via a prefill whose K/V are
  spliced into that slot's cache rows while the other slots' state is
  untouched — admission never pauses in-flight decodes.

Prefill pads prompts up to a power-of-two bucket so only a handful of
prefill programs ever compile.  Pad positions write garbage K/V beyond
the row's real length — harmless by construction: the ragged attention
mask hides positions >= length, and subsequent decode steps overwrite
exactly those cache rows.

The reference has no serving path at all (no model, no inference —
reference src/worker.cpp:316-329 fabricates 0.01-gradients); this is
TPU-native added capability alongside generation.py's one-shot decoders.
Composes with the int8 serving stack: ``cache_dtype="int8"`` quantizes
the slot cache (generation.QuantKVCache), and a models/quant.py
weight-quantized ``params`` store works unchanged.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import flight
from ..obs import stats as obs_stats
from .generation import (KVCache, QuantKVCache, _cached_runner,
                         _kv_quantize, _model_key, _spec_round_runner,
                         check_position_budget, decode_block, init_cache,
                         sample_token, sample_token_rowwise)
from .prefix_tree import PrefixTree, RowRef
from .transformer import Transformer

Array = jax.Array


@dataclasses.dataclass
class _Slot:
    request_id: int
    tokens: list[int]          # generated tokens so far
    max_new: int
    done: bool = False
    # per-request finish tokens checked alongside the server eos_id
    stop: frozenset = frozenset()


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _row_nbytes(row) -> int:
    """Device bytes pinned by one cached K/V row (native: (k, v); int8:
    (k8, v8, k_scale, v_scale)) — what the radix tree's byte-accounted
    LRU charges against PSDT_PREFIX_CACHE_BYTES."""
    return sum(int(leaf.nbytes) for leaf in row)


def _place_params(params, mesh, rule):
    """Place a (possibly int8-quantized) store on the mesh.  Dense leaves
    take the rule's spec directly; a QTensor's int8 matrix takes the spec
    of its own shape and the per-output-channel scale inherits the same
    mesh axes minus the contracted (-2) dim — so a tensor-column-sharded
    weight keeps its scale tensor-sharded alongside it and the wdot
    product needs no resharding."""
    from jax.sharding import NamedSharding, PartitionSpec

    from .quant import QTensor

    out = {}
    for name, value in params.items():
        if isinstance(value, QTensor):
            spec = rule(name, tuple(value.q.shape))
            # PartitionSpec may legally omit trailing replicated dims —
            # pad to full rank so the -2/-1 slicing below always refers
            # to the contracted/output axes
            axes = list(spec) + [None] * (value.q.ndim - len(spec))
            scale_axes = axes[:-2] + [axes[-1]]
            out[name] = QTensor(
                jax.device_put(value.q, NamedSharding(mesh, spec)),
                jax.device_put(value.scale,
                               NamedSharding(mesh,
                                             PartitionSpec(*scale_axes))))
        else:
            spec = rule(name, tuple(value.shape))
            out[name] = jax.device_put(value, NamedSharding(mesh, spec))
    return out


def _shard_cache(cache, mesh):
    """Place the slot cache on the mesh: batch over ``data``, kv heads
    over ``tensor`` (where divisible), everything else replicated.  K/V
    leaves are [L, B, M, H, D]; int8 scale leaves [L, B, M, H]; length
    is scalar."""
    from jax.sharding import NamedSharding, PartitionSpec

    def place(leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim < 4:
            spec = PartitionSpec()
        else:
            data = ("data" if mesh.shape.get("data", 1) > 1
                    and leaf.shape[1] % mesh.shape["data"] == 0 else None)
            tensor = ("tensor" if mesh.shape.get("tensor", 1) > 1
                      and leaf.shape[3] % mesh.shape["tensor"] == 0
                      else None)
            spec = PartitionSpec(*([None, data, None, tensor]
                                   + [None] * (ndim - 4)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, cache)


def _prefill_runner(model: Transformer, bucket: int, cache_dtype: str):
    """Jitted per (model, prompt bucket): forward the padded prompt, return
    the last REAL position's logits and the prompt's K/V stack (quantized
    already when the slot cache is int8, so splicing is dtype-pure)."""
    key = (_model_key(model), "serve_prefill", bucket, cache_dtype)

    def build():
        @jax.jit
        def run(params, padded, real_len):
            logits, kvs = model.apply_collect_kv(params, padded)
            last = logits[0, real_len - 1]                  # [vocab]
            k = jnp.stack([k for k, _ in kvs])[:, 0]        # [L, S', H, D]
            v = jnp.stack([v for _, v in kvs])[:, 0]
            if cache_dtype == "int8":
                k8, ks = _kv_quantize(k)
                v8, vs = _kv_quantize(v)
                return last, (k8, v8, ks, vs)
            return last, (k, v)

        return run

    return _cached_runner(key, build)


def _splice_runner(model: Transformer, bucket: int, cache_dtype: str):
    """Jitted per (model, bucket): write one prefilled row's K/V into slot
    ``slot`` of the batch cache (dynamic slot index — one program serves
    every slot)."""
    key = (_model_key(model), "serve_splice", bucket, cache_dtype)

    def build():
        # donate the cache: the host drops its old reference immediately,
        # so XLA may update the (large) K/V buffers in place
        @partial(jax.jit, donate_argnums=(0,))
        def run(cache, row, slot):
            if cache_dtype == "int8":
                k8, v8, ks, vs = row
                return QuantKVCache(
                    k=jax.lax.dynamic_update_slice(
                        cache.k, k8[:, None], (0, slot, 0, 0, 0)),
                    v=jax.lax.dynamic_update_slice(
                        cache.v, v8[:, None], (0, slot, 0, 0, 0)),
                    k_scale=jax.lax.dynamic_update_slice(
                        cache.k_scale, ks[:, None], (0, slot, 0, 0)),
                    v_scale=jax.lax.dynamic_update_slice(
                        cache.v_scale, vs[:, None], (0, slot, 0, 0)),
                    length=cache.length)
            k, v = row
            return KVCache(
                k=jax.lax.dynamic_update_slice(
                    cache.k, k[:, None].astype(cache.k.dtype),
                    (0, slot, 0, 0, 0)),
                v=jax.lax.dynamic_update_slice(
                    cache.v, v[:, None].astype(cache.v.dtype),
                    (0, slot, 0, 0, 0)),
                length=cache.length)

        return run

    return _cached_runner(key, build)


def _extend_runner(model: Transformer, pbucket: int, sbucket: int,
                   cache_dtype: str):
    """Jitted per (model, prefix bucket, suffix bucket): extend a cached
    prefix row by forwarding ONLY the suffix tokens against it — the
    shared-prefix half of the prompt cache.  The suffix runs through the
    same ragged ``decode_block`` a decode round uses (a [1, sbucket]
    block against a single-row cache seeded with the prefix K/V), so the
    suffix's K/V and logits are exactly what submitting the prefix and
    then decoding forward would have computed; pad positions past the
    real suffix write garbage beyond the frontier, masked and
    overwritten exactly like prefill pad positions.  Returns the last
    REAL suffix position's logits and the combined (prefix + suffix)
    K/V row, ready for the ordinary slot splice."""
    key = (_model_key(model), "serve_extend", pbucket, sbucket,
           cache_dtype)
    total = pbucket + sbucket

    def build():
        @jax.jit
        def run(params, row, padded_suffix, prefix_len, suffix_len):
            if cache_dtype == "int8":
                k8, v8, ks, vs = row
                layers, _, heads, dim = k8.shape
                cache = QuantKVCache(
                    k=jnp.zeros((layers, 1, total, heads, dim),
                                jnp.int8).at[:, 0, :pbucket].set(k8),
                    v=jnp.zeros((layers, 1, total, heads, dim),
                                jnp.int8).at[:, 0, :pbucket].set(v8),
                    k_scale=jnp.ones((layers, 1, total, heads),
                                     jnp.float32)
                    .at[:, 0, :pbucket].set(ks),
                    v_scale=jnp.ones((layers, 1, total, heads),
                                     jnp.float32)
                    .at[:, 0, :pbucket].set(vs),
                    length=jnp.zeros((), jnp.int32))
            else:
                k, v = row
                layers, _, heads, dim = k.shape
                dtype = model.config.dtype
                cache = KVCache(
                    k=jnp.zeros((layers, 1, total, heads, dim), dtype)
                    .at[:, 0, :pbucket].set(k.astype(dtype)),
                    v=jnp.zeros((layers, 1, total, heads, dim), dtype)
                    .at[:, 0, :pbucket].set(v.astype(dtype)),
                    length=jnp.zeros((), jnp.int32))
            logits, cache = decode_block(model, params, padded_suffix,
                                         cache,
                                         lengths=prefix_len[None])
            last = logits[0, suffix_len - 1]
            if cache_dtype == "int8":
                return last, (cache.k[:, 0], cache.v[:, 0],
                              cache.k_scale[:, 0], cache.v_scale[:, 0])
            return last, (cache.k[:, 0], cache.v[:, 0])

        return run

    return _cached_runner(key, build)


def _step_runner(model: Transformer, slots: int,
                 top_k: int, top_p: float, cache_dtype: str):
    """Jitted once per (model, B, truncation config): one ragged decode
    step over ALL slots + per-row-temperature sampling (temperatures are
    a traced [B] input, so per-request values never recompile).  Free/
    done slots decode garbage lanes that the host discards — the price
    of a single static program."""
    key = (_model_key(model), "serve_step", slots, top_k, top_p,
           cache_dtype)

    def build():
        # donate the cache: without it every per-token step would copy the
        # whole [L, B, max_len, H, D] K/V — doubling HBM traffic in the
        # exact loop this server exists to keep bandwidth-bound
        @partial(jax.jit, donate_argnums=(2,))
        def run(params, tokens, cache, lengths, temps, rng):
            return _decode_round(model, top_k, top_p, params, tokens,
                                 cache, lengths, temps, rng)

        return run

    return _cached_runner(key, build)


def _decode_round(model, top_k, top_p, params, tokens, cache, lengths,
                  temps, rng):
    """ONE plain decode round — the single definition both the per-round
    program (_step_runner) and the fused scan (_multi_step_runner) jit,
    so step_many's token-exactness vs a step() loop holds by
    construction (same decode_block -> rng split -> rowwise sample
    sequence)."""
    logits, cache = decode_block(model, params, tokens[:, None], cache,
                                 lengths=lengths)
    rng, sub = jax.random.split(rng)
    nxt = sample_token_rowwise(logits[:, 0], sub, temps, top_k, top_p)
    return nxt, cache, rng


def _multi_step_runner(model: Transformer, slots: int, top_k: int,
                       top_p: float, cache_dtype: str, n_rounds: int):
    """Jitted per (model, B, truncation, N): N plain decode rounds as ONE
    compiled lax.scan — rng split and per-round math identical to N
    calls of the single-step program, so outputs are token-exact vs a
    step() loop (tested).  The host lever for dispatch-bound serving:
    each step() round-trip costs a full host<->device dispatch (tens of
    ms through a tunneled device), and between admissions those rounds
    need no host decisions."""
    key = (_model_key(model), "serve_multistep", slots, top_k, top_p,
           cache_dtype, n_rounds)

    def build():
        @partial(jax.jit, donate_argnums=(2,))
        def run(params, tokens, cache, lengths, temps, rng):
            def body(carry, _):
                tokens, cache, lengths, rng = carry
                nxt, cache, rng = _decode_round(
                    model, top_k, top_p, params, tokens, cache, lengths,
                    temps, rng)
                return (nxt, cache, lengths + 1, rng), nxt

            (tokens, cache, lengths, rng), outs = jax.lax.scan(
                body, (tokens, cache, lengths, rng), None,
                length=n_rounds)
            return outs, tokens, cache, rng     # outs: [N, B]

        return run

    return _cached_runner(key, build)


class DecodeServer:
    """Slot-based continuous-batching decoder.

    >>> srv = DecodeServer(model, params, slots=8, max_len=2048)
    >>> rid = srv.submit([1, 2, 3], max_new_tokens=64)
    >>> while not srv.idle:
    ...     for request_id, token in srv.step():
    ...         ...                      # stream tokens as they decode
    >>> srv.result(rid)                  # full generation for a request

    Host-side state is per-slot bookkeeping only; all model math runs in
    three compiled programs (prefill-per-bucket, splice, step).  ``eos_id``
    frees a slot early; a freed slot is reused by the next ``submit``.
    """

    def __init__(self, model: Transformer, params: Mapping[str, Any],
                 slots: int = 8, max_len: int = 2048, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 cache_dtype: str = "native", seed: int = 0,
                 mesh=None, param_rule=None,
                 draft: Transformer | None = None, draft_params=None,
                 draft_len: int = 4, adaptive_draft: bool = True,
                 draft_cost_ratio: float = 0.5, prompt_cache: int = 0,
                 prefix_cache_bytes: int | None = None):
        """``mesh`` turns on multi-chip serving: params are placed under
        ``param_rule`` (default: models.transformer.transformer_rule —
        Megatron TP columns/rows + fsdp) and the slot cache is sharded
        batch-over-``data`` / kv-heads-over-``tensor`` where divisible;
        GSPMD then partitions the same three compiled programs, inserting
        the attention/MLP collectives.  Token-exact vs the single-device
        server for every weight/cache dtype combination (tested on the
        virtual mesh; int8 QTensor weights place their per-channel scale
        alongside the matrix's output sharding).

        ``draft`` turns on SPECULATIVE continuous batching: every step()
        runs one draft-propose/verify round over all slots, so each
        request advances 1..k+1 tokens per target forward at its own
        acceptance rate.  Greedy (default) stays token-exact vs the
        plain greedy server whatever the draft (tested);
        ``temperature>0`` applies the Leviathan/Chen rejection rule,
        preserving the target's sampling distribution (tested
        empirically); top_k/top_p do not combine.  The draft shares the
        cache dtype and mesh.

        ``adaptive_draft`` (default on) treats ``draft_len`` as the CAP
        and re-picks the per-round depth k every few rounds via
        generation.optimal_draft_depth: the EMA accept fraction inverts
        to per-proposal agreement p, and k* maximizes expected tokens
        per round cost (1 target forward + k drafts at
        ``draft_cost_ratio`` target-units each) — the controller that
        avoids the over-speculation regime where a fixed k=4 measured
        0.76x vs greedy (BASELINE.md).  Each k's round program is
        compiled once and cached; token-exactness is unaffected
        (speculative commits are exact at ANY depth).
        ``adaptive_draft=False`` pins k = draft_len.

        ``prompt_cache`` > 0 turns on the radix-tree prefix cache
        (models/prefix_tree.py): admitted prompts' prefill results
        (final-position logits + the prompt's K/V row, and the draft's
        row in speculative mode) are indexed token-by-token, so an
        identical resubmission skips the prefill entirely and only
        splices, while a prompt sharing ANY cached prefix — including
        the interior of a longer cached prompt — forwards only its
        suffix (vLLM-style prefix reuse, _extend_runner).  Token-exact:
        a cached row is exactly what the prefill would recompute
        (causal attention — see prefix_tree.py on handle sharing), and
        the first token is re-sampled per request, so per-request
        temperature still applies.  Cached rows pin device memory,
        bounded by byte-accounted LRU over tree nodes:
        ``prefix_cache_bytes`` (default env ``PSDT_PREFIX_CACHE_BYTES``,
        256 MiB) — a hit touches the whole ancestor path, so a hot
        shared prefix outlives its descendants' churn."""
        if prompt_cache < 0:
            raise ValueError(f"prompt_cache must be >= 0, "
                             f"got {prompt_cache}")
        self.model = model
        self.slots = slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.cache_dtype = cache_dtype
        self.mesh = mesh
        from .transformer import transformer_rule
        self._param_rule = (param_rule or transformer_rule(mesh)
                            if mesh is not None else None)
        if mesh is not None:
            params = _place_params(dict(params), mesh, self._param_rule)
        self.params = params
        self._n_swaps = 0  # live weight hot-swaps (swap_params)
        self._cache = init_cache(model, slots, max_len, cache_dtype)
        if mesh is not None:
            self._cache = _shard_cache(self._cache, mesh)
        self._lengths = np.zeros((slots,), np.int32)
        self._tokens = np.zeros((slots,), np.int32)
        self._slot: list[_Slot | None] = [None] * slots
        self._results: dict[int, list[int]] = {}
        self._next_id = 0
        # observability counters (the stats property)
        self._n_steps = 0
        self._n_emitted = 0
        self._n_requests = 0
        self._n_retired = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._plain_rounds = 0   # non-speculative rounds since last probe
        # obs-registry mirrors: serving health in the same process-wide
        # registry the RPC layer and train loops report to (obs/stats.py)
        self._obs_round = obs_stats.histogram("serve.round_s")
        self._obs_tokens = obs_stats.counter("serve.tokens")
        self._obs_active = obs_stats.gauge("serve.active_slots")
        self._obs_rate = obs_stats.gauge("serve.tokens_per_s")
        self._obs_accept = obs_stats.gauge("serve.accept_rate")
        # radix-tree prefix cache (ISSUE 20): token-level index over
        # cached K/V rows — exact hits replay, any shared prefix seeds
        # a suffix-only extension, byte-accounted LRU eviction.
        # prompt_cache_size > 0 stays the enable switch (the PR 14 flag
        # surface); the budget is bytes now, not entries.
        self.prompt_cache_size = prompt_cache
        budget = (int(prefix_cache_bytes) if prefix_cache_bytes is not None
                  else int(os.environ.get("PSDT_PREFIX_CACHE_BYTES",
                                          "268435456")))
        self._prefix_tree = PrefixTree(budget) if prompt_cache else None
        self._prompt_hits = 0
        # shared-PREFIX reuse: a miss whose prompt shares a cached
        # prefix forwards only the suffix (_extend_runner).  Speculative
        # mode extends the DRAFT row from the same tree node alongside
        # the target row (ISSUE 20 satellite — the PR 14 plain-mode-only
        # restriction is gone); a k==0-era ancestor without a draft row
        # falls back to a full draft prefill for the draft side only.
        self._prefix_hits = 0
        self._obs_prefix = obs_stats.counter("serve.prefix_hits")
        # prompt-phase accounting for the fleet bench's reuse ratio:
        # tokens actually forwarded in a prompt phase vs prompt tokens
        # admitted (exact hit: 0, extension: the suffix, miss: all)
        self._prefill_tokens = 0
        self._prompt_tokens = 0
        # params version tag (fleet/ version-skew bookkeeping): 0 = boot
        # weights; swap_params(version=...) stamps the published version
        # every subsequently decoded token is attributed to
        self.params_version = 0
        self._rng = jax.random.key(seed)
        self._step = _step_runner(model, slots, top_k, top_p, cache_dtype)
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        # per-slot sampling temperature (traced input to the step program;
        # submit(..., temperature=) overrides the server default per slot)
        self._temps = np.full((slots,), temperature, np.float32)
        # --- speculative mode state
        self.draft = draft
        self.draft_len = draft_len          # cap (verify-slack sizing)
        self.adaptive_draft = adaptive_draft
        if draft is not None:
            if top_k or top_p:
                raise ValueError("speculative serving supports greedy "
                                 "(default) or plain --temperature "
                                 "sampling; top_k/top_p must be off")
            if draft.config.vocab != model.config.vocab:
                raise ValueError(
                    f"vocab mismatch: target {model.config.vocab} vs "
                    f"draft {draft.config.vocab}")
            if draft_len < 1:
                raise ValueError("draft_len must be >= 1")
            if draft_params is None:
                raise ValueError("draft requires draft_params")
            if mesh is not None:
                draft_params = _place_params(
                    dict(draft_params), mesh,
                    param_rule or transformer_rule(mesh))
            self.draft_params = draft_params
            self._d_cache = init_cache(draft, slots, max_len, cache_dtype)
            if mesh is not None:
                self._d_cache = _shard_cache(self._d_cache, mesh)
            self._d_lengths = np.zeros((slots,), np.int32)  # pc per slot
            self._prev = np.zeros((slots,), np.int32)       # y per slot
            # current depth + adaptation state; one compiled round program
            # per depth, built lazily (cached in _cached_runner)
            self._k = min(2, draft_len) if adaptive_draft else draft_len
            self.draft_cost_ratio = draft_cost_ratio
            self._accept_ema: float | None = None
            self._rounds_since_adapt = 0
            self._ema_proposals = 0  # proposals folded into the EMA so far

    _ADAPT_EVERY = 4        # rounds between depth decisions
    _ADAPT_DECAY = 0.8      # EMA decay on the per-round accept fraction
    _MIN_DISABLE_PROPOSALS = 16  # EMA evidence required before k=0 allowed
    _REPROBE_AFTER_PLAIN = 64    # plain rounds between k=0 re-probes

    def _spec_round(self, *args):
        runner = _spec_round_runner(self.model, self.draft, self._k,
                                    self.cache_dtype,
                                    float(self._temperature))
        return runner(*args)

    def _adapt_depth(self, accepted: int, proposed: int) -> None:
        """Update the agreement estimate with this round's active-slot
        stats and re-pick k every _ADAPT_EVERY rounds via the shared
        expected-throughput controller (generation.optimal_draft_depth).
        The EMA runs in per-proposal-agreement space (each round's accept
        FRACTION is inverted at the depth it was measured at) so samples
        taken at different depths stay comparable.  Shortening when
        agreement is weak avoids over-speculation (k tokens drafted, few
        kept: wasted draft forwards AND a wider verify); deepening when
        it is strong converts cheap drafts into >1 token/verify."""
        if not self.adaptive_draft or not proposed:
            return
        from .generation import _invert_accept_fraction, optimal_draft_depth
        p_round = _invert_accept_fraction(accepted / proposed, self._k)
        self._accept_ema = (p_round if self._accept_ema is None else
                            self._ADAPT_DECAY * self._accept_ema
                            + (1.0 - self._ADAPT_DECAY) * p_round)
        self._ema_proposals += proposed
        self._rounds_since_adapt += 1
        if self._rounds_since_adapt < self._ADAPT_EVERY:
            return
        self._rounds_since_adapt = 0
        # the EMA is already p, so invert at k=1 (identity).  Disabling
        # (k=0) needs _MIN_DISABLE_PROPOSALS of evidence in the EMA: one
        # unlucky early round must not shut speculation off (ADVICE.md
        # round 5 — k=0 used to be permanent AND cheap to reach).
        self._k = optimal_draft_depth(
            self._accept_ema, 1, self.draft_len, self.draft_cost_ratio,
            allow_disable=self._ema_proposals >= self._MIN_DISABLE_PROPOSALS)
        if self._k == 0:
            self._plain_rounds = 0   # count plain rounds toward a re-probe

    def _maybe_rearm_speculation(self) -> None:
        """k=0 is no longer forever (ADVICE.md round 5): after
        _REPROBE_AFTER_PLAIN plain rounds, the next IDLE admission re-arms
        speculation at a probe depth of 1 with fresh adaptation state (the
        workload may have shifted toward the draft since the disable).
        Idle matters for correctness: requests admitted while k=0 skipped
        their draft prefill, so their draft-cache rows are holes — once
        idle, every active request after the rearm is admitted with a
        draft prefill again."""
        if (self.draft is None or not self.adaptive_draft or self._k > 0
                or not self.idle
                or self._plain_rounds < self._REPROBE_AFTER_PLAIN):
            return
        self._k = 1
        self._plain_rounds = 0
        self._accept_ema = None
        self._ema_proposals = 0
        self._rounds_since_adapt = 0

    # ------------------------------------------------------------- admin
    def swap_params(self, params: Mapping[str, Any], *,
                    version: int | None = None) -> None:
        """Hot-swap the model weights (live weight publication — a
        follower tracking a training run feeds fresh versions through
        here, cli/serve_main.py ``--follow``).  Call BETWEEN decode
        rounds from the serving thread: the compiled programs take the
        params as a traced input, so no retrace happens and the very
        next round reads the new weights.  In-flight requests keep
        their slots, KV rows, and sampling state — their already-emitted
        tokens stand and their continuations decode under the new
        weights, which is the point of tracking a live run (token
        streams are uninterrupted, not retroactively recomputed).

        The prefix cache is dropped: its prefill logits/KV rows were
        computed under the old weights, and replaying them would splice
        stale state next to fresh-weight decode steps.

        Raises on name/shape drift against the current params (an
        upstream model change mid-publication): the swap point is where
        callers catch a bad publication and keep the last-good weights
        (cli/serve_main.py maybe_swap) — without this check the mismatch
        would surface as a crash inside a later decode round."""
        current = {name: np.shape(arr)
                   for name, arr in self.params.items()}
        fresh = {name: np.shape(arr) for name, arr in params.items()}
        if current != fresh:
            drift = {name for name in (set(current) ^ set(fresh))} | {
                name for name in set(current) & set(fresh)
                if current[name] != fresh[name]}
            raise ValueError(
                f"published weights do not match the served model "
                f"(name/shape drift: {sorted(drift)[:4]}...)")
        if self.mesh is not None:
            params = _place_params(dict(params), self.mesh,
                                   self._param_rule)
        self.params = params
        if self._prefix_tree is not None:
            self._prefix_tree.clear()
        self._n_swaps += 1
        if version is not None:
            self.params_version = int(version)

    @property
    def idle(self) -> bool:
        return all(s is None for s in self._slot)

    @property
    def has_free_slot(self) -> bool:
        return self._free_slot() is not None

    @property
    def active(self) -> int:
        """Number of in-flight requests."""
        return sum(s is not None for s in self._slot)

    def _free_slot(self) -> int | None:
        for i, s in enumerate(self._slot):
            if s is None:
                return i
        return None

    def prefix_fingerprint(self) -> bytes:
        """Compact prefix fingerprint of the radix cache (packed chained
        CRC32 block hashes — prefix_tree.block_hashes) for the fleet
        heartbeat.  Safe to call from the heartbeat thread: it reads one
        immutable bytes snapshot the decode thread swaps in after each
        tree mutation.  Empty when the cache is off — the router's
        overlap term degrades to zero and PR 14 scoring stands."""
        tree = self._prefix_tree
        return tree.fingerprint if tree is not None else b""

    def _radix_extend(self, prompt: np.ndarray, real_len: int,
                      node, matched: int):
        """Shared-prefix extension from the deepest cached ancestor:
        forward only the suffix past the ``matched``-token tree prefix
        against the covering node's K/V row (_extend_runner).  Returns
        (last logits, combined row, draft row | None) or None (no
        usable prefix / combined row would not fit the slot cache —
        the caller full-prefills).  The suffix math is a ragged
        decode_block — exactly what decoding those tokens one round at
        a time would compute — so the continuation is decode-path-
        consistent by construction.  A prompt that IS a cached path
        (an interior split node with no replayable logits) caps the
        prefix at real_len - 1 and extends a single token.

        Speculative mode extends the draft row from the same node's
        draft handle; an ancestor admitted while the depth controller
        had speculation off carries no draft row, so the draft side
        (only) falls back to a full prefill — the target row still
        rides the suffix-only path."""
        plen = min(matched, real_len - 1)
        if plen <= 0 or node.handle is None:
            return None
        pre_row = node.handle.row
        pbucket = int(pre_row[0].shape[1])
        slen = real_len - plen
        sbucket = _bucket(slen)
        if pbucket + sbucket > self.max_len:
            return None  # combined row would overflow the slot cache
        padded = np.zeros((1, sbucket), np.int32)
        padded[0, :slen] = prompt[plen:]
        suffix = jnp.asarray(padded)
        plen_j = jnp.asarray(plen, jnp.int32)
        slen_j = jnp.asarray(slen, jnp.int32)
        last, row = _extend_runner(self.model, pbucket, sbucket,
                                   self.cache_dtype)(
            self.params, pre_row, suffix, plen_j, slen_j)
        d_row = None
        if self.draft is not None and self._k > 0:
            dpre = node.dhandle.row if node.dhandle is not None else None
            dbucket = int(dpre[0].shape[1]) if dpre is not None else 0
            if dpre is not None and dbucket + sbucket <= self.max_len:
                _, d_row = _extend_runner(self.draft, dbucket, sbucket,
                                          self.cache_dtype)(
                    self.draft_params, dpre, suffix, plen_j, slen_j)
            else:
                dbucket = min(_bucket(real_len), self.max_len)
                dpadded = np.zeros((1, dbucket), np.int32)
                dpadded[0, :real_len] = prompt
                _, d_row = _prefill_runner(self.draft, dbucket,
                                           self.cache_dtype)(
                    self.draft_params, jnp.asarray(dpadded),
                    jnp.asarray(real_len, jnp.int32))
        self._prefix_tree.touch(node)  # the whole ancestor path is hot
        self._prefill_tokens += slen
        return last, row, d_row

    def _admit_to_tree(self, pkey: tuple, last, row, d_row) -> None:
        """Insert an admitted prompt's rows into the radix tree (an
        edge split shares the descendant's handles — no device copy)
        and run the byte-budget LRU eviction pass."""
        tree = self._prefix_tree
        splits = tree.splits
        node = tree.insert(pkey, last, RowRef(row, _row_nbytes(row)),
                           RowRef(d_row, _row_nbytes(d_row))
                           if d_row is not None else None)
        if tree.splits != splits:
            flight.record("serve.prefix.split", a=node.depth,
                          b=tree.nodes)
        evicted = tree.evict_over_budget()
        if evicted:
            flight.record("serve.prefix.evict", a=evicted, b=tree.bytes)

    # ------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens: int = 64, *,
               temperature: float | None = None,
               stop=()) -> int:
        """Admit a request into a free slot (prefill + cache splice).
        Raises RuntimeError when every slot is busy — callers queue above
        this layer.  Returns the request id.

        ``temperature`` overrides the server default for THIS request
        (0.0 = greedy; temperatures are a traced per-slot input, so
        mixed-temperature batches share one compiled step).  Speculative
        mode bakes the temperature into the verify round's acceptance
        rule, so per-request overrides are rejected there.  ``stop`` is
        an iterable of token ids that finish this request, checked
        alongside the server ``eos_id``."""
        if temperature is not None and self.draft is not None \
                and temperature != self._temperature:
            raise ValueError(
                "per-request temperature is not supported in speculative "
                "mode (the accept rule is compiled for the server "
                "temperature); construct the server with the temperature "
                "you need")
        self._maybe_rearm_speculation()
        slot = self._free_slot()
        if slot is None:
            raise RuntimeError("no free slot; drain with step() first")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        # speculative mode: a verify round may write draft_len+1 entries
        # past the committed frontier before the host truncates
        slack = self.draft_len + 1 if self.draft is not None else 0
        if real_len + max_new_tokens + slack > self.max_len:
            raise ValueError(
                f"prompt {real_len} + max_new {max_new_tokens} (+ "
                f"speculative slack {slack}) exceeds cache max_len "
                f"{self.max_len}")
        check_position_budget(self.model, real_len,
                              max_new_tokens + slack)
        bucket = min(_bucket(real_len), self.max_len)
        if self.draft is not None:
            check_position_budget(self.draft, real_len,
                                  max_new_tokens + slack)
        tree = self._prefix_tree
        pkey = tuple(int(t) for t in prompt) if tree is not None else None
        hit = None
        anc, matched = None, 0
        if tree is not None:
            anc, matched, partial = tree.lookup(pkey)
            if (matched == real_len and not partial
                    and anc.last is not None):
                hit = anc  # whole-prompt node: replayable logits + row
        if hit is not None:
            tree.touch(hit)  # the whole ancestor path, not one entry
            self._prompt_hits += 1
            self._prompt_tokens += real_len
            last = hit.last
            row = hit.handle.row
            d_row = hit.dhandle.row if hit.dhandle is not None else None
            if self.draft is not None and self._k > 0 and d_row is None:
                # node was cached while the controller had speculation
                # off (k=0 skips the draft prefill below); replaying it
                # as-is after a re-probe re-armed k would skip the draft
                # splice and leave this slot's _d_lengths/_prev stale —
                # backfill the draft half and attach it to the node
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :real_len] = prompt
                _, d_row = _prefill_runner(self.draft, bucket,
                                           self.cache_dtype)(
                    self.draft_params, jnp.asarray(padded),
                    jnp.asarray(real_len, jnp.int32))
                self._admit_to_tree(pkey, last, row, d_row)
        else:
            # Shared-prefix extension serves the prompt phase whenever
            # the tree holds ANY prefix of this prompt — including the
            # interior of a longer cached prompt (the radix point) —
            # and in speculative mode the draft row extends alongside
            # the target row (_radix_extend), so spec admissions no
            # longer fall back to full prefill (ISSUE 20 satellite).
            extended = (self._radix_extend(prompt, real_len, anc, matched)
                        if tree is not None else None)
            if extended is not None:
                # only the suffix ran a forward; the combined row
                # splices below under its own (wider) width
                last, row, d_row = extended
                self._prefix_hits += 1
                self._obs_prefix.add()
                flight.record("serve.prefix.hit",
                              a=min(matched, real_len - 1),
                              b=real_len - min(matched, real_len - 1))
            else:
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :real_len] = prompt
                last, row = _prefill_runner(self.model, bucket,
                                            self.cache_dtype)(
                    self.params, jnp.asarray(padded),
                    jnp.asarray(real_len, jnp.int32))
                d_row = None
                self._prefill_tokens += real_len
                if self.draft is not None and self._k > 0:
                    # k=0 (controller disabled speculation): the draft
                    # cache is not read while disabled, so skip its
                    # prefill + splice; a later re-probe backfills via
                    # the cache-hit repair above
                    _, d_row = _prefill_runner(self.draft, bucket,
                                               self.cache_dtype)(
                        self.draft_params, jnp.asarray(padded),
                        jnp.asarray(real_len, jnp.int32))
            self._prompt_tokens += real_len
            if tree is not None:
                self._admit_to_tree(pkey, last, row, d_row)
        req_temp = self._temperature if temperature is None else temperature
        self._rng, sub = jax.random.split(self._rng)
        first = int(sample_token(last[None], sub, req_temp,
                                 self._top_k, self._top_p)[0])
        # splice widths come from the rows themselves: a radix-served
        # row is prefix-bucket + suffix-bucket wide, and the target and
        # draft rows may differ (each extended from its own ancestor
        # width)
        self._cache = _splice_runner(self.model, int(row[0].shape[1]),
                                     self.cache_dtype)(
            self._cache, row, jnp.asarray(slot, jnp.int32))
        if self.draft is not None and d_row is not None:
            self._d_cache = _splice_runner(self.draft,
                                           int(d_row[0].shape[1]),
                                           self.cache_dtype)(
                self._d_cache, d_row, jnp.asarray(slot, jnp.int32))
            self._d_lengths[slot] = real_len
            self._prev[slot] = int(prompt[-1])
        rid = self._next_id
        self._next_id += 1
        self._n_requests += 1
        entry = _Slot(request_id=rid, tokens=[first],
                      max_new=max_new_tokens, stop=frozenset(stop))
        self._slot[slot] = entry
        self._lengths[slot] = real_len
        self._tokens[slot] = first
        self._temps[slot] = req_temp
        if self._finishes(entry, first):
            self._retire(slot)
        return rid

    # -------------------------------------------------------------- step
    def step(self) -> list[tuple[int, int]]:
        """One device decode step over all slots (a speculative round when
        a draft is configured — each slot may advance several tokens).
        Returns [(request_id, token), ...] for every ACTIVE slot's newly
        decoded token(s) (already appended to its result)."""
        if self.idle:
            return []
        t0 = time.perf_counter()
        if self.draft is not None and self._k > 0:
            # k can reach 0 when the adaptive controller concludes this
            # draft cannot pay (optimal_draft_depth allow_disable) —
            # the server then serves plain greedy rounds below, which
            # read the same _tokens/_lengths state the spec rounds kept.
            # Disable is NOT forever: submit() re-probes at the next idle
            # admission boundary (see _maybe_rearm_speculation).
            emitted = self._spec_step()
            self._obs_record_round(t0, len(emitted))
            return emitted
        self._plain_rounds += 1
        nxt, self._cache, self._rng = self._step(
            self.params, jnp.asarray(self._tokens), self._cache,
            jnp.asarray(self._lengths), jnp.asarray(self._temps),
            self._rng)
        nxt = np.asarray(nxt)
        emitted: list[tuple[int, int]] = []
        for i, entry in enumerate(self._slot):
            if entry is None:
                continue
            token = int(nxt[i])
            entry.tokens.append(token)
            emitted.append((entry.request_id, token))
            # the step consumed self._tokens[i] at position lengths[i]
            self._lengths[i] += 1
            self._tokens[i] = token
            if self._finishes(entry, token):
                self._retire(i)
        self._n_steps += 1
        self._n_emitted += len(emitted)
        self._obs_record_round(t0, len(emitted))
        return emitted

    def step_many(self, max_rounds: int = 8) -> list[tuple[int, int]]:
        """Up to ``max_rounds`` decode rounds in ONE device dispatch
        (plain mode; speculative mode falls back to per-round step()s —
        its depth controller needs host decisions between rounds).

        Trades admission latency for dispatch overhead: new submissions
        wait until the fused rounds return, so call this when the
        admission queue is empty (bench_serve does between arrivals —
        the win is the per-round host<->device round-trip, tens of ms on
        tunneled devices).  The round count is clamped to the minimum
        remaining budget across active slots (then rounded down to a
        power of two — one compiled scan per size class), so no slot
        overshoots max_new; a row finishing EARLY (eos/stop) keeps decoding garbage
        into its own lane for the rest of the fused block, exactly like
        a retired lane does between rounds — host truncation discards
        those tokens and the splice on reuse resets the cache rows.
        Token-exact vs the equivalent step() loop (identical rng
        sequence and math; tested)."""
        if self.idle:
            return []
        t0 = time.perf_counter()
        if self.draft is not None and self._k > 0:
            emitted = self._spec_step()
            self._obs_record_round(t0, len(emitted))
            return emitted
        remaining = [entry.max_new - len(entry.tokens)
                     for entry in self._slot if entry is not None]
        n = max(1, min([max_rounds] + remaining))
        # round DOWN to a power of two: a mixed-budget drain would
        # otherwise compile a separate scan per distinct n (each compile
        # costs far more than the dispatches it saves); log2(max_rounds)
        # programs cover every clamp
        n = 1 << (n.bit_length() - 1)
        if n == 1:
            return self.step()
        runner = _multi_step_runner(self.model, self.slots, self._top_k,
                                    self._top_p, self.cache_dtype, n)
        outs, last, self._cache, self._rng = runner(
            self.params, jnp.asarray(self._tokens), self._cache,
            jnp.asarray(self._lengths), jnp.asarray(self._temps),
            self._rng)
        outs = np.asarray(outs)                   # [n, B]
        last = np.asarray(last)
        emitted: list[tuple[int, int]] = []
        for r in range(n):
            for i, entry in enumerate(self._slot):
                if entry is None:
                    continue
                token = int(outs[r, i])
                entry.tokens.append(token)
                emitted.append((entry.request_id, token))
                if self._finishes(entry, token):
                    # later fused rounds decoded garbage continuations
                    # for this lane; they are simply not appended
                    self._retire(i)
        # mirror what the device wrote: every lane (retired included)
        # advanced n positions and holds its last fused token
        self._lengths += n
        self._tokens[:] = last
        self._n_steps += n
        self._n_emitted += len(emitted)
        self._plain_rounds += n
        self._obs_record_round(t0, len(emitted))
        return emitted

    def _spec_step(self) -> list[tuple[int, int]]:
        """One speculative round: commit each slot's accepted prefix plus
        the target's correction token.  Free/garbage lanes advance their
        device-side frontiers like active ones (host state must mirror
        what the device wrote; a reused slot's splice resets both)."""
        (commit, n_commit, cur_new, y_new, self._cache, self._d_cache,
         self._rng) = self._spec_round(
            self.params, self.draft_params,
            jnp.asarray(self._tokens), jnp.asarray(self._prev),
            self._cache, self._d_cache,
            jnp.asarray(self._lengths), jnp.asarray(self._d_lengths),
            self._rng)
        commit = np.asarray(commit)
        n_commit = np.asarray(n_commit)
        cur_new = np.asarray(cur_new)
        y_new = np.asarray(y_new)
        emitted: list[tuple[int, int]] = []
        round_proposed = round_accepted = 0
        for i, entry in enumerate(self._slot):
            n = int(n_commit[i])
            if entry is not None:
                # active-slot acceptance stats: n-1 of this round's k
                # accepted (k is the adaptive depth, not the cap)
                round_proposed += self._k
                round_accepted += n - 1
                for t in commit[i, :n]:
                    token = int(t)
                    entry.tokens.append(token)
                    emitted.append((entry.request_id, token))
                    if self._finishes(entry, token):
                        # tokens past EOS/limit in this round's commit are
                        # discarded; the cache rows they wrote sit beyond
                        # the retired frontier and splice-reset on reuse
                        self._retire(i)
                        break
            self._lengths[i] += n
            self._d_lengths[i] += n
            self._tokens[i] = int(cur_new[i])
            self._prev[i] = int(y_new[i])
        self._spec_proposed += round_proposed
        self._spec_accepted += round_accepted
        self._adapt_depth(round_accepted, round_proposed)
        self._n_steps += 1
        self._n_emitted += len(emitted)
        return emitted

    def _obs_record_round(self, t0: float, n_tokens: int) -> None:
        """Mirror one decode round into the process-wide obs registry:
        round latency, emitted tokens, queue depth (active slots), the
        instantaneous token rate, and (speculative mode) the lifetime
        accept rate — what obs/export rolls up for pst-status."""
        dt = time.perf_counter() - t0
        self._obs_round.observe(dt)
        self._obs_tokens.add(n_tokens)
        self._obs_active.set(self.active)
        if dt > 0:
            self._obs_rate.set(n_tokens / dt)
        if self._spec_proposed:
            self._obs_accept.set(self._spec_accepted / self._spec_proposed)

    def _finishes(self, entry: _Slot, token: int) -> bool:
        return (len(entry.tokens) >= entry.max_new
                or (self.eos_id is not None and token == self.eos_id)
                or token in entry.stop)

    def cancel(self, request_id: int) -> bool:
        """Free an in-flight request's slot WITHOUT recording a result —
        the abandoned-stream reap (fleet/decode.py: the client is gone,
        so decoding its remaining budget would burn a slot into a queue
        nobody reads).  The lane decodes garbage until reused, exactly
        like a retired lane.  False when the id is not in flight."""
        for i, entry in enumerate(self._slot):
            if entry is not None and entry.request_id == request_id:
                self._slot[i] = None
                return True
        return False

    def _retire(self, slot: int) -> None:
        entry = self._slot[slot]
        entry.done = True
        self._results[entry.request_id] = entry.tokens
        self._slot[slot] = None
        self._n_retired += 1
        # lengths/tokens stay — the lane decodes garbage until reused;
        # the splice on reuse rewrites the cache rows that matter

    @property
    def stats(self) -> dict:
        """Serving counters since construction: device steps/rounds run,
        tokens emitted to active requests, requests admitted/completed,
        and (speculative mode) the measured draft acceptance rate."""
        out = {
            "steps": self._n_steps,
            "tokens_emitted": self._n_emitted,
            "requests_admitted": self._n_requests,
            "requests_completed": self._n_retired,
        }
        if self._n_swaps:
            out["weight_swaps"] = self._n_swaps
        if self.prompt_cache_size:
            out["prompt_cache_hits"] = self._prompt_hits
            out["prefix_hits"] = self._prefix_hits
            out["prefix_cache_nodes"] = self._prefix_tree.nodes
            out["prefix_cache_bytes"] = self._prefix_tree.bytes
            out["prefix_evictions"] = self._prefix_tree.evictions
        # prompt-phase reuse ratio inputs (fleet bench): tokens the
        # prompt phase actually forwarded vs prompt tokens admitted
        out["prefill_tokens"] = self._prefill_tokens
        out["prompt_tokens"] = self._prompt_tokens
        if self.draft is not None:
            out["draft_accept_rate"] = (
                self._spec_accepted / self._spec_proposed
                if self._spec_proposed else 0.0)
            out["tokens_per_round"] = (
                self._n_emitted / self._n_steps if self._n_steps else 0.0)
            out["draft_depth"] = self._k   # current adaptive depth
        return out

    # ------------------------------------------------------------ result
    def peek(self, request_id: int) -> list[int]:
        """Tokens generated so far for an IN-FLIGHT request (the prefill
        token appears here immediately after submit; finished requests
        live in result())."""
        for entry in self._slot:
            if entry is not None and entry.request_id == request_id:
                return list(entry.tokens)
        raise KeyError(f"request {request_id} is not in flight")

    def finished(self) -> list[int]:
        """Request ids whose results are ready to collect."""
        return list(self._results)

    def result(self, request_id: int) -> list[int]:
        """Generated tokens for a finished request (pops it)."""
        return self._results.pop(request_id)

    def run_to_completion(self) -> dict[int, list[int]]:
        """Drain all in-flight requests; returns {request_id: tokens}."""
        while not self.idle:
            self.step()
        out, self._results = self._results, {}
        return out
