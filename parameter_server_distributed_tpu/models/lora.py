"""LoRA: low-rank adaptation for parameter-efficient fine-tuning.

Fine-tunes a pretrained store (e.g. a :func:`models.hf.from_hf_gpt2` /
``from_hf_llama`` conversion) by training only a rank-r product added to
selected 2-D weights — W_eff = W + (alpha/r) * A @ B with A [in, r] and
B [r, out] (the paper writes the same product as B@A under its
transposed layout) — while the base weights stay frozen (Hu et al.,
LoRA).  The reference
framework has no fine-tuning story at all (no models — reference
src/worker.cpp:316-329); this makes converted checkpoints cheaply
adaptable on the PS/SPMD training stack.

Design: model-agnostic and zero-intrusion.  Adapters are ordinary store
entries (``<weight>/lora_a`` [in, r] and ``<weight>/lora_b`` [r, out])
living alongside the base weights in ONE params dict, so sharding rules,
checkpointing, and the PS protocol all see a plain store.  The loss
wrapper materializes W + scale*A@B per step (one rank-r matmul per
adapted weight — negligible FLOPs) and hands the model a store it cannot
distinguish from a dense one; the optimizer is masked so ONLY ``/lora_``
entries update.  ``merge_lora`` collapses adapters into the base weights
for serving/export — numerically identical to the adapted forward.
"""

from __future__ import annotations

import math
import re
from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array

A_SUFFIX = "/lora_a"
B_SUFFIX = "/lora_b"

# default adaptation targets: the attention q/v projections (the
# original-paper recipe); members of TransformerConfig naming, matched
# as name suffixes so layer prefixes and scan-stacked blocks both hit
DEFAULT_TARGETS = ("attn/wq", "attn/wv")


DEFAULT_ALPHA = 16.0


def init_lora(params: Mapping[str, Array], rank: int = 8,
              targets: Sequence[str] = DEFAULT_TARGETS,
              rng: jax.Array | int = 0) -> dict[str, Array]:
    """Return ``params`` + freshly-initialized adapter entries for every
    >=2-D weight whose name ends with one of ``targets``.  Leading axes
    are batch axes for per-slice factors: scan-stacked [L, in, out]
    blocks get [L, in, r] / [L, r, out], pipeline-restacked
    [P(,V), Lc, in, out] blocks get matching [P(,V), Lc, ...] factors —
    the adapters inherit the weight's layout, so sharding rules
    (transformer_rule, pipeline_rule) place them with their base weight.
    A is Gaussian / sqrt(in), B is zero — the adapted model starts
    EXACTLY at the base model."""
    if isinstance(rng, int):
        rng = jax.random.key(rng)
    matched = [name for name, w in params.items()
               if name.endswith(tuple(targets)) and jnp.ndim(w) >= 2]
    if not matched:
        raise ValueError(f"no parameters match LoRA targets {targets}; "
                         f"store has e.g. {sorted(params)[:5]}")
    out = dict(params)
    for name in matched:
        w = params[name]
        rng, sub = jax.random.split(rng)
        *lead, d_in, d_out = w.shape
        a_shape, b_shape = (*lead, d_in, rank), (*lead, rank, d_out)
        out[name + A_SUFFIX] = (jax.random.normal(sub, a_shape, w.dtype)
                                / math.sqrt(d_in))
        out[name + B_SUFFIX] = jnp.zeros(b_shape, w.dtype)
    return out


def lora_names(params: Mapping[str, Array]) -> list[str]:
    return [n for n in params if n.endswith((A_SUFFIX, B_SUFFIX))]


def _effective(params: Mapping[str, Array],
               alpha: float) -> dict[str, Array]:
    """Collapse adapters: {base + (alpha/r) * A @ B}, adapter entries
    removed.  The rank is READ FROM the stored A factor (its trailing
    dim), never passed — a rank argument that disagreed with the trained
    factors would silently mis-scale the merge.  Works on stacked
    [L, ...] factors via a batched matmul."""
    eff = {}
    for name, value in params.items():
        if name.endswith((A_SUFFIX, B_SUFFIX)):
            continue
        a = params.get(name + A_SUFFIX)
        if a is not None:
            b = params[name + B_SUFFIX]
            scale = alpha / a.shape[-1]
            delta = jnp.einsum("...ir,...ro->...io", a, b) * scale
            value = (value + delta).astype(value.dtype)
        eff[name] = value
    return eff


def lora_loss(base_loss: Callable,
              alpha: float = DEFAULT_ALPHA) -> Callable:
    """Wrap a model's ``loss(params, batch)``: the wrapped function takes
    the base+adapter store, materializes effective weights (rank read
    from the factors themselves), and calls the model unchanged.
    Differentiable end to end — gradients flow to A/B through the add;
    pair with :func:`trainable_mask` so the optimizer freezes everything
    else."""

    def loss(params: Mapping[str, Array], batch):
        return base_loss(_effective(params, alpha), batch)

    return loss


def lora_value_and_grad(grad_fn: Callable,
                        alpha: float = DEFAULT_ALPHA) -> Callable:
    """Compose LoRA with a model whose backward IS a schedule (the 1F1B
    pipeline's ``value_and_grad``) rather than jax.grad of a loss.

    The schedule computes (loss, grads) w.r.t. an EFFECTIVE dense store;
    differentiating through :func:`_effective` around it maps those
    cotangents back to (base, A, B) — d loss/dA = dW_eff @ B^T * scale and
    d loss/dB = A^T @ dW_eff * scale flow through the ``jax.vjp`` of the
    collapse, while the base-weight cotangents pass through unchanged
    (and are then frozen by :func:`freeze_base`).  The wrapped function
    has the same (params, batch) -> (loss, grads) contract, so
    ShardedTrainer uses it as a drop-in ``grad_fn``."""

    def value_and_grad(params: Mapping[str, Array], batch):
        eff, vjp = jax.vjp(lambda p: _effective(p, alpha), dict(params))
        loss, g_eff = grad_fn(eff, batch)
        (grads,) = vjp(g_eff)
        return loss, grads

    return value_and_grad


def trainable_mask(params: Mapping[str, Array]) -> dict[str, bool]:
    """True for adapter entries, False for frozen base weights — the
    shape optax.masked expects (matching the params dict)."""
    return {name: name.endswith((A_SUFFIX, B_SUFFIX)) for name in params}


def freeze_base(optimizer):
    """Wrap an optax optimizer so base weights are frozen: updates apply
    to ``/lora_`` entries only, and no optimizer state is allocated for
    the (much larger) base store."""
    import optax

    return optax.multi_transform(
        {"train": optimizer, "freeze": optax.set_to_zero()},
        lambda params: {name: ("train" if name.endswith((A_SUFFIX, B_SUFFIX))
                               else "freeze")
                        for name in params})


def merge_lora(params: Mapping[str, Array],
               alpha: float = DEFAULT_ALPHA) -> dict[str, Array]:
    """Export: fold adapters into the base weights permanently (rank read
    from the stored factors — only alpha must match training).  The
    returned plain store serves/saves/converts (models/hf.to_hf_*)
    exactly like any dense checkpoint, and its forward equals the
    adapted model's."""
    return _effective(params, alpha)


def split_rank_alpha(spec: str) -> tuple[int, float]:
    """Parse the CLI's ``--lora=R[:ALPHA]`` spec (alpha defaults 2*R,
    the common heuristic)."""
    m = re.fullmatch(r"(\d+)(?::([\d.]+))?", spec)
    if not m:
        raise ValueError(f"--lora expects R or R:ALPHA, got {spec!r}")
    rank = int(m.group(1))
    if rank < 1:
        raise ValueError(f"LoRA rank must be >= 1, got {rank}")
    alpha = float(m.group(2)) if m.group(2) else 2.0 * rank
    return rank, alpha
