"""Mixture-of-Experts layer with expert parallelism.

Top-k routing with capacity (k=1 gives the Switch transformer, k=2 the
Mixtral/GShard shape): the router picks each token's top-k experts, gates
are the top-k probabilities renormalized to sum one, and (token, choice)
assignments beyond an expert's capacity are dropped (pass through the
residual).  Dispatch/combine are expressed as einsums so that with the
expert dimension of w1/w2 sharded over the mesh's ``expert`` axis, GSPMD
lowers dispatch to an all-to-all over ICI — no manual collective code.

Load-balancing auxiliary loss per Switch Transformer: E * sum_e f_e * p_e
(fraction of assignments routed * mean router prob).  No reference
analogue (SURVEY.md §2: expert parallelism absent from the reference).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 128
    d_ff: int = 512
    num_experts: int = 8
    capacity_factor: float = 1.25
    # experts per token: 1 = Switch, 2 = Mixtral/GShard top-2
    top_k: int = 1
    dtype: object = jnp.float32


class MoELayer:
    def __init__(self, config: MoEConfig):
        if not 1 <= config.top_k <= config.num_experts:
            raise ValueError(
                f"top_k={config.top_k} must be in [1, num_experts="
                f"{config.num_experts}]")
        self.config = config

    def param_shapes(self) -> dict[str, tuple[int, ...]]:
        c = self.config
        return {
            "moe/router/w": (c.d_model, c.num_experts),
            "moe/w1": (c.num_experts, c.d_model, c.d_ff),
            "moe/w2": (c.num_experts, c.d_ff, c.d_model),
        }

    def init_params(self, rng: jax.Array | int = 0,
                    prefix: str = "") -> dict[str, Array]:
        c = self.config
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            f"{prefix}moe/router/w": jax.random.normal(
                k1, (c.d_model, c.num_experts), c.dtype) * 0.02,
            f"{prefix}moe/w1": jax.random.normal(
                k2, (c.num_experts, c.d_model, c.d_ff), c.dtype)
                / math.sqrt(c.d_model),
            f"{prefix}moe/w2": jax.random.normal(
                k3, (c.num_experts, c.d_ff, c.d_model), c.dtype)
                / math.sqrt(c.d_ff),
        }

    def capacity(self, num_assignments: int) -> int:
        """Per-expert queue length for ``num_assignments`` (token, choice)
        routing assignments — N tokens produce N * top_k assignments."""
        c = self.config
        return max(1, int(math.ceil(
            num_assignments / c.num_experts * c.capacity_factor)))

    def apply(self, params: Mapping[str, Array], x: Array,
              prefix: str = "",
              capacity_override: int | None = None,
              expert_slice: "tuple[Array, int] | None" = None
              ) -> tuple[Array, Array]:
        """x: [B, S, D] -> (out [B, S, D], aux_loss scalar).

        Dropped tokens (over capacity) contribute zero output — callers add
        the residual connection.  ``capacity_override`` replaces the
        factor-derived capacity; pass the token count for drop-free
        inference (capacity dropping is a batch-global training-time
        mechanism: which token drops depends on every other token in the
        batch, so it cannot be reproduced causally at decode time).

        ``expert_slice=(start, count)``: manual expert parallelism for
        callers INSIDE shard_map (parallel/pipeline.py), where GSPMD can't
        partition the dispatch einsums.  Routing/capacity/aux are computed
        over ALL num_experts from the (expert-axis-replicated) tokens —
        identical on every rank — but ``params[...moe/w1|w2]`` hold only
        this rank's ``count`` experts starting at ``start``, and the
        returned out is that PARTIAL contribution: the caller psums it
        over the expert axis.  ``start`` may be traced (lax.axis_index);
        ``count`` must be static."""
        c = self.config
        k = c.top_k
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)
        n = b * s
        cap = capacity_override if capacity_override is not None \
            else self.capacity(n * k)

        logits = jnp.dot(tokens.astype(jnp.float32),
                         params[f"{prefix}moe/router/w"].astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # [N, E]
        top_probs, top_idx = jax.lax.top_k(probs, k)       # [N, k]
        if k == 1:
            # Switch gates by the raw router prob — renormalizing would
            # make the gate a constant 1 and cut the router's gradient
            gates = top_probs
        else:
            # Mixtral/GShard: top-k probs renormalized to sum one (the
            # router still gets gradients through the ratios)
            gates = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)

        # flatten (token, choice) assignments, token-major so earlier
        # tokens win expert queue slots regardless of choice rank
        a_idx = top_idx.reshape(n * k)                     # [A]
        a_gate = gates.reshape(n * k)
        # position of each assignment within its expert's queue
        onehot = jax.nn.one_hot(a_idx, c.num_experts, dtype=jnp.int32)
        position = jnp.cumsum(onehot, axis=0) * onehot     # [A, E], 1-based
        pos_in_expert = jnp.sum(position, axis=-1) - 1     # [A]
        keep = pos_in_expert < cap

        # dispatch tensor [N, K, E, C]: token n's choice j -> slot (e, c);
        # contracting the (n) or (k, e, c) sides directly avoids ever
        # materializing a [N*k, D] repeated-token copy
        dispatch = ((jax.nn.one_hot(a_idx, c.num_experts, dtype=x.dtype)
                     [:, :, None]
                     * jax.nn.one_hot(jnp.where(keep, pos_in_expert, cap),
                                      cap + 1, dtype=x.dtype)[:, None, :cap])
                    .reshape(n, k, c.num_experts, cap))
        w1, w2 = params[f"{prefix}moe/w1"], params[f"{prefix}moe/w2"]
        if expert_slice is not None:
            start, count = expert_slice
            if w1.shape[0] != count:
                raise ValueError(
                    f"expert_slice count {count} != local expert weights "
                    f"{w1.shape[0]}")
            dispatch = jax.lax.dynamic_slice_in_dim(dispatch, start, count,
                                                    axis=2)
        # expert inputs [E, C, D] — with w1/w2 sharded over 'expert', GSPMD
        # turns this einsum contraction into the dispatch all-to-all
        expert_in = jnp.einsum("nkec,nd->ecd", dispatch, tokens)
        h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
        h = jax.nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h, w2)
        combined = jnp.einsum("nkec,ecd->nkd", dispatch, expert_out)
        weighted = combined * (a_gate * keep).astype(x.dtype).reshape(
            n, k)[..., None]
        out = weighted.sum(axis=1)

        # Switch load-balancing aux: E * sum_e (fraction of assignments
        # to e) * (mean router prob of e)
        frac = jnp.mean(jax.nn.one_hot(a_idx, c.num_experts,
                                       dtype=jnp.float32), axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = c.num_experts * jnp.sum(frac * mean_prob)
        return out.reshape(b, s, d), aux


def moe_expert_weight_spec(name: str, shape: tuple[int, ...], n_exp: int,
                           n_tp: int, n_fsdp: int) -> PartitionSpec:
    """Sharding for a [E, in, out] expert weight: ``expert`` on the expert
    dim, Megatron within-expert TP on the d_ff dim (w1 output / w2 input —
    one all-reduce per MoE branch, inserted by GSPMD), fsdp storage
    sharding on the free d_model dim.  Shared by moe_sharding_rule and
    models.transformer.transformer_rule."""
    spec: list = [None] * len(shape)
    if n_exp > 1 and shape[0] % n_exp == 0:
        spec[0] = "expert"
    is_w1 = name.endswith("w1")
    ff_axis = len(shape) - 1 if is_w1 else 1
    d_axis = 1 if is_w1 else len(shape) - 1
    if n_tp > 1 and shape[ff_axis] % n_tp == 0:
        spec[ff_axis] = "tensor"
    if n_fsdp > 1 and shape[d_axis] % n_fsdp == 0:
        spec[d_axis] = "fsdp"
    return PartitionSpec(*spec)


def moe_sharding_rule(mesh: Mesh):
    """Shard expert weights over ``expert`` (+ within-expert ``tensor`` on
    d_ff, ``fsdp`` on d_model); router replicated."""
    n_exp = mesh.shape["expert"]
    n_tp = mesh.shape["tensor"]
    n_fsdp = mesh.shape["fsdp"]

    def rule(name: str, shape: tuple[int, ...]) -> PartitionSpec:
        if "/moe/w" in name or name.startswith("moe/w"):
            return moe_expert_weight_spec(name, shape, n_exp, n_tp, n_fsdp)
        return PartitionSpec()  # router + anything else: replicated

    return rule
