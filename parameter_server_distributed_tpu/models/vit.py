"""Vision Transformer (ViT) — image classification on the transformer
block machinery.

The reference framework has no model layer at all (its "gradient" is a
0.01-constant stub — reference src/worker.cpp:316-329); this family
widens the model zoo beyond the MLP/ResNet/LM entries with the standard
patch-token transformer (Dosovitskiy et al.): non-overlapping patches
linearly embedded, a learned [CLS] token + learned positions,
pre-LN encoder blocks with BIDIRECTIONAL attention, and a linear head
on the [CLS] representation.

Parameter names reuse the transformer's suffix conventions
(``layer<i>/attn/wq`` ... ``mlp/w2``, ``lm_head/w`` for the classifier)
so :func:`models.transformer.transformer_rule` shards a ViT store with
the same Megatron TP columns/rows + fsdp layout without modification —
one sharding rule serves both families.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping

import jax
import jax.numpy as jnp

from .transformer import rms_norm, wdot

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    num_classes: int = 10
    d_model: int = 192
    n_heads: int = 3
    n_layers: int = 6
    d_ff: int = 768
    dtype: object = jnp.float32
    norm_eps: float = 1e-6
    # classifier input: the [CLS] token ("cls") or mean over patch
    # tokens ("mean")
    pool: str = "cls"

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(f"image_size {self.image_size} must divide by "
                             f"patch_size {self.patch_size}")
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.pool not in ("cls", "mean"):
            raise ValueError(f"pool must be 'cls' or 'mean', got {self.pool!r}")

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.n_patches + 1  # + [CLS]

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bidirectional_attention(q: Array, k: Array, v: Array) -> Array:
    """Unmasked einsum attention (every patch attends to every patch).
    q/k/v: [B, S, H, D] -> [B, S, H, D]; float32 logits/softmax like the
    causal kernel (models/transformer.py causal_attention)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(q.shape[-1])
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


class ViT:
    def __init__(self, config: ViTConfig):
        self.config = config

    # ------------------------------------------------------------ params
    def param_shapes(self) -> dict[str, tuple[int, ...]]:
        c = self.config
        patch_dim = c.patch_size * c.patch_size * c.channels
        shapes: dict[str, tuple[int, ...]] = {
            "patch/w": (patch_dim, c.d_model),
            # "/bias" suffix: transformer_rule's replicate-biases branch
            "patch/bias": (c.d_model,),
            "embed/cls": (1, 1, c.d_model),
            "embed/pos": (c.seq_len, c.d_model),
        }
        for i in range(c.n_layers):
            p = f"layer{i}"
            shapes[f"{p}/ln1/scale"] = (c.d_model,)
            shapes[f"{p}/attn/wq"] = (c.d_model, c.d_model)
            shapes[f"{p}/attn/wk"] = (c.d_model, c.d_model)
            shapes[f"{p}/attn/wv"] = (c.d_model, c.d_model)
            shapes[f"{p}/attn/wo"] = (c.d_model, c.d_model)
            shapes[f"{p}/ln2/scale"] = (c.d_model,)
            shapes[f"{p}/mlp/w1"] = (c.d_model, c.d_ff)
            shapes[f"{p}/mlp/w2"] = (c.d_ff, c.d_model)
        shapes["final_ln/scale"] = (c.d_model,)
        shapes["lm_head/w"] = (c.d_model, c.num_classes)  # classifier
        return shapes

    def num_params(self) -> int:
        return sum(math.prod(s) for s in self.param_shapes().values())

    def flops_per_sample(self, remat_credited: bool = False) -> float:
        """Training fwd+bwd FLOPs per image: 6*P per token for the 2-D
        parameter matmuls (the classifier head sees only the ONE pooled
        token) plus the attention einsums (12*L*d*S per token over
        S = n_patches+1) — same convention as
        Transformer.flops_per_sample.  ``remat_credited`` is accepted
        for signature compatibility and ignored: ViT has no remat."""
        c = self.config
        s = c.seq_len
        head = c.d_model * c.num_classes
        # Only weights that participate in matmuls count: embed/pos is a
        # 2-D table consumed by an add, and patch/w sees the n_patches
        # patch tokens but never the CLS token.
        block_params = sum(math.prod(shape)
                           for name, shape in self.param_shapes().items()
                           if len(shape) == 2
                           and name not in ("lm_head/w", "embed/pos",
                                            "patch/w"))
        patch_params = math.prod(self.param_shapes()["patch/w"])
        return (6.0 * (block_params * s + patch_params * c.n_patches + head)
                + 12.0 * c.n_layers * c.d_model * s * s)

    def init_params(self, rng: jax.Array | int = 0) -> dict[str, Array]:
        c = self.config
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        params: dict[str, Array] = {}
        for name, shape in self.param_shapes().items():
            rng, sub = jax.random.split(rng)
            if name.endswith("/scale"):
                params[name] = jnp.ones(shape, c.dtype)
            elif name.endswith(("/bias", "cls")):
                params[name] = jnp.zeros(shape, c.dtype)
            elif name == "embed/pos":
                params[name] = jax.random.normal(sub, shape, c.dtype) * 0.02
            else:
                fan_in = shape[0] if len(shape) > 1 else shape[-1]
                params[name] = (jax.random.normal(sub, shape, c.dtype)
                                / math.sqrt(fan_in))
        return params

    # ----------------------------------------------------------- forward
    def _patchify(self, x: Array) -> Array:
        """[B, H, W, C] images -> [B, N, patch*patch*C] patch vectors."""
        c = self.config
        b = x.shape[0]
        g = c.image_size // c.patch_size
        x = x.reshape(b, g, c.patch_size, g, c.patch_size, c.channels)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, g * g, c.patch_size * c.patch_size * c.channels)

    def apply(self, params: Mapping[str, Array], x: Array) -> Array:
        """images [B, H, W, C] -> logits [B, num_classes]."""
        c = self.config
        h = wdot(self._patchify(x.astype(c.dtype)), params["patch/w"],
                 preferred_element_type=jnp.float32)
        h = (h + params["patch/bias"].astype(jnp.float32)).astype(c.dtype)
        cls = jnp.broadcast_to(params["embed/cls"],
                               (h.shape[0], 1, c.d_model))
        h = jnp.concatenate([cls, h], axis=1) + params["embed/pos"]
        for i in range(c.n_layers):
            p = f"layer{i}"
            y = rms_norm(h, params[f"{p}/ln1/scale"], c.norm_eps)
            q = wdot(y, params[f"{p}/attn/wq"]).astype(c.dtype)
            k = wdot(y, params[f"{p}/attn/wk"]).astype(c.dtype)
            v = wdot(y, params[f"{p}/attn/wv"]).astype(c.dtype)
            shape = (h.shape[0], c.seq_len, c.n_heads, c.head_dim)
            attn = bidirectional_attention(q.reshape(shape),
                                           k.reshape(shape),
                                           v.reshape(shape))
            attn = attn.reshape(h.shape[0], c.seq_len, c.d_model)
            h = h + wdot(attn, params[f"{p}/attn/wo"],
                         preferred_element_type=jnp.float32).astype(c.dtype)
            y = rms_norm(h, params[f"{p}/ln2/scale"], c.norm_eps)
            ff = jax.nn.gelu(wdot(y, params[f"{p}/mlp/w1"],
                                  preferred_element_type=jnp.float32
                                  ).astype(c.dtype))
            h = h + wdot(ff, params[f"{p}/mlp/w2"],
                         preferred_element_type=jnp.float32).astype(c.dtype)
        h = rms_norm(h, params["final_ln/scale"], c.norm_eps)
        pooled = h[:, 0] if c.pool == "cls" else jnp.mean(h[:, 1:], axis=1)
        return wdot(pooled, params["lm_head/w"],
                    preferred_element_type=jnp.float32)

    def loss(self, params: Mapping[str, Array], batch: tuple) -> Array:
        """Mean softmax cross-entropy (same contract as MLP/ResNet.loss:
        batch = (images [B, H, W, C], int labels [B]))."""
        x, y = batch
        logp = jax.nn.log_softmax(self.apply(params, x), axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, y[:, None].astype(jnp.int32), axis=-1))


def vit_tiny(num_classes: int = 10, image_size: int = 32,
             dtype=jnp.float32) -> ViT:
    """ViT-Ti-ish at CIFAR scale: 6 layers, d_model 192, patch 4."""
    return ViT(ViTConfig(image_size=image_size, patch_size=4,
                         num_classes=num_classes, d_model=192, n_heads=3,
                         n_layers=6, d_ff=768, dtype=dtype))


def vit_s16(num_classes: int = 1000, image_size: int = 224,
            dtype=jnp.bfloat16) -> ViT:
    """ViT-S/16 (ImageNet scale): 12 layers, d_model 384, patch 16."""
    return ViT(ViTConfig(image_size=image_size, patch_size=16,
                         num_classes=num_classes, d_model=384, n_heads=6,
                         n_layers=12, d_ff=1536, dtype=dtype))
