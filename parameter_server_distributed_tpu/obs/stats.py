"""Process-wide metric instruments: counters, gauges, log-bucket histograms.

Design constraints, in order:

1. **Hot-path cheap.**  ``Histogram.observe`` is one ``log`` + one dict
   increment under a per-instrument lock — safe to leave unconditionally
   on every RPC and every step phase (the <2% bench-overhead budget).
2. **Mergeable.**  Everything snapshots to plain JSON (bucket maps, not
   percentiles), so worker snapshots can ride heartbeats and be aggregated
   or re-quantiled at the coordinator losslessly (obs/export.py).
3. **Bounded error.**  Buckets are geometric with ratio 2**(1/4) (~19%
   wide), so any percentile read off the bucket midpoints is within ~9%
   of the true value — plenty for p50/p95 latency and straggler spread.

Also home to the pieces folded in from the old ``utils/metrics.py``
(StepTimer, MetricsLogger, profile_trace, samples_per_sec); that module
re-exports them for backward compatibility.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Any, Iterator

# Geometric bucket ratio: value v (>0) lands in bucket ceil(log(v, BASE));
# bucket i spans (BASE**(i-1), BASE**i].
_BASE = 2.0 ** 0.25
_LOG_BASE = math.log(_BASE)


class Counter:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def add(self, n: int | float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Log-bucketed distribution: O(1) memory in observations, bounded
    relative error on percentiles (see module docstring)."""

    __slots__ = ("_lock", "buckets", "count", "total", "zeros",
                 "vmin", "vmax")

    def __init__(self):
        self._lock = threading.Lock()
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.zeros = 0  # observations <= 0 (kept out of the log buckets)
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v
            if v <= 0.0:
                self.zeros += 1
                return
            idx = math.ceil(math.log(v) / _LOG_BASE - 1e-9)
            self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def percentile(self, q: float) -> float:
        with self._lock:
            return percentile_from(self._snapshot_locked(), q)

    def summary(self) -> dict[str, float]:
        with self._lock:
            snap = self._snapshot_locked()
        if not snap["count"]:
            return {"count": 0}
        return {"count": snap["count"],
                "mean": snap["sum"] / snap["count"],
                "p50": percentile_from(snap, 50),
                "p95": percentile_from(snap, 95),
                "min": snap["min"], "max": snap["max"]}

    def _snapshot_locked(self) -> dict:
        return {"count": self.count, "sum": self.total, "zeros": self.zeros,
                "min": self.vmin if self.count else 0.0,
                "max": self.vmax if self.count else 0.0,
                "buckets": dict(self.buckets)}

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot_locked()


def percentile_from(snap: dict, q: float) -> float:
    """q-th percentile from a histogram SNAPSHOT (local or one that rode a
    heartbeat — bucket keys may have become strings in JSON).  Returns the
    geometric midpoint of the bucket holding the target rank, clamped to
    the observed [min, max]."""
    count = snap.get("count", 0)
    if not count:
        return float("nan")
    rank = max(1, math.ceil(q / 100.0 * count))
    seen = snap.get("zeros", 0)
    if rank <= seen:
        return min(0.0, snap["min"])
    items = sorted((int(k), v) for k, v in snap["buckets"].items())
    for idx, n in items:
        seen += n
        if rank <= seen:
            mid = _BASE ** (idx - 0.5)
            return min(max(mid, snap["min"]), snap["max"])
    return snap["max"]


class Registry:
    """Name -> instrument map; the process-wide default is ``REGISTRY``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls()
            elif not isinstance(inst, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(inst).__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()

    def snapshot(self) -> dict:
        """JSON-able view of every instrument (histograms as bucket maps —
        see obs/export.py for percentile/rollup computation)."""
        with self._lock:
            items = list(self._instruments.items())
        out: dict[str, dict] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        for name, inst in items:
            if isinstance(inst, Counter):
                out["counters"][name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.value
            else:
                out["histograms"][name] = inst.snapshot()
        return out


REGISTRY = Registry()


class TimeSeriesRing:
    """Bounded ring of timestamped registry snapshots, and the rate/delta
    math between them — what ``pst-status --watch`` renders (ISSUE 8).

    Snapshots are the same plain-JSON shape :meth:`Registry.snapshot`
    emits (and that heartbeats carry), so the ring works identically over
    a local registry or over rollup snapshots fetched from the
    coordinator.  ``push`` stamps ``t`` if absent; :meth:`rates` derives
    per-second counter rates, histogram observation rates, and gauge
    values between the two most recent snapshots (or any pair)."""

    def __init__(self, capacity: int = 64):
        from collections import deque

        from ..analysis.lock_order import checked_lock

        # leaf (analysis/lock_order.py): guards only the deque
        self._lock = checked_lock("TimeSeriesRing._lock")
        self._snaps: deque = deque(maxlen=max(2, int(capacity)))

    def push(self, snap: dict) -> dict:
        snap = dict(snap)
        snap.setdefault("t", time.time())
        with self._lock:
            self._snaps.append(snap)
        return snap

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)

    def last(self, n: int = 1) -> list[dict]:
        with self._lock:
            return list(self._snaps)[-n:]

    def rates(self) -> dict | None:
        """Deltas between the two newest snapshots, or None until two
        exist."""
        pair = self.last(2)
        if len(pair) < 2:
            return None
        return snapshot_rates(pair[0], pair[1])


def snapshot_rates(prev: dict, cur: dict) -> dict:
    """Per-second rates between two registry snapshots: counters become
    ``delta/dt``, histograms become observation rates (count delta/dt)
    with the interval mean, gauges pass through at their current value.
    Counters that went BACKWARD (process restart) report the current
    value over dt — a restart reads as a burst, not a negative rate."""
    dt = max(1e-9, float(cur.get("t", 0.0)) - float(prev.get("t", 0.0)))
    counters = {}
    for name, value in cur.get("counters", {}).items():
        before = prev.get("counters", {}).get(name, 0)
        delta = value - before if value >= before else value
        # zero rates are kept, deliberately: a STALLED worker showing
        # 0.00/s is exactly the signal --watch exists to surface —
        # eliding it would be indistinguishable from the worker not
        # being part of the cluster at all
        counters[name] = delta / dt
    hists = {}
    for name, h in cur.get("histograms", {}).items():
        count = h.get("count", 0)
        ph = prev.get("histograms", {}).get(name, {})
        pcount = ph.get("count", 0)
        dcount = count - pcount if count >= pcount else count
        if not dcount:
            continue
        dsum = (h.get("sum", 0.0) - ph.get("sum", 0.0)
                if count >= pcount else h.get("sum", 0.0))
        hists[name] = {"per_s": dcount / dt, "mean": dsum / dcount}
    return {"dt_s": dt, "t": cur.get("t"), "counters": counters,
            "histograms": hists, "gauges": dict(cur.get("gauges", {}))}


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


# --------------------------------------------------------------------------
# Folded in from utils/metrics.py (imports preserved via that module)
# --------------------------------------------------------------------------

class StepTimer:
    def __init__(self, capacity: int = 1024):
        self._durations: list[float] = []
        self._capacity = capacity
        self._t0: float | None = None

    def __enter__(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._t0 is not None
        self.record(time.perf_counter() - self._t0)

    def record(self, duration_s: float) -> None:
        self._durations.append(duration_s)
        if len(self._durations) > self._capacity:
            del self._durations[:-self._capacity]

    @property
    def count(self) -> int:
        return len(self._durations)

    def percentile(self, q: float) -> float:
        if not self._durations:
            return float("nan")
        ordered = sorted(self._durations)
        idx = min(len(ordered) - 1, int(q / 100.0 * len(ordered)))
        return ordered[idx]

    def summary(self) -> dict[str, float]:
        if not self._durations:
            return {"count": 0}
        return {
            "count": len(self._durations),
            "mean_s": sum(self._durations) / len(self._durations),
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "last_s": self._durations[-1],
        }


class MetricsLogger:
    """Append-only JSONL metrics stream (path=None: in-memory only)."""

    def __init__(self, path: str | None = None):
        self._path = path
        self._records: list[dict] = []
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def log(self, **fields: Any) -> dict:
        record = {"t": time.time(), **fields}
        self._records.append(record)
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps(record, default=float) + "\n")
        return record

    @property
    def records(self) -> list[dict]:
        return list(self._records)

    def latest(self, metric: str) -> Any:
        for record in reversed(self._records):
            if metric in record:
                return record[metric]
        return None


@contextlib.contextmanager
def profile_trace(name: str = "train",
                  trace_dir: str | None = None) -> Iterator[None]:
    """TPU timeline capture via jax.profiler; no-op unless a directory is
    given or PSDT_TRACE_DIR is set."""
    trace_dir = trace_dir or os.environ.get("PSDT_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield


def samples_per_sec(batch_size: int, step_time_s: float,
                    num_chips: int = 1) -> float:
    if step_time_s <= 0:
        return float("nan")
    return batch_size / step_time_s / max(1, num_chips)
