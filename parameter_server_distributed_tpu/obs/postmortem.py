"""Cross-process iteration postmortems over flight rings (``pst-trace``).

The flight recorder (:mod:`obs.flight`) leaves one mmap-backed ring per
process under ``PSDT_FLIGHT_DIR`` — including for processes that died by
``kill -9`` or SIGSEGV.  This module merges them (plus any Chrome-trace
dumps the span layer wrote via ``PSDT_TRACE_FILE``) and reconstructs what
actually happened:

- **process listing** — every ring's role/pid, whether it shut down clean
  or DIED (header ``clean`` flag), how much history the ring wrapped
  away, and any faulthandler crash sidecar.
- **iteration timeline** — all events of iteration N keyed by
  ``(iteration, worker)``, time-ordered across processes: worker step
  legs, per-worker push commits, the PS barrier phases
  (seal → drain → apply → publish), replication ships/installs, failover
  reports/promotions, reshard fences.
- **critical path + straggler attribution** — the barrier closes when the
  LAST worker commits; the path from that worker's step start through
  seal/drain/apply to publish is the iteration's critical path, and the
  commit spread across workers is the straggler attribution the elastic
  K-of-N policy (ROADMAP item 1) needs per-worker, per-phase.
- **failure narrative** — dead processes, failover promotions (which
  shard, which new primary, at which epoch) and the worker-side retries
  of the same iteration that made the failover invisible to training.

Renders: text (:func:`render_report`), JSON (:func:`report`), and a
merged Chrome trace (:func:`chrome_events` — paired ``*.start``/``*.end``
events become duration slices, everything else instants) that loads in
Perfetto next to the span layer's own dumps.

Wall clocks: rings merge on ``time.time()`` stamps, which is exact for
same-host postmortems (the chaos drives and tests) and as good as NTP
across hosts — good enough to order millisecond-scale barrier phases in
practice; the per-process ``seq`` breaks ties.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Iterable

from . import flight

# Mirrors tiers/messages.py TIER_AGGREGATE_ID_BASE (asserted equal by
# tests/test_tiers.py): a push-commit worker id at or above this base is
# a leaf aggregator's GROUP contribution, and the postmortem names the
# group — not a phantom worker — in timelines and the critical path.
_TIER_ID_BASE = 1 << 20


def _is_group(worker_id: int) -> bool:
    return worker_id >= _TIER_ID_BASE


def _group_label(worker_id: int) -> str:
    return f"group[leader {worker_id - _TIER_ID_BASE}]"


# One human-readable row per registered flight event: what it marks and
# what the a/b/note payload fields carry.  Keys must cover flight.EVENTS
# exactly — pst-analyze's flight-event pass diffs the two tables, so a
# new event without a decode row (or a stale row) fails the analyzer.
EVENT_DECODE: dict[str, str] = {
    "proc.start": "process came up (role in note)",
    "proc.exit": "clean shutdown recorded",
    "proc.sigterm": "SIGTERM received",
    "rpc.cli.start": "client RPC issued (note = method)",
    "rpc.cli.end": "client RPC done; a=duration_us b=1 ok/0 error",
    "rpc.srv.start": "server handler entered (note = method)",
    "rpc.srv.end": "server handler done; a=duration_us",
    "step.start": "worker step began",
    "step.end": "worker step done; a=duration_us",
    "fused.start": "fused push+pull issued",
    "fused.end": "fused push+pull done; a=duration_us b=1 ok/0 degraded",
    "boot.seed": "worker seeded an empty store",
    "fold.reserve": "gradient chunk fold reserved (sampled); a=tensors",
    "push.commit": "worker push committed; a=contributors b=width",
    "barrier.seal": "barrier sealed; a=contributors",
    "barrier.drain": "in-flight folds drained; a=folds",
    "apply.start": "optimizer apply began",
    "apply.end": "optimizer apply done; a=duration_us",
    "barrier.publish": "new params published; a=contributors b=width",
    "barrier.retry": "failed close left the barrier retryable",
    "repl.ship.start": "replica snapshot ship began; a=bytes b=version",
    "repl.ship.end": "replica ship done; a=duration_us b=version",
    "repl.ack": "replica acked a ship; a=1 ok/0 refused b=version",
    "repl.install": "replica installed a shipped store; a=bytes "
                    "b=version",
    "repl.refuse": "replica refused a ship (note = reason)",
    "repl.degrade": "replication permanently degraded",
    "failover.report": "dead primary reported; a=shard (note = address)",
    "failover.promote": "replica promoted; a=shard b=new epoch",
    "failover.retry": "worker retried onto replacement; a=shard",
    "reshard.fence": "reshard fence; a=tensors retired b=map epoch",
    "reshard.install": "resharded store installed; a=bytes b=epoch",
    "reshard.epoch": "shard map advanced; a=new epoch b=shard count",
    "shm.negotiate": "shm ring negotiated; a=connection b=ring bytes",
    "shm.refuse": "shm refused (note = reason)",
    "shm.attach": "client attached shm ring; b=ring bytes",
    "shm.downgrade": "shm downgraded to TCP (note = reason)",
    "shm.reap": "shm connection reaped; a=connection",
    "shm.reap.dup": "second shm release attempt hit the latch",
    "codec.select": "wire codec chosen; a=1 native/0 python",
    "ckpt.restore": "checkpoint restored",
    "tier.elect": "tier topology elected; a=group size b=epoch/agg id",
    "tier.fold": "member push folded at leaf (sampled); a=tensors "
                 "b=aggregate id",
    "tier.seal": "leaf group sealed; a=contributors b=group size",
    "tier.upstream": "group aggregate shipped upstream; a=duration_us "
                     "b=wire bytes",
    "tier.downgrade": "permanent flat downgrade (note = reason)",
    "serve.delta.build": "serve delta built; a=bytes b=to_version",
    "serve.delta.hit": "delta chain served; a=wire bytes b=pairs",
    "serve.delta.miss": "delta miss, full store served; a=held "
                        "b=current (note = reason)",
    "serve.delta.downgrade": "client permanently downgraded deltas "
                             "(note = reason)",
    "publish.subscribe": "weight subscriber joined; a=held version "
                         "b=subscriber id",
    "publish.swap": "subscriber swapped weights; a=version "
                    "b=duration_us",
    "publish.lag": "subscriber lag sample; a=versions behind",
    "apply.device": "device-resident apply; a=duration_us b=stripes",
    "apply.device.fallback": "device apply degraded to host "
                             "(note = reason)",
    "apply.readback": "async D2H readback started; a=tensors",
    "elastic.join": "member ACTIVE; a=membership epoch",
    "elastic.drain": "member DRAINING; a=epoch (note = reason)",
    "elastic.evict": "coordinator reap marked member GONE; a=epoch",
    "quorum.seal": "barrier closed at K of N; a=contributors b=width",
    "stale.fold": "straggler folded forward; a=staleness b=tensors",
    "fleet.register": "decode server ACTIVE; a=slots b=fleet epoch",
    "fleet.drain": "decode server DRAINING; a=fleet epoch",
    "fleet.evict": "coordinator reap marked server GONE; a=fleet epoch",
    "fleet.route": "router pinned a stream; a=request b=server",
    "fleet.scale": "scale decision; a=target b=epoch/current size",
    "fleet.rollout": "rolling update step; a=version b=server",
    "fleet.swap": "decode server swapped serving version; a=version "
                  "b=server",
    "apply.arena.pack": "arena packing table built; a=duration_us "
                        "b=stripes",
    "apply.arena.repack": "arena table rebuilt on shape change; "
                          "a=duration_us",
    "apply.arena.fallback": "arena close downgraded to per-tensor "
                            "(note = reason)",
    "apply.arena": "flat arena close published; a=dispatch_us "
                   "b=readback_us",
    "freerun.apply": "apply-on-arrival landed; a=staleness b=damp ppm",
    "freerun.dup": "version-vector dedup dropped a replay; a=last step",
    "freerun.publish": "coalesced publication; a=version b=applies",
    "damp.floor": "contribution damped below the floor; a=staleness "
                  "b=scale ppb",
    "shard.install": "partition shard installed; a=bytes b=version",
    "shard.update.degrade": "sharded close degraded to replicated path "
                            "(note = reason)",
    "apply.sharded": "sharded close published; a=replicas b=wire bytes",
    "serve.prefix.hit": "radix prefix reuse; a=prefix tokens reused "
                        "b=suffix tokens forwarded",
    "serve.prefix.evict": "prefix-cache LRU pass; a=nodes evicted "
                          "b=bytes pinned after",
    "serve.prefix.split": "radix edge split; a=split depth b=tree nodes",
}


def describe_event(name: str) -> str:
    """One-line decode of a flight event name (the name itself when the
    table has no row — old rings can carry codes newer than this build)."""
    return EVENT_DECODE.get(name, name)


# ------------------------------------------------------------------- loading


def load_rings(directory: str) -> list[dict]:
    """Decode every ``flight-*.ring`` under ``directory`` (skipping
    unreadable/foreign files with a note instead of dying — a postmortem
    tool must not crash on a half-written artifact) and attach any
    ``crash-<pid>.txt`` faulthandler sidecar."""
    rings: list[dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "flight-*.ring"))):
        try:
            ring = flight.decode_ring(path)
        except (OSError, ValueError) as exc:
            rings.append({"path": path, "error": str(exc), "events": [],
                          "pid": 0, "role": "?", "clean": False,
                          "dropped": 0})
            continue
        # clean=0 means "no clean shutdown RECORDED" — which is also the
        # steady state of a process still running.  A same-host liveness
        # probe (signal 0) separates "still running" from "DIED"; rings
        # merge same-host by design (module docstring), and a recycled
        # pid at worst reports a dead process as running, never the
        # reverse.
        ring["alive"] = False
        if not ring["clean"] and ring["pid"]:
            try:
                os.kill(int(ring["pid"]), 0)
                ring["alive"] = True
            except ProcessLookupError:
                pass
            except (PermissionError, OSError):
                ring["alive"] = True  # exists, not ours
        crash = os.path.join(directory, f"crash-{ring['pid']}.txt")
        try:
            if os.path.getsize(crash) > 0:
                with open(crash, errors="replace") as fh:
                    ring["crash"] = fh.read()
        except OSError:
            pass
        rings.append(ring)
    return rings


def merge_events(rings: Iterable[dict]) -> list[dict]:
    """All rings' events in one wall-clock-ordered list, each stamped
    with its source pid/role (per-process seq breaks same-stamp ties)."""
    merged: list[dict] = []
    for ring in rings:
        for ev in ring.get("events", ()):
            ev = dict(ev)
            ev["pid"] = ring.get("pid", 0)
            ev["role"] = ring.get("role", "?")
            merged.append(ev)
    merged.sort(key=lambda e: (e["ts"], e["pid"], e["seq"]))
    return merged


# ------------------------------------------------------------ reconstruction


def iterations_seen(events: Iterable[dict]) -> list[int]:
    return sorted({e["iteration"] for e in events if e["iteration"] >= 0})


def _pairs(events: list[dict], start: str, end: str,
           key=lambda e: (e["pid"], e["tid"], e["iteration"],
                          e["worker"]),
           return_open: bool = False):
    """Match ``start``/``end`` events into intervals per (process,
    thread, iteration, worker) — nearest-start wins, unmatched ends
    dropped.  A crash between start and end leaves an OPEN interval;
    ``return_open=True`` additionally returns those unmatched starts —
    the "in flight at death" evidence the Chrome export must not lose."""
    open_by_key: dict[tuple, list[dict]] = {}
    out: list[tuple[dict, dict]] = []
    for ev in events:
        if ev["event"] == start:
            open_by_key.setdefault(key(ev), []).append(ev)
        elif ev["event"] == end:
            stack = open_by_key.get(key(ev))
            if stack:
                out.append((stack.pop(), ev))
    if return_open:
        opens = [ev for stack in open_by_key.values() for ev in stack]
        return out, opens
    return out


def iteration_timeline(events: list[dict], iteration: int) -> dict:
    """Everything that happened to ``iteration``, reconstructed across
    processes.  Returns a JSON-able dict; see :func:`render_report` for
    the human view."""
    evs = [e for e in events if e["iteration"] == iteration]
    commits = [e for e in evs if e["event"] == "push.commit"]
    publishes = [e for e in evs if e["event"] == "barrier.publish"]
    seals = [e for e in evs if e["event"] == "barrier.seal"]
    drains = [e for e in evs if e["event"] == "barrier.drain"]
    applies = _pairs(evs, "apply.start", "apply.end",
                     key=lambda e: (e["pid"], e["iteration"]))
    retries = [e for e in evs if e["event"] == "failover.retry"]
    # per-worker legs: step + fused/push spans and this worker's commit.
    # Commits are counted PER SOURCE PID: under the sharded topology a
    # worker legitimately commits once on every shard's barrier, so
    # "retried" means >1 commit on the SAME shard process (a replay the
    # dedup absorbed), never the normal per-shard fan-out.
    workers: dict[int, dict] = {}
    groups: dict[int, dict] = {}
    commits_by_pid: dict[tuple[int, int], int] = {}
    for ev in evs:
        wid = ev["worker"]
        if wid < 0:
            continue
        if _is_group(wid):
            # a leaf aggregator's group lane (tiers/): seal → upstream →
            # PS commit, keyed by the synthetic aggregate id
            g = groups.setdefault(wid, {"events": 0})
            g["events"] += 1
            if ev["event"] == "tier.seal":
                g["seal_ts"] = ev["ts"]
                g["sealed_members"] = ev["a"]
                g["group_size"] = ev["b"]
            elif ev["event"] == "tier.upstream":
                g["upstream_ts"] = ev["ts"]
                g["upstream_s"] = ev["a"] / 1e6
                g["upstream_bytes"] = ev["b"]
            elif ev["event"] == "push.commit":
                g["commit"] = ev["ts"]
            continue
        w = workers.setdefault(wid, {"events": 0})
        w["events"] += 1
        if ev["event"] == "step.start":
            w["step_start"] = ev["ts"]
        elif ev["event"] == "step.end":
            w["step_end"] = ev["ts"]
        elif ev["event"] == "push.commit":
            # the LAST commit wins: a failover retry of the same
            # iteration commits again (dedup makes it idempotent)
            w["commit"] = ev["ts"]
            key = (wid, ev["pid"])
            commits_by_pid[key] = commits_by_pid.get(key, 0) + 1
        elif ev["event"] == "failover.retry":
            w["failover_retry"] = ev["note"]
        elif ev["event"] == "tier.fold":
            w["tier_folds"] = w.get("tier_folds", 0) + 1
    for (wid, _pid), n in commits_by_pid.items():
        w = workers[wid]
        w["commits"] = max(w.get("commits", 0), n)
    out: dict[str, Any] = {"iteration": iteration, "workers": workers,
                           "events": len(evs)}
    if groups:
        out["groups"] = groups
    # K-of-N quorum close (elastic/, ISSUE 13): name the workers left
    # OUTSIDE the close — every worker that actually RAN this iteration
    # (a step/fused start or commit FOR it) but had no commit before the
    # quorum seal.  Scoped to this iteration's events deliberately: a
    # gracefully drained member has no step here and must not be named
    # a straggler of closes it was legitimately not part of.  The seal
    # note carries the contributor ids too (belt and braces for wrapped
    # rings).
    quorum_seals = [e for e in evs if e["event"] == "quorum.seal"]
    if quorum_seals:
        q = quorum_seals[0]
        inside = {e["worker"] for e in commits
                  if e["ts"] <= q["ts"] and not _is_group(e["worker"])}
        for tok in (q.get("note") or "").split(","):
            if tok.strip().lstrip("-").isdigit():
                inside.add(int(tok))
        ran_here = {e["worker"] for e in evs
                    if 0 <= e["worker"] < _TIER_ID_BASE
                    and e["event"] in ("push.commit", "step.start",
                                       "fused.start")}
        out["quorum"] = {
            "contributors": q["a"], "width": q["b"],
            "outside": sorted(ran_here - inside),
        }
    stale_folds = [e for e in evs if e["event"] == "stale.fold"]
    if stale_folds:
        # folds INTO this iteration: a straggler's carried gradient
        out["stale_folds"] = [{"worker": e["worker"], "staleness": e["a"],
                               "tensors": e["b"]} for e in stale_folds]
    if commits:
        first, last = commits[0], commits[-1]
        out["first_commit"] = {"worker": first["worker"], "ts": first["ts"]}
        out["last_commit"] = {"worker": last["worker"], "ts": last["ts"]}
        out["commit_spread_s"] = last["ts"] - first["ts"]
        out["straggler"] = last["worker"]
        if _is_group(last["worker"]):
            # attribution by NAME: the barrier-close critical path ran
            # through this group's leaf hop, not a phantom worker
            out["straggler_group"] = _group_label(last["worker"])
    if seals:
        out["seal_ts"] = seals[0]["ts"]
    if drains:
        out["drained_folds"] = drains[0]["a"]
    if applies:
        start, end = applies[0]
        out["apply_s"] = end["a"] / 1e6
        out["apply_ts"] = start["ts"]
    # flat arena apply (core/arena.py, ISSUE 15): the close's arena
    # phases — slab pack(s) attributed to this iteration, the fused
    # stage dispatch, and the contiguous per-stripe readback — rendered
    # as an "arena:" line next to the apply phases
    arena_closes = [e for e in evs if e["event"] == "apply.arena"]
    if arena_closes:
        a = arena_closes[-1]
        arena: dict[str, Any] = {"dispatch_s": a["a"] / 1e6,
                                 "readback_s": a["b"] / 1e6}
        packs = [e for e in evs
                 if e["event"] in ("apply.arena.pack",
                                   "apply.arena.repack")]
        if packs:
            arena["pack_s"] = sum(e["a"] for e in packs) / 1e6
            arena["repacked"] = any(e["event"] == "apply.arena.repack"
                                    for e in packs)
        out["arena"] = arena
    arena_fallbacks = [e for e in evs
                       if e["event"] == "apply.arena.fallback"]
    if arena_fallbacks:
        out["arena_fallback"] = arena_fallbacks[-1].get("note", "")
    if publishes:
        pub = publishes[-1]
        out["publish_ts"] = pub["ts"]
        out["contributors"] = pub["a"]
        out["barrier_width"] = pub["b"]
    if retries:
        out["failover_retries"] = [
            {"worker": e["worker"], "shard": e["a"], "to": e["note"]}
            for e in retries]
    # replication/reshard activity attributed to this iteration
    ships = [e for e in evs if e["event"] == "repl.ship.end"]
    if ships:
        out["replica_ships"] = len(ships)
    installs = [e for e in evs if e["event"] == "repl.install"]
    if installs:
        out["replica_installs"] = [
            {"role": e["role"], "bytes": e["a"], "version": e["b"]}
            for e in installs]
    # versioned delta serving (delta/, ISSUE 10): how this iteration's
    # serve fan-out rode the delta chain vs fell back to full encodes
    dhits = [e for e in evs if e["event"] == "serve.delta.hit"]
    dmisses = [e for e in evs if e["event"] == "serve.delta.miss"]
    if dhits or dmisses:
        out["delta_serve"] = {
            "hits": len(dhits), "misses": len(dmisses),
            "delta_bytes": sum(e["a"] for e in dhits),
            "miss_reasons": sorted({e["note"] for e in dmisses
                                    if e["note"]}),
        }
    return out


def critical_path(events: list[dict], iteration: int,
                  timeline: dict | None = None) -> list[dict]:
    """The ordered chain of events that gated ``iteration``'s barrier
    close: the straggler's step start → its push commit → seal → drain →
    apply → publish, each with its delta to the previous link.  Empty
    when the iteration never published.  ``timeline`` (an
    :func:`iteration_timeline` result) avoids recomputing it."""
    tl = timeline if timeline is not None \
        else iteration_timeline(events, iteration)
    if "publish_ts" not in tl or "last_commit" not in tl:
        return []
    straggler = tl["last_commit"]["worker"]
    chain: list[tuple[str, float]] = []
    if _is_group(straggler):
        # the close gated on a GROUP's leaf hop (tiers/): name it, and
        # chart the intra-group legs — seal (last member arrived at the
        # leaf) and the quantized upstream push — so a slow group is
        # attributable to its own phases, not just "slow"
        label = tl.get("straggler_group") or _group_label(straggler)
        g = tl.get("groups", {}).get(straggler, {})
        if "seal_ts" in g:
            chain.append((f"{label} sealed at its leaf "
                          f"({g.get('sealed_members', '?')} members)",
                          g["seal_ts"]))
        if "upstream_ts" in g:
            chain.append((f"{label} quantized upstream push "
                          f"({g.get('upstream_bytes', 0)} B)",
                          g["upstream_ts"]))
        chain.append((f"{label} upstream commit (closes barrier)",
                      tl["last_commit"]["ts"]))
    else:
        w = tl["workers"].get(straggler, {})
        if "step_start" in w:
            chain.append((f"worker {straggler} step start",
                          w["step_start"]))
        chain.append((f"worker {straggler} push commit (closes barrier)",
                      tl["last_commit"]["ts"]))
    if "seal_ts" in tl:
        chain.append(("barrier seal", tl["seal_ts"]))
    if "apply_ts" in tl:
        chain.append(("optimizer apply", tl["apply_ts"]))
    chain.append(("barrier publish", tl["publish_ts"]))
    chain.sort(key=lambda c: c[1])
    out = []
    prev_ts = chain[0][1]
    for name, ts in chain:
        out.append({"what": name, "ts": ts, "dt_s": ts - prev_ts})
        prev_ts = ts
    return out


def stalled_iterations(events: list[dict], stall_s: float) -> list[dict]:
    """Iterations whose barrier STALLED (elastic/, ISSUE 13 acceptance:
    under an armed quorum no barrier may wait past grace on a gone or
    slow worker).  An iteration counts as stalled when a worker actually
    ran it (a step/fused start exists — pure forward-fold target
    iterations have no step of their own) and either

    - it never published a barrier, or
    - its seal came more than ``stall_s`` after the last pre-seal commit
      (the barrier sat waiting on someone who never arrived).

    Returns ``[{iteration, reason, waited_s?}]`` — empty is the
    acceptance condition pst-trace verifies for the preemption-chaos
    drives."""
    out: list[dict] = []
    for it in iterations_seen(events):
        evs = [e for e in events if e["iteration"] == it]
        if not any(e["event"] in ("step.start", "fused.start")
                   for e in evs):
            continue
        pubs = [e for e in evs if e["event"] == "barrier.publish"]
        if not pubs:
            out.append({"iteration": it, "reason": "never published"})
            continue
        seals = [e for e in evs if e["event"] == "barrier.seal"]
        commits = [e["ts"] for e in evs if e["event"] == "push.commit"]
        if seals and commits:
            pre = [ts for ts in commits if ts <= seals[0]["ts"]]
            if pre:
                waited = seals[0]["ts"] - max(pre)
                if waited > stall_s:
                    out.append({"iteration": it,
                                "reason": f"seal waited {waited:.3f}s "
                                          f"after the last commit",
                                "waited_s": waited})
    return out


def failure_narrative(rings: list[dict], events: list[dict]) -> dict:
    """Dead processes, promotions, and same-iteration failover retries —
    the across-iterations story pst-trace leads with."""
    dead = [{"role": r.get("role", "?"), "pid": r.get("pid", 0),
             "path": r.get("path", ""),
             "crash_traceback": bool(r.get("crash"))}
            for r in rings if not r.get("clean") and not r.get("alive")
            and not r.get("error")]
    promotions = [{"shard": e["a"], "epoch": e["b"], "new_primary": e["note"],
                   "ts": e["ts"], "role": e["role"]}
                  for e in events if e["event"] == "failover.promote"]
    reports = [{"worker": e["worker"], "shard": e["a"], "dead": e["note"]}
               for e in events if e["event"] == "failover.report"]
    retries = [{"worker": e["worker"], "iteration": e["iteration"],
                "shard": e["a"], "to": e["note"]}
               for e in events if e["event"] == "failover.retry"]
    degrades = [{"role": e["role"], "what": e["event"], "note": e["note"]}
                for e in events
                if e["event"] in ("repl.degrade", "shm.downgrade",
                                  "tier.downgrade", "serve.delta.downgrade")]
    # live weight publication (delta/, ISSUE 10): subscriptions opened,
    # decode-side hot swaps (last version swapped in), worst version lag
    subs = [e for e in events if e["event"] == "publish.subscribe"]
    swaps = [e for e in events if e["event"] == "publish.swap"]
    lags = [e["a"] for e in events if e["event"] == "publish.lag"]
    publish: dict[str, Any] = {}
    if subs:
        publish["subscriptions"] = len(subs)
    if swaps:
        publish["swaps"] = len(swaps)
        publish["last_version"] = swaps[-1]["a"]
    if lags:
        publish["max_lag"] = max(lags)
    # elastic membership transitions (elastic/, ISSUE 13): who drained
    # (ctl/SIGTERM/leave), who the reaper marked GONE, and how many
    # quorum closes / forward folds the run saw
    drains = [{"worker": e["worker"], "note": e["note"], "role": e["role"]}
              for e in events if e["event"] == "elastic.drain"]
    evicts = [{"worker": e["worker"]}
              for e in events if e["event"] == "elastic.evict"]
    quorum_closes = sum(1 for e in events if e["event"] == "quorum.seal")
    stale_count = sum(1 for e in events if e["event"] == "stale.fold")
    elastic: dict[str, Any] = {}
    if drains:
        elastic["drains"] = drains
    if evicts:
        elastic["evictions"] = evicts
    if quorum_closes:
        elastic["quorum_closes"] = quorum_closes
    if stale_count:
        elastic["stale_folds"] = stale_count
    out: dict[str, Any] = {}
    if elastic:
        out["membership"] = elastic
    if publish:
        out["publication"] = publish
    if dead:
        out["dead_processes"] = dead
    if promotions:
        out["promotions"] = promotions
    if reports:
        out["failure_reports"] = reports
    if retries:
        out["failover_retries"] = retries
    if degrades:
        out["degrades"] = degrades
    return out


def report(directory: str, iteration: int | None = None) -> dict:
    """The full postmortem as JSON-able data: process listing, failure
    narrative, and the timeline + critical path of ``iteration``
    (default: the last iteration that published a barrier, else the last
    seen)."""
    rings = load_rings(directory)
    events = merge_events(rings)
    published = sorted({e["iteration"] for e in events
                        if e["event"] == "barrier.publish"})
    seen = iterations_seen(events)
    if iteration is None:
        iteration = (published[-1] if published
                     else (seen[-1] if seen else -1))
    out = {
        "directory": directory,
        "processes": [{
            "role": r.get("role", "?"), "pid": r.get("pid", 0),
            "clean": r.get("clean", False),
            "alive": r.get("alive", False),
            "events": len(r.get("events", ())),
            "dropped": r.get("dropped", 0),
            **({"error": r["error"]} if r.get("error") else {}),
            **({"crash": True} if r.get("crash") else {}),
        } for r in rings],
        "iterations": {"seen": seen[:200], "published": published[:200]},
        "narrative": failure_narrative(rings, events),
    }
    if iteration >= 0:
        out["iteration"] = iteration
        tl = iteration_timeline(events, iteration)
        out["timeline"] = tl
        out["critical_path"] = critical_path(events, iteration,
                                             timeline=tl)
    return out


# ------------------------------------------------------------------- renders


def _fmt_dt(s: float) -> str:
    return f"{s * 1e3:.2f}ms" if abs(s) < 1.0 else f"{s:.3f}s"


def render_report(rep: dict) -> str:
    """Human text view of :func:`report` — what pst-trace prints."""
    lines = [f"flight postmortem: {rep['directory']}"]
    for p in rep["processes"]:
        if p["clean"]:
            status = "clean exit"
        elif p.get("alive"):
            status = "still running"
        else:
            status = "DIED (no clean shutdown)"
        extra = ""
        if p.get("crash"):
            extra += ", fatal-signal traceback captured"
        if p.get("dropped"):
            extra += f", ring wrapped ({p['dropped']} events lost)"
        if p.get("error"):
            status, extra = f"unreadable: {p['error']}", ""
        lines.append(f"  {p['role']} (pid {p['pid']}): {status}, "
                     f"{p['events']} events{extra}")
    seen = rep["iterations"]["seen"]
    published = rep["iterations"]["published"]
    lines.append(f"  iterations: {len(seen)} seen, "
                 f"{len(published)} published barriers")
    narrative = rep.get("narrative", {})
    for promo in narrative.get("promotions", ()):
        lines.append(f"  PROMOTION: shard {promo['shard']} -> "
                     f"{promo['new_primary']} at map epoch {promo['epoch']} "
                     f"({promo['role']})")
    for retry in narrative.get("failover_retries", ()):
        lines.append(f"  RETRIED ITERATION: worker {retry['worker']} "
                     f"retried iteration {retry['iteration']} against "
                     f"{retry['to']} (shard {retry['shard']})")
    for d in narrative.get("degrades", ()):
        lines.append(f"  degrade: {d['what']} at {d['role']} ({d['note']})")
    elastic = narrative.get("membership")
    if elastic:
        parts = []
        for d in elastic.get("drains", ()):
            parts.append(f"worker {d['worker']} drained"
                         + (f" ({d['note']})" if d.get("note") else ""))
        for e in elastic.get("evictions", ()):
            parts.append(f"worker {e['worker']} evicted (reap)")
        if elastic.get("quorum_closes"):
            parts.append(f"{elastic['quorum_closes']} quorum closes")
        if elastic.get("stale_folds"):
            parts.append(f"{elastic['stale_folds']} stale folds")
        lines.append(f"  membership: {', '.join(parts)}")
    publish = narrative.get("publication")
    if publish:
        parts = []
        if publish.get("subscriptions"):
            parts.append(f"{publish['subscriptions']} subscriptions")
        if publish.get("swaps"):
            parts.append(f"{publish['swaps']} weight swaps "
                         f"(last version {publish.get('last_version', '?')})")
        if publish.get("max_lag"):
            parts.append(f"max lag {publish['max_lag']} versions")
        lines.append(f"  weight publication: {', '.join(parts)}")
    tl = rep.get("timeline")
    if tl:
        lines.append(f"iteration {rep['iteration']}:")
        if "barrier_width" in tl:
            straggler = ""
            if "straggler" in tl:
                straggler = (f", straggler {tl['straggler_group']}"
                             if "straggler_group" in tl
                             else f", straggler worker {tl['straggler']}")
            lines.append(f"  barrier: {tl.get('contributors', '?')}/"
                         f"{tl['barrier_width']} contributors, "
                         f"commit spread "
                         f"{_fmt_dt(tl.get('commit_spread_s', 0.0))}"
                         + straggler)
        for gid in sorted(tl.get("groups", {})):
            g = tl["groups"][gid]
            parts = [f"{g.get('sealed_members', '?')}/"
                     f"{g.get('group_size', '?')} members sealed"]
            if "upstream_s" in g:
                parts.append(f"upstream {_fmt_dt(g['upstream_s'])} "
                             f"({g.get('upstream_bytes', 0)} B quantized)")
            lines.append(f"  {_group_label(gid)}: {', '.join(parts)}")
        quorum = tl.get("quorum")
        if quorum:
            outside = quorum.get("outside")
            lines.append(
                f"  QUORUM close: {quorum['contributors']}/"
                f"{quorum['width']} contributors"
                + (", left outside: "
                   + ", ".join(f"worker {w}" for w in outside)
                   if outside else ""))
        for fold in tl.get("stale_folds", ()):
            lines.append(f"  stale fold: worker {fold['worker']} carried "
                         f"in at staleness {fold['staleness']} "
                         f"({fold['tensors']} tensors, lr damped)")
        if "apply_s" in tl:
            lines.append(f"  optimizer apply: {_fmt_dt(tl['apply_s'])}")
        arena = tl.get("arena")
        if arena:
            parts = []
            if "pack_s" in arena:
                parts.append(
                    ("repack " if arena.get("repacked") else "pack ")
                    + _fmt_dt(arena["pack_s"]))
            parts.append(f"dispatch {_fmt_dt(arena['dispatch_s'])}")
            parts.append(f"readback {_fmt_dt(arena['readback_s'])}")
            lines.append("  arena: " + " + ".join(parts))
        if tl.get("arena_fallback") is not None and "arena" not in tl:
            lines.append("  arena: FELL BACK to per-tensor "
                         f"({tl['arena_fallback'] or 'unknown'})")
        dserve = tl.get("delta_serve")
        if dserve:
            note = (f"  delta serve: {dserve['hits']} chain hits "
                    f"({dserve['delta_bytes']} B), "
                    f"{dserve['misses']} full serves")
            if dserve.get("miss_reasons"):
                note += f" ({', '.join(dserve['miss_reasons'])})"
            lines.append(note)
        for wid in sorted(tl.get("workers", {})):
            w = tl["workers"][wid]
            parts = []
            if "step_start" in w and "step_end" in w:
                parts.append(
                    f"step {_fmt_dt(w['step_end'] - w['step_start'])}")
            elif "step_start" in w:
                parts.append("step OPEN (in flight at death?)")
            if w.get("commits", 0) > 1:
                parts.append(f"{w['commits']} commits (retried)")
            if "failover_retry" in w:
                parts.append(f"failed over to {w['failover_retry']}")
            if w.get("tier_folds"):
                parts.append(f"{w['tier_folds']} leaf folds (tiered)")
            lines.append(f"  worker {wid}: "
                         + (", ".join(parts) if parts
                            else f"{w['events']} events"))
        path = rep.get("critical_path") or []
        if path:
            lines.append("  critical path to barrier close:")
            for link in path:
                lines.append(f"    +{_fmt_dt(link['dt_s'])} {link['what']}")
    return "\n".join(lines)


def chrome_events(events: list[dict]) -> list[dict]:
    """Flight events as Chrome-trace events: paired ``*.start``/``*.end``
    become ``ph="X"`` duration slices, everything else ``ph="i"``
    instants.  pid/tid lanes match the span layer's own dumps, so the
    merged file lines flight evidence up under the spans in Perfetto."""
    out: list[dict] = []
    starts = {name[:-6] for name in flight.EVENTS if name.endswith(".start")}
    paired = {base for base in starts if f"{base}.end" in flight.EVENTS}
    for base in paired:
        matched, opens = _pairs(events, f"{base}.start", f"{base}.end",
                                return_open=True)
        for start, end in matched:
            out.append({
                "name": base, "ph": "X", "cat": "flight",
                "ts": start["ts"] * 1e6,
                "dur": max(end["ts"] - start["ts"], 1e-7) * 1e6,
                "pid": start["pid"], "tid": start["tid"],
                "args": {k: start[k] for k in
                         ("iteration", "worker", "a", "b", "note")
                         if start.get(k) not in (None, "", -1)},
            })
        for start in opens:
            # an operation in flight when the process died (or when the
            # ring was snapshotted): exactly the crash-point evidence —
            # render as a marked instant, never drop it
            out.append({
                "name": f"{base} (open)", "ph": "i", "cat": "flight",
                "s": "p", "ts": start["ts"] * 1e6,
                "pid": start["pid"], "tid": start["tid"],
                "args": {k: start[k] for k in
                         ("iteration", "worker", "a", "b", "note")
                         if start.get(k) not in (None, "", -1)},
            })
    instant = {f"{b}.start" for b in paired} | {f"{b}.end" for b in paired}
    for ev in events:
        if ev["event"] in instant:
            continue
        args = {k: ev[k] for k in
                ("iteration", "worker", "a", "b", "note")
                if ev.get(k) not in (None, "", -1)}
        args["decode"] = describe_event(ev["event"])
        out.append({
            "name": ev["event"], "ph": "i", "cat": "flight", "s": "p",
            "ts": ev["ts"] * 1e6, "pid": ev["pid"], "tid": ev["tid"],
            "args": args,
        })
    out.sort(key=lambda e: e["ts"])
    return out


def export_chrome_trace(directory: str, out_path: str) -> str:
    """Merged Chrome trace of the directory's flight rings PLUS any span
    dumps (``*.json`` written by ``PSDT_TRACE_FILE``) in the same
    directory — the one-file Perfetto view of a postmortem."""
    events = chrome_events(merge_events(load_rings(directory)))
    for path in sorted(glob.glob(os.path.join(directory, "*.json"))):
        if os.path.abspath(path) == os.path.abspath(out_path):
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
            events.extend(doc["traceEvents"] if isinstance(doc, dict)
                          else doc)
        except (OSError, ValueError, KeyError):
            continue  # not a chrome trace: skip, don't die
    events.sort(key=lambda e: e.get("ts", 0.0))
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return out_path
