"""Cluster metric export: heartbeat piggyback + coordinator aggregation.

Workers serialize their registry snapshot (:func:`snapshot_blob`) into the
``obs_snapshot`` extension field of every heartbeat (rpc/messages.py —
reference coordinators skip the unknown field).  The coordinator keeps the
latest snapshot per worker (:class:`ClusterAggregator`) and serves the
rollup over the ``GetClusterMetrics`` extension RPC, which
``pst-status --metrics`` renders: per-worker RPC p50/p95 latency, wire-byte
totals, step-phase breakdown, and the cluster straggler spread — the
telemetry elastic-membership and quantized-transport tuning need
(PAPERS.md: arXiv:2204.03211, arXiv:2506.17615).
"""

from __future__ import annotations

import json
import time
from typing import Any

from ..analysis.lock_order import checked_lock
from .stats import REGISTRY, percentile_from

# step-phase histograms recorded by worker/worker.py, in display order
# ("fused" is the single push→barrier→pull round of the pipelined data
# plane; the serial pull/push/barrier_wait phases appear when it is off
# or degraded)
_PHASES = ("data", "pull", "compute", "push", "fused", "barrier_wait")


def snapshot_blob(**extra: Any) -> bytes:
    """The process registry as JSON bytes, ready for the heartbeat
    extension field.  ``extra`` rides alongside (worker_id etc.)."""
    snap = REGISTRY.snapshot()
    snap["t"] = time.time()
    snap.update(extra)
    return json.dumps(snap, default=float).encode("utf-8")


def _hist_stats(snap: dict, name: str) -> dict | None:
    h = snap.get("histograms", {}).get(name)
    if not h or not h.get("count"):
        return None
    return {"count": h["count"],
            "mean": h["sum"] / h["count"],
            "p50": percentile_from(h, 50),
            "p95": percentile_from(h, 95)}


def _sum_counters(snap: dict, suffix: str, prefix: str = "") -> int:
    return sum(v for k, v in snap.get("counters", {}).items()
               if k.endswith(suffix) and k.startswith(prefix))


def _ps_rollup(snap: dict) -> dict:
    """PS-side hot-path metrics present in a snapshot (a colocated PS —
    tests, bench, single-process demos — shares the process registry, so
    its instruments ride the worker's heartbeat snapshot): the serve
    encode-once cache hit/miss counters, the barrier-close latency, and
    the peak resident gradient-buffer gauge (server/ps_service.py,
    core/ps_core.py)."""
    out: dict = {}
    counters = snap.get("counters", {})
    hits = counters.get("ps.serve.cache_hit", 0)
    misses = counters.get("ps.serve.cache_miss", 0)
    if hits or misses:
        out["serve_cache"] = {"hits": hits, "misses": misses}
    # versioned delta serving (delta/, ISSUE 10): chain-hit vs full-serve
    # fallbacks plus the actual delta wire volume served
    delta: dict = {}
    for key, name in (("hits", "ps.serve.delta_hit"),
                      ("misses", "ps.serve.delta_miss"),
                      ("bytes", "ps.serve.delta_bytes")):
        value = counters.get(name, 0)
        if value:
            delta[key] = value
    if delta:
        out["delta"] = delta
    # accelerator-resident apply (core/device_apply.py, ISSUE 11):
    # device-resident barrier closes next to the selection downgrades
    device: dict = {}
    for key, name in (("applies", "ps.apply.device"),
                      ("fallbacks", "ps.apply.device_fallback")):
        value = counters.get(name, 0)
        if value:
            device[key] = value
    if device:
        out["device_apply"] = device
    # flat arena apply (core/arena.py, ISSUE 15): mega-array closes,
    # per-close downgrades to the per-tensor path, and the packing
    # padding overhead (the PSDT_ARENA_ALIGN cost)
    arena: dict = {}
    for key, name in (("applies", "ps.apply.arena"),
                      ("fallbacks", "ps.apply.arena_fallback")):
        value = counters.get(name, 0)
        if value:
            arena[key] = value
    pad = snap.get("gauges", {}).get("ps.apply.arena_pad")
    if arena and pad is not None:
        arena["pad"] = pad
    if arena:
        out["arena"] = arena
    # free-running barrier-free training (freerun/, ISSUE 16):
    # apply-on-arrival volume, version-vector dedups, floor drops,
    # coalesced publications, the live staleness distribution, and the
    # per-unit-staleness damp the schedule currently applies
    freerun: dict = {}
    for key, name in (("applies", "ps.freerun.applies"),
                      ("duplicates", "ps.freerun.duplicates"),
                      ("floor_drops", "ps.freerun.floor_drops"),
                      ("publishes", "ps.freerun.publishes")):
        value = counters.get(name, 0)
        if value:
            freerun[key] = value
    staleness = _hist_stats(snap, "ps.freerun.staleness")
    if staleness:
        freerun["staleness"] = staleness
    beta = snap.get("gauges", {}).get("ps.freerun.effective_beta")
    if freerun and beta is not None:
        freerun["effective_beta"] = beta
    if freerun:
        out["freerun"] = freerun
    # elastic quorum barriers (elastic/, ISSUE 13): K-of-N closes and
    # straggler gradients folded forward damped
    quorum = counters.get("ps.barrier.quorum_closes", 0)
    if quorum:
        out["quorum_closes"] = quorum
    stale = counters.get("ps.stale.folds", 0)
    if stale:
        out["stale_folds"] = stale
    close = _hist_stats(snap, "ps.barrier_close_s")
    if close:
        out["barrier_close"] = close
    peak = snap.get("gauges", {}).get("ps.peak_grad_buffer_bytes", 0)
    if peak:
        out["peak_grad_buffer_bytes"] = peak
    # striped hot path (core/ps_core.py, PSDT_STRIPES): per-stripe apply
    # wall time + the achieved parallelism of the last striped apply
    stripe = _hist_stats(snap, "ps.apply.stripe_ms")
    if stripe:
        out["apply_stripe_ms"] = stripe
    par = snap.get("gauges", {}).get("ps.apply.parallelism", 0)
    if par:
        out["apply_parallelism"] = par
    # replication / failover / resharding (replication/, ISSUE 7)
    replica: dict = {}
    shipped = counters.get("ps.replica.shipped_bytes", 0)
    if shipped:
        replica["shipped_bytes"] = shipped
    lag = snap.get("gauges", {}).get("ps.replica.lag_bytes", 0)
    if lag:
        replica["lag_bytes"] = lag
    ship = _hist_stats(snap, "ps.replica.ship_s")
    if ship:
        replica["ship_s"] = ship
    for key, name in (("promotions", "ps.replica.promotions"),
                      ("failovers", "ps.replica.failovers"),
                      ("fallbacks", "ps.replica.fallback"),
                      ("installed_bytes", "ps.replica.installed_bytes"),
                      ("reshard_moved_bytes", "ps.reshard.moved_bytes")):
        value = counters.get(name, 0)
        if value:
            replica[key] = value
    # cross-replica sharded update (replication/sharded_update.py,
    # ISSUE 18): sharded closes vs local fallbacks on the primary, the
    # exchange payload volume, and the backup-side slice applies
    for key, name in (("sharded_closes", "ps.apply.sharded"),
                      ("sharded_fallbacks", "ps.apply.sharded_fallback"),
                      ("sharded_bytes", "ps.replica.sharded_bytes"),
                      ("sharded_applies", "ps.replica.sharded_applies")):
        value = counters.get(name, 0)
        if value:
            replica[key] = value
    # 1 while this backup replicates by flat SHIPPING only (its
    # accelerator idle through every close), cleared by the first
    # sharded slice apply
    if snap.get("gauges", {}).get("ps.replica.idle_accelerator"):
        replica["idle_accelerator"] = True
    # a promoted primary serving with NO backup (ISSUE 9 satellite):
    # the unreplicated window the standby re-arm closes
    if snap.get("gauges", {}).get("ps.replica.unarmed"):
        replica["unarmed"] = True
    if replica:
        out["replica"] = replica
    # hierarchical aggregation (tiers/, ISSUE 9): leaf relay volume +
    # downgrade count, recorded wherever the leaf/worker runtime lives
    tier: dict = {}
    for key, name in (("upstream_bytes", "tier.upstream_bytes"),
                      ("relays", "tier.relays"),
                      ("rounds", "tier.rounds"),
                      ("downgrades", "tier.downgrades")):
        value = counters.get(name, 0)
        if value:
            tier[key] = value
    upstream = _hist_stats(snap, "tier.upstream_s")
    if upstream:
        tier["upstream_s"] = upstream
    size = snap.get("gauges", {}).get("tier.group_size", 0)
    if size:
        tier["group_size"] = size
    if tier:
        out["tier"] = tier
    return out


def worker_rollup(snap: dict) -> dict:
    """Derived per-worker view of one snapshot: per-method RPC latency
    percentiles, wire-byte totals, and the step-phase breakdown."""
    rpc: dict[str, dict] = {}
    for name in snap.get("histograms", {}):
        if name.startswith("rpc.client.") and name.endswith(".latency_s"):
            method = name[len("rpc.client."):-len(".latency_s")]
            stats = _hist_stats(snap, name)
            if stats:
                rpc[method] = stats
    phases = {}
    for phase in _PHASES:
        stats = _hist_stats(snap, f"worker.{phase}_s")
        if stats:
            phases[phase] = stats
    out = {
        "rpc": rpc,
        "phases": phases,
        "step": _hist_stats(snap, "worker.step_s"),
        "bytes_sent": _sum_counters(snap, ".request_bytes", "rpc.client."),
        "bytes_received": _sum_counters(snap, ".response_bytes",
                                        "rpc.client."),
        "retries": snap.get("counters", {}).get("rpc.client.retries", 0),
        "t": snap.get("t"),
    }
    ps = _ps_rollup(snap)
    if ps:
        out["ps"] = ps
    # native data plane (ISSUE 6): which codec this process resolved
    # (rpc.codec.native gauge) and how much of its fused traffic rode the
    # same-host shared-memory rings vs downgraded to TCP
    shm_bytes = snap.get("counters", {}).get("rpc.shm.bytes", 0)
    shm_fallback = snap.get("counters", {}).get("rpc.shm.fallback", 0)
    codec_native = snap.get("gauges", {}).get("rpc.codec.native")
    if shm_bytes or shm_fallback or codec_native is not None:
        out["native_plane"] = {
            "codec_native": codec_native,
            "shm_bytes": shm_bytes,
            "shm_fallbacks": shm_fallback,
        }
    payload = _sum_counters(snap, ".payload_bytes", "rpc.client.")
    if payload:
        # uncompressed (f32) size of the tensors that rode those wire
        # bytes — the with/without-compression comparison in one view
        out["payload_bytes_f32"] = payload
        # The matching denominator, preferring the worker's exact
        # wire-encoded tensor byte counter (rpc.client.push.wire_bytes —
        # uniform across the unary/stream/fused push paths); older
        # snapshots fall back to the push methods' request_bytes
        # (bytes_sent alone also counts heartbeat snapshots, sync polls,
        # and registration, which would understate the ratio).
        push = _sum_counters(snap, "push.wire_bytes", "rpc.client.")
        if not push:
            push = sum(_sum_counters(snap, ".request_bytes",
                                     f"rpc.client.{method}")
                       for method in ("ReceiveGradients",
                                      "PushGradientsStream",
                                      "PushPullStream"))
        if push:
            out["push_bytes"] = push
    return out


class ClusterAggregator:
    """Latest snapshot per worker + the cluster rollup.

    Entries expire after ``ttl_s`` without a heartbeat so an evicted
    worker's stale numbers do not skew the straggler spread forever."""

    def __init__(self, ttl_s: float = 120.0):
        # leaf rank: held only around snapshot-dict ops
        # (analysis/lock_order.py; order-asserted under PSDT_LOCK_CHECK=1)
        self._lock = checked_lock("ClusterAggregator._lock")
        self._snaps: dict[int, dict] = {}
        self._ttl_s = ttl_s

    def ingest(self, worker_id: int, blob: bytes | str) -> bool:
        if not blob:
            return False
        try:
            snap = json.loads(bytes(blob).decode("utf-8")
                              if not isinstance(blob, str) else blob)
        except (ValueError, UnicodeDecodeError):
            return False
        snap["received_t"] = time.time()
        with self._lock:
            self._snaps[int(worker_id)] = snap
        return True

    def snapshots(self) -> dict[int, dict]:
        now = time.time()
        with self._lock:
            for wid in [w for w, s in self._snaps.items()
                        if now - s.get("received_t", now) > self._ttl_s]:
                del self._snaps[wid]
            return {wid: dict(snap) for wid, snap in self._snaps.items()}

    def rollup(self) -> dict:
        """Cluster view: per-worker derived metrics plus cross-worker
        aggregates (straggler spread, slowest RPC p95, byte totals)."""
        per_worker = {wid: worker_rollup(snap)
                      for wid, snap in self.snapshots().items()}
        step_p50s = {wid: w["step"]["p50"] for wid, w in per_worker.items()
                     if w.get("step")}
        rpc_worst: dict[str, dict] = {}
        for wid, w in per_worker.items():
            for method, stats in w["rpc"].items():
                worst = rpc_worst.get(method)
                if worst is None or stats["p95"] > worst["p95"]:
                    rpc_worst[method] = {**stats, "worker": wid}
        cluster = {
            "workers": len(per_worker),
            "bytes_sent": sum(w["bytes_sent"]
                              for w in per_worker.values()),
            "bytes_received": sum(w["bytes_received"]
                                  for w in per_worker.values()),
            "slowest_rpc": rpc_worst,
        }
        if step_p50s:
            fastest, slowest = min(step_p50s.values()), max(step_p50s.values())
            cluster["straggler"] = {
                "fastest_p50_s": fastest, "slowest_p50_s": slowest,
                "spread": slowest / fastest if fastest > 0 else float("inf"),
                "slowest_worker": max(step_p50s, key=step_p50s.get),
            }
        return {"per_worker": per_worker, "cluster": cluster}


def _fmt_s(v: float | None) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.2f}s"


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def render_membership(membership: dict) -> str:
    """One-line view of the coordinator's membership rollup (elastic/,
    ISSUE 13): ``"3 active, 1 draining, 2 gone (epoch 7)"``."""
    states = membership.get("states", {})
    order = ("active", "joining", "draining", "gone")
    parts = [f"{states[k]} {k}" for k in order if states.get(k)]
    parts += [f"{v} {k}" for k, v in sorted(states.items())
              if k not in order and v]
    return (", ".join(parts) if parts else "no members") + \
        f" (epoch {membership.get('epoch', 0)})"


def render_fleet(fleet: dict) -> str:
    """One-line view of the coordinator's decode-fleet rollup (fleet/,
    ISSUE 14): ``"4 active (27/32 slots free, queue 3), versions
    v3..v4, target 4 (epoch 9)"``."""
    states = fleet.get("states", {})
    order = ("active", "joining", "draining", "gone")
    parts = [f"{states[k]} {k}" for k in order if states.get(k)]
    parts += [f"{v} {k}" for k, v in sorted(states.items())
              if k not in order and v]
    line = ", ".join(parts) if parts else "no servers"
    line += (f" ({fleet.get('free_slots', 0)}/{fleet.get('slots', 0)} "
             f"slots free, queue {fleet.get('queue_depth', 0)})")
    versions = fleet.get("versions") or []
    if versions:
        line += (f", version v{versions[0]}" if len(versions) == 1 else
                 f", versions v{versions[0]}..v{versions[-1]}")
    target = fleet.get("target", 0)
    line += f", target {target}" if target else ", autoscale"
    return line + f" (epoch {fleet.get('epoch', 0)})"


def render_rollup(rollup: dict) -> str:
    """Human view of :meth:`ClusterAggregator.rollup` for pst-status."""
    lines: list[str] = []
    cluster = rollup.get("cluster", {})
    lines.append(f"cluster metrics ({cluster.get('workers', 0)} workers "
                 f"reporting)")
    straggler = cluster.get("straggler")
    if straggler:
        lines.append(
            f"  step p50 spread: {_fmt_s(straggler['fastest_p50_s'])} .. "
            f"{_fmt_s(straggler['slowest_p50_s'])} "
            f"({straggler['spread']:.2f}x, slowest worker "
            f"{straggler['slowest_worker']})")
    lines.append(f"  wire bytes: {_fmt_bytes(cluster.get('bytes_sent', 0))} "
                 f"sent / {_fmt_bytes(cluster.get('bytes_received', 0))} "
                 f"received (client-side totals)")
    membership = rollup.get("membership")
    if membership:
        lines.append("  membership: "
                     + render_membership(membership))
    fleet = rollup.get("fleet")
    if fleet:
        lines.append("  fleet: " + render_fleet(fleet))
    for method, stats in sorted(cluster.get("slowest_rpc", {}).items()):
        lines.append(f"  slowest {method}: p95 {_fmt_s(stats['p95'])} "
                     f"(worker {stats['worker']})")
    for wid, w in sorted(rollup.get("per_worker", {}).items()):
        lines.append(f"  worker {wid}:")
        for method, stats in sorted(w["rpc"].items()):
            lines.append(
                f"    rpc {method}: n={stats['count']} "
                f"p50={_fmt_s(stats['p50'])} p95={_fmt_s(stats['p95'])}")
        if w.get("phases"):
            parts = " ".join(
                f"{phase}={_fmt_s(stats['p50'])}"
                for phase, stats in w["phases"].items())
            lines.append(f"    step phases (p50): {parts}")
        ps = w.get("ps")
        if ps:
            parts = []
            cache = ps.get("serve_cache")
            if cache:
                total = cache["hits"] + cache["misses"]
                parts.append(f"serve cache {cache['hits']}/{total} hits "
                             f"({cache['misses']} encodes)")
            dserve = ps.get("delta")
            if dserve:
                total = dserve.get("hits", 0) + dserve.get("misses", 0)
                parts.append(
                    f"delta serve {dserve.get('hits', 0)}/{total} hits "
                    f"({_fmt_bytes(dserve.get('bytes', 0))} delta)")
            dapply = ps.get("device_apply")
            if dapply:
                note = f"device apply {dapply.get('applies', 0)} closes"
                if dapply.get("fallbacks"):
                    note += f" ({dapply['fallbacks']} fallbacks)"
                parts.append(note)
            arena = ps.get("arena")
            if arena:
                note = f"arena {arena.get('applies', 0)} flat closes"
                extras = []
                if arena.get("fallbacks"):
                    extras.append(f"{arena['fallbacks']} fallbacks")
                if arena.get("pad"):
                    extras.append(f"pad {100 * arena['pad']:.1f}%")
                if extras:
                    note += f" ({', '.join(extras)})"
                parts.append(note)
            fr = ps.get("freerun")
            if fr:
                note = f"freerun {fr.get('applies', 0)} applies"
                extras = []
                if fr.get("duplicates"):
                    extras.append(f"{fr['duplicates']} dups")
                if fr.get("floor_drops"):
                    extras.append(f"{fr['floor_drops']} floor drops")
                if fr.get("publishes"):
                    extras.append(f"{fr['publishes']} publishes")
                if extras:
                    note += f" ({', '.join(extras)})"
                stl = fr.get("staleness")
                if stl:
                    note += (f", staleness p50={stl['p50']:.1f} "
                             f"p95={stl['p95']:.1f}")
                if fr.get("effective_beta") is not None:
                    note += f", eff beta {fr['effective_beta']:.4f}"
                parts.append(note)
            if ps.get("quorum_closes"):
                parts.append(f"{ps['quorum_closes']} quorum closes")
            if ps.get("stale_folds"):
                parts.append(f"{ps['stale_folds']} stale folds")
            close = ps.get("barrier_close")
            if close:
                parts.append(f"barrier close p50={_fmt_s(close['p50'])}")
            stripe = ps.get("apply_stripe_ms")
            if stripe:
                note = (f"apply stripes p50={stripe['p50']:.2f}ms")
                par = ps.get("apply_parallelism")
                if par:
                    note += f" ({par:g}x parallel)"
                parts.append(note)
            peak = ps.get("peak_grad_buffer_bytes")
            if peak:
                parts.append(f"peak grad buffer {_fmt_bytes(peak)}")
            lines.append(f"    ps: {', '.join(parts)}")
            replica = ps.get("replica")
            if replica:
                rparts = []
                if replica.get("shipped_bytes"):
                    note = f"shipped {_fmt_bytes(replica['shipped_bytes'])}"
                    ship = replica.get("ship_s")
                    if ship:
                        note += f" (ship p50={_fmt_s(ship['p50'])})"
                    rparts.append(note)
                if replica.get("lag_bytes"):
                    rparts.append(f"lag {_fmt_bytes(replica['lag_bytes'])}")
                if replica.get("installed_bytes"):
                    rparts.append(
                        f"installed {_fmt_bytes(replica['installed_bytes'])}")
                if replica.get("promotions"):
                    rparts.append(f"{replica['promotions']} promotions")
                if replica.get("failovers"):
                    rparts.append(f"{replica['failovers']} failovers")
                if replica.get("fallbacks"):
                    rparts.append(f"{replica['fallbacks']} fallbacks")
                if replica.get("reshard_moved_bytes"):
                    rparts.append(
                        "reshard moved "
                        + _fmt_bytes(replica["reshard_moved_bytes"]))
                if replica.get("sharded_closes"):
                    rparts.append(
                        f"{replica['sharded_closes']} sharded closes "
                        f"({_fmt_bytes(replica.get('sharded_bytes', 0))} "
                        f"exchanged)")
                if replica.get("sharded_fallbacks"):
                    rparts.append(f"{replica['sharded_fallbacks']} "
                                  f"sharded fallbacks")
                if replica.get("sharded_applies"):
                    rparts.append(f"{replica['sharded_applies']} "
                                  f"sharded slice applies")
                if replica.get("idle_accelerator"):
                    rparts.append("idle accelerator (flat-ship replica)")
                if replica.get("unarmed"):
                    rparts.append("UNARMED (promoted primary, no backup)")
                lines.append(f"    replication: {', '.join(rparts)}")
            tier = ps.get("tier")
            if tier:
                tparts = []
                if tier.get("relays"):
                    note = (f"{tier['relays']} relays "
                            f"({_fmt_bytes(tier.get('upstream_bytes', 0))} "
                            f"quantized upstream)")
                    up = tier.get("upstream_s")
                    if up:
                        note += f" p50={_fmt_s(up['p50'])}"
                    tparts.append(note)
                if tier.get("group_size"):
                    tparts.append(f"group of {tier['group_size']:g}")
                if tier.get("rounds"):
                    tparts.append(f"{tier['rounds']} tiered rounds")
                if tier.get("downgrades"):
                    tparts.append(f"{tier['downgrades']} downgrades")
                lines.append(f"    tiers: {', '.join(tparts)}")
        native_plane = w.get("native_plane")
        if native_plane:
            parts = []
            if native_plane.get("codec_native") is not None:
                parts.append("codec="
                             + ("native" if native_plane["codec_native"]
                                else "python"))
            if native_plane.get("shm_bytes"):
                parts.append(
                    f"shm {_fmt_bytes(native_plane['shm_bytes'])}")
            if native_plane.get("shm_fallbacks"):
                parts.append(
                    f"{native_plane['shm_fallbacks']} shm fallbacks")
            if parts:
                lines.append(f"    data plane: {', '.join(parts)}")
        extra = (f"    bytes: {_fmt_bytes(w['bytes_sent'])} sent / "
                 f"{_fmt_bytes(w['bytes_received'])} received")
        if w.get("payload_bytes_f32"):
            ratio = (w["payload_bytes_f32"]
                     / max(1, w.get("push_bytes") or w["bytes_sent"]))
            extra += (f" (f32 payload {_fmt_bytes(w['payload_bytes_f32'])}"
                      f", {ratio:.1f}x compression)")
        if w.get("retries"):
            extra += f", {w['retries']} retries"
        lines.append(extra)
    return "\n".join(lines)
