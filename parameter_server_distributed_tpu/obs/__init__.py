"""Cluster-wide observability subsystem.

Three layers, one package (the reference's observability was bare stdout
prints — SURVEY.md §5):

- :mod:`~parameter_server_distributed_tpu.obs.trace` — trace/span IDs with
  a thread-local current-span stack, propagated across processes via a
  high-numbered extension field on the RPC request messages (reference
  protoc gencode skips unknown fields, so C++ peers are unaffected —
  tests/test_wire_interop.py), exported as Chrome-trace (catapult) JSON so
  one distributed training step renders in ``chrome://tracing``/Perfetto;
- :mod:`~parameter_server_distributed_tpu.obs.stats` — cheap log-bucket
  histograms, counters, and gauges behind a process-wide registry; every
  RPC endpoint, step phase, and serving loop reports here;
- :mod:`~parameter_server_distributed_tpu.obs.export` — workers piggyback
  registry snapshots on heartbeats, the coordinator aggregates them
  per-worker, and ``pst-status --metrics`` prints the cluster rollup.

``utils/metrics.py`` (StepTimer, MetricsLogger, profile_trace) folded in
here; the old module re-exports for backward compatibility.
"""

from . import export, stats, trace
from .stats import (MetricsLogger, StepTimer, profile_trace,
                    samples_per_sec)

__all__ = ["trace", "stats", "export", "StepTimer", "MetricsLogger",
           "profile_trace", "samples_per_sec"]
