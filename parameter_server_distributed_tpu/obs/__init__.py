"""Cluster-wide observability subsystem.

Three layers, one package (the reference's observability was bare stdout
prints — SURVEY.md §5):

- :mod:`~parameter_server_distributed_tpu.obs.trace` — trace/span IDs with
  a thread-local current-span stack, propagated across processes via a
  high-numbered extension field on the RPC request messages (reference
  protoc gencode skips unknown fields, so C++ peers are unaffected —
  tests/test_wire_interop.py), exported as Chrome-trace (catapult) JSON so
  one distributed training step renders in ``chrome://tracing``/Perfetto;
- :mod:`~parameter_server_distributed_tpu.obs.stats` — cheap log-bucket
  histograms, counters, and gauges behind a process-wide registry; every
  RPC endpoint, step phase, and serving loop reports here;
- :mod:`~parameter_server_distributed_tpu.obs.export` — workers piggyback
  registry snapshots on heartbeats, the coordinator aggregates them
  per-worker, and ``pst-status --metrics`` prints the cluster rollup;
- :mod:`~parameter_server_distributed_tpu.obs.flight` — the
  crash-surviving flight recorder: an always-on mmap-backed event ring
  per process under ``PSDT_FLIGHT_DIR``, decodable after ``kill -9``;
- :mod:`~parameter_server_distributed_tpu.obs.postmortem` — merges the
  rings of all processes (dead ones included) into cross-process
  iteration postmortems with critical-path/straggler attribution; the
  ``pst-trace`` CLI renders them.

``utils/metrics.py`` (StepTimer, MetricsLogger, profile_trace) folded in
here; the old module re-exports for backward compatibility.
"""

from . import export, flight, postmortem, stats, trace
from .stats import (MetricsLogger, StepTimer, profile_trace,
                    samples_per_sec)

__all__ = ["trace", "stats", "export", "flight", "postmortem",
           "StepTimer", "MetricsLogger", "profile_trace",
           "samples_per_sec"]
