"""Crash-surviving flight recorder: a per-process mmap-backed event ring.

PR 1's ``obs/`` layer is live-only: counters and in-memory spans die with
the process, so a backup PS that segfaults under post-failover churn (the
PR-7 known flake) leaves NO evidence.  This module is the black box the
postmortem toolchain (:mod:`obs.postmortem`, ``pst-trace``) reads after
the fact — including for processes that died by ``kill -9``.

Design constraints, in order:

1. **Crash-surviving.**  The ring is a fixed-size file under
   ``PSDT_FLIGHT_DIR`` mapped MAP_SHARED: every record lands in the page
   cache the instant it is written, so a SIGKILL/SIGSEGV loses at most
   the record being written (and the seq field is written LAST, so a torn
   record is recognizably invalid, never silently wrong).  No flush call
   is ever needed for survival — the kernel owns the pages.
2. **Always-on cheap.**  :func:`record` is one global truthiness check
   when no ring is open; with a ring it is one GIL-atomic counter
   increment + one ``struct.pack`` + two slice stores (~1-2 us) and takes
   NO lock — safe inside ``_state_lock`` and the striped fold hot path.
   The per-chunk fold class honors ``PSDT_FLIGHT_SAMPLE`` (record every
   Nth); paired start/end events are never sampled, so the postmortem's
   interval matching always reconstructs.
3. **Fixed decode.**  96-byte records: seq, wall-clock ts, tid, event
   code, (iteration, worker) — the postmortem join key — two i64 args and
   a 48-byte note (room for a full host:port).  The decoder needs only
   the header; unknown event codes stay decodable as ``ev<code>``.

Crash markers: a clean exit (atexit, or a chained SIGTERM handler) stamps
``clean=1`` in the header and records ``proc.exit``; a ring whose header
still says ``clean=0`` belonged to a process that DIED (kill -9, SIGSEGV,
OOM) — ``pst-trace`` flags it and its last records are the final evidence.
``faulthandler`` is armed at a ``crash-<pid>.txt`` sidecar in the same
directory, so fatal-signal tracebacks (SIGSEGV/SIGABRT/SIGBUS) survive
alongside the ring.

Env knobs: ``PSDT_FLIGHT_DIR`` (enables recording; the ring directory),
``PSDT_FLIGHT_RECORDS`` (ring capacity in records, default 65536 — 6 MB),
``PSDT_FLIGHT_SAMPLE`` (sample 1-in-N for the per-chunk fold records,
default 1 = everything).
"""

from __future__ import annotations

import atexit
import faulthandler
import itertools
import mmap
import os
import signal
import struct
import threading
import time
import uuid
from typing import Any

from ..analysis.lock_order import checked_lock

MAGIC = b"PSTFLT01"
HEADER_BYTES = 4096
RECORD_BYTES = 96
# header: magic, record_size, capacity, pid, start wall-clock, clean flag,
# reserved, role label
_HEADER_FMT = "<8sIIqdII64s"
# record: seq, ts, tid, code, flags, iteration, worker, a, b, note.
# The 48-byte note holds a full host:port address — the postmortem's
# PROMOTION/RETRY lines must name real endpoints, not truncated ones.
_RECORD_FMT = "<QdIHhiiqq48s"
assert struct.calcsize(_RECORD_FMT) == RECORD_BYTES
_NOTE_BYTES = 48

ENV_DIR = "PSDT_FLIGHT_DIR"
ENV_RECORDS = "PSDT_FLIGHT_RECORDS"
ENV_SAMPLE = "PSDT_FLIGHT_SAMPLE"
DEFAULT_RECORDS = 65536

# ---------------------------------------------------------------- event table
# One stable u16 code per structured event.  Append-only: codes are wire
# format for on-disk rings, so renumbering breaks old-ring decode.
EVENTS: dict[str, int] = {
    "proc.start": 1,
    "proc.exit": 2,
    "proc.sigterm": 3,
    # RPC edges, both ends (note = method name, truncated)
    "rpc.cli.start": 10,
    "rpc.cli.end": 11,       # a = duration_us, b = 1 ok / 0 error
    "rpc.srv.start": 12,
    "rpc.srv.end": 13,       # a = duration_us
    # worker step phases
    "step.start": 20,
    "step.end": 21,          # a = duration_us
    "fused.start": 22,
    "fused.end": 23,         # a = duration_us, b = 1 ok / 0 degraded
    "boot.seed": 24,         # worker seeded an empty store
    # PS barrier phase transitions (core/ps_core.py)
    "fold.reserve": 30,      # sampled; a = tensors in the chunk
    "push.commit": 31,       # a = contributors after, b = barrier width
    "barrier.seal": 32,      # a = contributors at seal
    "barrier.drain": 33,     # a = in-flight folds drained
    "apply.start": 34,
    "apply.end": 35,         # a = duration_us
    "barrier.publish": 36,   # a = contributors, b = barrier width
    "barrier.retry": 37,     # a failed close left the barrier retryable
    # replication / failover / resharding (replication/)
    "repl.ship.start": 40,   # a = bytes, b = params_version
    "repl.ship.end": 41,     # a = duration_us, b = params_version
    "repl.ack": 42,          # a = 1 ok / 0 refused, b = params_version
    "repl.install": 43,      # a = bytes, b = params_version
    "repl.refuse": 44,       # note = reason
    "repl.degrade": 45,      # replication permanently degraded
    "failover.report": 50,   # a = shard index; note = dead address
    "failover.promote": 51,  # a = shard index, b = new epoch; note = new
    "failover.retry": 52,    # a = shard index; note = replacement address
    "reshard.fence": 53,     # a = tensors retired, b = map epoch
    "reshard.install": 54,   # a = bytes, b = epoch
    "reshard.epoch": 55,     # a = new epoch, b = shard count
    # shm transport (rpc/shm_transport.py)
    "shm.negotiate": 60,     # a = connection index, b = ring bytes
    "shm.refuse": 61,        # note = reason
    "shm.attach": 62,        # client side; b = ring bytes
    "shm.downgrade": 63,     # note = reason
    "shm.reap": 64,          # a = connection index
    "shm.reap.dup": 65,      # second release attempt (latch hit)
    # codec selection (rpc/codec.py)
    "codec.select": 70,      # a = 1 native / 0 python
    "ckpt.restore": 71,
    # hierarchical aggregation (tiers/, ISSUE 9)
    "tier.elect": 80,        # a = group size, b = epoch (coordinator) or
                             # aggregate id (worker edge); note = leaf addr
    "tier.fold": 81,         # leaf edge, sampled: member push arriving;
                             # a = tensors, b = aggregate id
    "tier.seal": 82,         # leaf group sealed; a = contributors,
                             # b = group size (worker = aggregate id)
    "tier.upstream": 83,     # a = duration_us, b = quantized wire bytes
    "tier.downgrade": 84,    # permanent flat downgrade; note = reason
    # versioned delta serving + live weight publication (delta/, ISSUE 10)
    "serve.delta.build": 90,     # a = pair delta bytes, b = to_version
    "serve.delta.hit": 91,       # a = chain wire bytes, b = pairs served
    "serve.delta.miss": 92,      # a = held version, b = current version;
                                 # note = reason (no base / depth/reset /
                                 # dtype / disabled)
    "serve.delta.downgrade": 93,  # client-side permanent downgrade;
                                  # note = reason (checksum/UNIMPLEMENTED)
    "publish.subscribe": 94,     # a = held version, b = subscriber id
    "publish.swap": 95,          # a = new version, b = duration_us
    "publish.lag": 96,           # a = versions behind the training run
    # accelerator-resident sharded apply (core/device_apply.py, ISSUE 11)
    "apply.device": 100,          # device-resident barrier apply swapped
                                  # in; a = duration_us, b = stripes
    "apply.device.fallback": 101,  # device optimizer selection degraded
                                   # to the host family; note = reason
    "apply.readback": 102,        # async D2H readback of the fresh store
                                  # started; a = tensors
    # elastic membership + quorum barriers (elastic/, ISSUE 13)
    "elastic.join": 110,          # member ACTIVE; a = membership epoch
    "elastic.drain": 111,         # DRAINING (ctl/SIGTERM) or graceful
                                  # leave; a = epoch; note = reason
    "elastic.evict": 112,         # coordinator reap marked GONE;
                                  # a = epoch
    "quorum.seal": 113,           # barrier closed at K of N; a =
                                  # contributors, b = width; note =
                                  # contributor ids (comma list)
    "stale.fold": 114,            # straggler folded forward into
                                  # `iteration`; a = staleness,
                                  # b = tensors folded
    # decode fleet control plane (fleet/, ISSUE 14)
    "fleet.register": 120,        # decode server ACTIVE; a = slots,
                                  # b = fleet epoch; note = address
    "fleet.drain": 121,           # server DRAINING (scale-in / ctl);
                                  # a = fleet epoch
    "fleet.evict": 122,           # coordinator reap marked GONE;
                                  # a = fleet epoch
    "fleet.route": 123,           # router pinned a stream; a = request
                                  # id, b = server id; note = address
    "fleet.scale": 124,           # scale decision/target; a = target,
                                  # b = fleet epoch (coordinator) or
                                  # current size (autoscaler edge)
    "fleet.rollout": 125,         # rolling update step; a = version,
                                  # b = server id; note = phase
    "fleet.swap": 126,            # decode server swapped its serving
                                  # version; a = version, b = server id
    # flat arena apply (core/arena.py, ISSUE 15)
    "apply.arena.pack": 130,      # packing table built / param slabs
                                  # packed; a = duration_us, b = stripes
    "apply.arena.repack": 131,    # table REBUILT on a store-shape
                                  # change (epoch bump); a = duration_us
    "apply.arena.fallback": 132,  # a close downgraded to the per-tensor
                                  # path; note = reason (coverage /
                                  # counts / epoch / slots / latched)
    "apply.arena": 133,           # flat close published; a =
                                  # dispatch_us, b = readback_us
    # free-running barrier-free training (freerun/, ISSUE 16)
    "freerun.apply": 140,         # apply-on-arrival landed; a =
                                  # staleness, b = damp scale in ppm
    "freerun.dup": 141,           # version-vector dedup dropped an RPC
                                  # replay; a = last applied worker step
    "freerun.publish": 142,       # coalesced publication; a = published
                                  # version, b = applies coalesced
    "damp.floor": 143,            # a contribution damped below
                                  # PSDT_DAMP_FLOOR (effectively
                                  # dropped); a = staleness, b = scale
                                  # in ppb
    # cross-replica sharded update (replication/sharded_update.py)
    "shard.install": 150,         # partition shard installed into the
                                  # store; a = bytes, b = params_version
    "shard.update.degrade": 151,  # sharded close degraded to the
                                  # replicated path; note = reason
    "apply.sharded": 152,         # sharded close published; a =
                                  # replica count, b = wire bytes;
                                  # note = duration
    # radix-tree prefix cache (models/prefix_tree.py, ISSUE 20)
    "serve.prefix.hit": 160,      # suffix-only admission; a = prefix
                                  # tokens reused, b = suffix tokens
                                  # forwarded
    "serve.prefix.evict": 161,    # byte-budget LRU pass; a = nodes
                                  # evicted, b = bytes pinned after
    "serve.prefix.split": 162,    # edge split at a divergence point;
                                  # a = split-node depth, b = tree nodes
}
EVENT_NAMES = {code: name for name, code in EVENTS.items()}

# High-frequency classes that honor PSDT_FLIGHT_SAMPLE.  Only the
# per-chunk fold record qualifies: RPC start/end events are PAIRED
# (the postmortem matches them into intervals), and sampling the two
# halves independently would destroy the pairing — every RPC would
# decode as permanently open.
# tier.fold is the same per-member-push class at the leaf edge — one
# record per member stream, sampled alongside the per-chunk folds
SAMPLED = frozenset({EVENTS["fold.reserve"], EVENTS["tier.fold"]})


class FlightRecorder:
    """One process's ring.  Constructed open; every :meth:`record` claims
    a slot via a GIL-atomic counter and writes it lock-free (distinct
    slots, single writer each; the seq field is stored last so a record
    is valid only once fully written)."""

    def __init__(self, directory: str, role: str = "",
                 records: int | None = None, sample: int | None = None):
        self.directory = directory
        self.role = role or f"proc-{os.getpid()}"
        self.capacity = int(records if records is not None
                            else os.environ.get(ENV_RECORDS,
                                                str(DEFAULT_RECORDS)))
        if self.capacity < 16:
            self.capacity = 16
        self.sample = max(1, int(sample if sample is not None
                                 else os.environ.get(ENV_SAMPLE, "1")))
        os.makedirs(directory, exist_ok=True)
        # pid + uniquifier: a pid alone recycles under churn drives, and
        # a recycled pid must never O_TRUNC a DEAD process's ring — the
        # crash evidence this recorder exists to preserve
        self.path = os.path.join(
            directory,
            f"flight-{os.getpid()}-{uuid.uuid4().hex[:6]}.ring")
        size = HEADER_BYTES + self.capacity * RECORD_BYTES
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size, mmap.MAP_SHARED,
                                 mmap.PROT_READ | mmap.PROT_WRITE)
        finally:
            os.close(fd)
        self.start_wall = time.time()
        struct.pack_into(_HEADER_FMT, self._mm, 0, MAGIC, RECORD_BYTES,
                         self.capacity, os.getpid(), self.start_wall, 0, 0,
                         self.role.encode("utf-8", "replace")[:64])
        self._next = itertools.count()
        self._sample_next = itertools.count()
        self._closed = False
        self.record_event("proc.start", note=self.role[:16])

    # ------------------------------------------------------------- hot path
    def record_event(self, name_or_code: str | int, iteration: int = -1,
                     worker: int = -1, a: int = 0, b: int = 0,
                     note: str | bytes = b"") -> None:
        code = (name_or_code if isinstance(name_or_code, int)
                else EVENTS[name_or_code])
        if self.sample > 1 and code in SAMPLED \
                and next(self._sample_next) % self.sample:
            return
        if self._closed:
            return
        seq = next(self._next) + 1  # seq 0 = empty slot
        off = HEADER_BYTES + ((seq - 1) % self.capacity) * RECORD_BYTES
        if isinstance(note, str):
            note = note.encode("utf-8", "replace")
        rec = struct.pack(_RECORD_FMT, seq, time.time(),
                          threading.get_ident() & 0xFFFFFFFF, code, 0,
                          int(iteration), int(worker),
                          int(a), int(b), note[:_NOTE_BYTES])
        try:
            # seq zeroed FIRST, payload second, seq (bytes 0..8) LAST: a
            # write torn by a crash leaves a slot whose seq does not
            # match — invalid, never a plausible-but-wrong record.  The
            # zeroing matters once the ring has wrapped: without it the
            # slot's STALE seq (which maps to this same slot) would
            # validate a half-overwritten payload as an old record.
            self._mm[off:off + 8] = b"\x00" * 8
            self._mm[off + 8:off + RECORD_BYTES] = rec[8:]
            self._mm[off:off + 8] = rec[:8]
        except (ValueError, IndexError):  # ring closed under us (teardown)
            pass

    # ------------------------------------------------------------ lifecycle
    def mark_clean(self) -> None:
        """Stamp the clean-shutdown flag (header offset of the ``clean``
        u32: after magic+2*u32+q+d = 8+4+4+8+8 = 32)."""
        try:
            struct.pack_into("<I", self._mm, 32, 1)
        except ValueError:
            pass

    def set_role(self, role: str) -> None:
        self.role = role
        try:
            struct.pack_into("<64s", self._mm, 40,
                             role.encode("utf-8", "replace")[:64])
        except ValueError:
            pass

    def close(self, clean: bool = True) -> None:
        if self._closed:
            return
        self.record_event("proc.exit")
        if clean:
            self.mark_clean()
        self._closed = True
        try:
            self._mm.flush()
            self._mm.close()
        except (ValueError, OSError):
            pass


# --------------------------------------------------------------- module state
_rec: FlightRecorder | None = None
# serializes enable/disable/atexit (the file I/O under it is the lock's
# purpose — BLOCKING_ALLOWED in analysis/lock_order.py); never taken on
# the record() hot path
_lock = checked_lock("FlightRecorder._lock")
_signal_armed = False
_atexit_armed = False
_crash_file = None  # the faulthandler sidecar fd (one at a time)


def recorder() -> FlightRecorder | None:
    return _rec


def enabled() -> bool:
    return _rec is not None


def record(name_or_code: str | int, iteration: int = -1, worker: int = -1,
           a: int = 0, b: int = 0, note: str | bytes = b"") -> None:
    """Record one structured event into the process ring; no-op (one
    truthiness check) when the recorder is off."""
    rec = _rec
    if rec is None:
        return
    rec.record_event(name_or_code, iteration=iteration, worker=worker,
                     a=a, b=b, note=note)


def set_role(role: str) -> None:
    """Label this process's ring (e.g. ``ps:127.0.0.1:50051``,
    ``worker:0``, ``coordinator``) for the postmortem process listing."""
    with _lock:
        if _rec is not None:
            _rec.set_role(role)


def _at_exit() -> None:
    with _lock:
        if _rec is not None:
            _rec.close(clean=True)


def _arm_crash_handlers(directory: str) -> None:
    """faulthandler sidecar for fatal signals + a chained SIGTERM handler
    (servers normally die by SIGTERM, which skips atexit — without this
    their rings would read as crashes)."""
    global _signal_armed, _crash_file
    try:
        crash_path = os.path.join(directory,
                                  f"crash-{os.getpid()}.txt")
        # the fd stays open while armed — faulthandler needs a live fd at
        # signal time, and a 0-byte sidecar is the "no fatal signal"
        # marker pst-trace can skip.  Append mode: a recycled pid must
        # not truncate a dead predecessor's traceback.  One sidecar fd at
        # a time: re-arming (enable() into a new directory, bench arm
        # toggles) closes the previous one instead of leaking it.
        fh = open(crash_path, "a")
        faulthandler.enable(fh, all_threads=True)
        if _crash_file is not None:
            try:
                _crash_file.close()
            except OSError:
                pass
        _crash_file = fh
    except (OSError, ValueError, RuntimeError):
        pass
    if _signal_armed:
        return

    def _on_sigterm(signum, frame):
        rec = _rec
        if rec is not None:
            rec.record_event("proc.sigterm")
            rec.mark_clean()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    try:
        if signal.getsignal(signal.SIGTERM) is signal.SIG_DFL:
            signal.signal(signal.SIGTERM, _on_sigterm)
            _signal_armed = True
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


def enable(directory: str | None = None, role: str = "",
           records: int | None = None,
           sample: int | None = None) -> FlightRecorder:
    """Open (or replace) this process's ring under ``directory`` (default
    ``PSDT_FLIGHT_DIR``) and arm the crash handlers.  Idempotent per
    directory: re-enabling in the same directory keeps the open ring."""
    global _rec, _atexit_armed
    directory = directory or os.environ.get(ENV_DIR, "")
    if not directory:
        raise ValueError("flight.enable needs a directory "
                         f"(or {ENV_DIR} set)")
    with _lock:
        if _rec is not None and _rec.directory == directory:
            if role:
                _rec.set_role(role)
            return _rec
        if _rec is not None:
            _rec.close(clean=True)
        _rec = FlightRecorder(directory, role=role, records=records,
                              sample=sample)
    _arm_crash_handlers(directory)
    if not _atexit_armed:
        atexit.register(_at_exit)
        _atexit_armed = True
    return _rec


def disable() -> None:
    """Close the ring (clean).  Test hygiene; production rings stay open
    for the process lifetime."""
    global _rec
    with _lock:
        if _rec is not None:
            _rec.close(clean=True)
            _rec = None


def suppress_for_tool() -> None:
    """Analysis/status CLIs (pst-trace, pst-status, pst-analyze) call
    this first: when ``PSDT_FLIGHT_DIR`` is still exported from the shell
    that drove the cluster, the import-time auto-enable opened a ring for
    the TOOL process inside the very directory under analysis — which
    would then list the tool itself as a (possibly dead) cluster process.
    Closes the recorder, deletes its ring, and removes its crash sidecar
    while still empty."""
    global _rec
    with _lock:
        rec, _rec = _rec, None
    if rec is None:
        return
    rec.close(clean=True)
    try:
        os.unlink(rec.path)
    except OSError:
        pass
    crash = os.path.join(rec.directory, f"crash-{os.getpid()}.txt")
    try:
        if os.path.getsize(crash) == 0:
            os.unlink(crash)
    except OSError:
        pass


# ------------------------------------------------------------------- decoding
def decode_ring(path: str) -> dict[str, Any]:
    """Decode one on-disk ring (live or from a dead process) into
    ``{path, pid, role, start, clean, capacity, events}`` with events
    oldest-first.  Torn/empty slots are skipped; a seq that does not map
    to its slot (wraparound remnants, torn writes) is invalid."""
    with open(path, "rb") as fh:
        blob = fh.read()
    if len(blob) < HEADER_BYTES:
        raise ValueError(f"{path}: truncated flight ring")
    (magic, record_size, capacity, pid, start_wall, clean, _res,
     role_raw) = struct.unpack_from(_HEADER_FMT, blob, 0)
    if magic != MAGIC:
        raise ValueError(f"{path}: not a flight ring (magic {magic!r})")
    if record_size != RECORD_BYTES:
        raise ValueError(f"{path}: record size {record_size} unsupported")
    events: list[dict] = []
    n_slots = min(capacity, (len(blob) - HEADER_BYTES) // RECORD_BYTES)
    for slot in range(n_slots):
        off = HEADER_BYTES + slot * RECORD_BYTES
        (seq, ts, tid, code, _flags, iteration, worker, a, b,
         note) = struct.unpack_from(_RECORD_FMT, blob, off)
        if seq == 0 or (seq - 1) % capacity != slot:
            continue
        events.append({
            "seq": seq, "ts": ts, "tid": tid, "code": code,
            "event": EVENT_NAMES.get(code, f"ev{code}"),
            "iteration": iteration, "worker": worker, "a": a, "b": b,
            "note": note.rstrip(b"\x00").decode("utf-8", "replace"),
        })
    events.sort(key=lambda e: e["seq"])
    dropped = 0
    if events and events[0]["seq"] > 1:
        # the ring wrapped: seq numbering tells exactly how much history
        # was overwritten
        dropped = events[0]["seq"] - 1
    return {"path": path, "pid": pid,
            "role": role_raw.rstrip(b"\x00").decode("utf-8", "replace"),
            "start": start_wall, "clean": bool(clean),
            "capacity": capacity, "dropped": dropped, "events": events}


# Env wiring: PSDT_FLIGHT_DIR turns the recorder on for the process
# lifetime — the zero-code path for real cluster runs and chaos drives.
if os.environ.get(ENV_DIR, ""):
    enable()
