"""Distributed trace spans with wire-level propagation.

A *trace* is one logical operation across the cluster (e.g. one training
step: worker pull -> compute -> push -> PS apply -> barrier); a *span* is
one timed piece of it in one thread of one process.  Spans carry
(trace_id, span_id, parent_id); the current span rides a thread-local
stack, and crosses process boundaries as a ``b"trace_id/span_id"`` blob in
a high-numbered extension field of the RPC request messages
(rpc/messages.py — reference protoc gencode skips unknown fields, so
reference C++ peers are unaffected; proven by tests/test_wire_interop.py).

Recording is OFF by default: ``span()`` costs one truthiness check when
disabled, so instrumentation can stay unconditionally in hot paths.
Enable with :func:`enable`, ``PSDT_TRACE=1``, or ``PSDT_TRACE_FILE=path``
(the latter also registers an atexit Chrome-trace dump, ``%d`` in the path
expands to the pid — how multi-process cluster runs each drop their slice;
:func:`merge_chrome_traces` stitches the slices into one file that renders
in ``chrome://tracing`` / Perfetto with a shared trace id per step).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Iterator

_BUFFER_MAX = 200_000  # spans kept per process (oldest dropped)

_enabled = False
_buffer: deque = deque(maxlen=_BUFFER_MAX)
_lock = threading.Lock()
_tls = threading.local()


def enable(on: bool = True) -> None:
    """Turn span recording on/off process-wide."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def _new_id() -> str:
    return os.urandom(8).hex()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current() -> tuple[str, str] | None:
    """(trace_id, span_id) of the innermost open span on this thread."""
    stack = _stack()
    return (stack[-1][0], stack[-1][1]) if stack else None


def wire_context() -> bytes:
    """Current span serialized for the RPC extension field (empty bytes
    when tracing is off or no span is open — proto3 elides the field, so
    the wire bytes are identical to an uninstrumented build)."""
    if not _enabled:
        return b""
    ctx = current()
    return f"{ctx[0]}/{ctx[1]}".encode("ascii") if ctx else b""


def parse_context(raw: bytes | str) -> tuple[str, str] | None:
    """Inverse of :func:`wire_context`; None on empty/garbage (a peer that
    does not trace simply leaves the field at its default)."""
    if not raw:
        return None
    try:
        text = raw.decode("ascii") if isinstance(raw, (bytes, bytearray,
                                                       memoryview)) else raw
        trace_id, _, span_id = text.partition("/")
        if len(trace_id) == 16 and len(span_id) == 16:
            return trace_id, span_id
    except (UnicodeDecodeError, ValueError):
        pass
    return None


def _record(name: str, trace_id: str, span_id: str, parent_id: str,
            t0: float, dur: float, args: dict | None) -> None:
    span = {"name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "pid": os.getpid(),
            "tid": threading.get_ident(), "ts": t0, "dur": dur}
    if args:
        span["args"] = args
    with _lock:
        _buffer.append(span)


@contextlib.contextmanager
def span(name: str, **args: Any) -> Iterator[None]:
    """Record one span; nests under the thread's current span (same trace)
    or roots a fresh trace.  No-op when tracing is disabled."""
    if not _enabled:
        yield
        return
    stack = _stack()
    trace_id = stack[-1][0] if stack else _new_id()
    parent_id = stack[-1][1] if stack else ""
    span_id = _new_id()
    stack.append((trace_id, span_id))
    t0 = time.time()
    try:
        yield
    finally:
        dur = time.time() - t0
        stack.pop()
        _record(name, trace_id, span_id, parent_id, t0, dur, args)


@contextlib.contextmanager
def attach(ctx: tuple[str, str] | None) -> Iterator[None]:
    """Make ``ctx`` (a :func:`current` result captured on ANOTHER thread)
    this thread's innermost span, without recording a span of its own.
    The span stack is thread-local, so work handed to a pool (e.g. the
    sharded-PS fan-out) would otherwise root fresh traces instead of
    nesting under the caller's push/pull span.  No-op for None/disabled."""
    if not _enabled or ctx is None:
        yield
        return
    stack = _stack()
    stack.append((ctx[0], ctx[1]))
    try:
        yield
    finally:
        stack.pop()


@contextlib.contextmanager
def server_span(name: str, ctx: bytes | str, **args: Any) -> Iterator[None]:
    """Server-side span adopting a REMOTE parent from the request's wire
    context: the handler's work joins the caller's trace.  Falls back to
    :func:`span` semantics when the context is absent/unparseable."""
    if not _enabled:
        yield
        return
    parsed = parse_context(ctx)
    if parsed is None:
        with span(name, **args):
            yield
        return
    trace_id, parent_id = parsed
    span_id = _new_id()
    stack = _stack()
    stack.append((trace_id, span_id))
    t0 = time.time()
    try:
        yield
    finally:
        dur = time.time() - t0
        stack.pop()
        _record(name, trace_id, span_id, parent_id, t0, dur, args)


class SpanHolder:
    """Deferred-context server span for CLIENT-STREAMING handlers: the
    remote parent arrives on the first request chunk, after the handler
    already started.  Construct at handler entry (stamps t0), call
    :meth:`adopt` as chunks arrive (first parseable context wins — it is
    pushed onto the thread's span stack so spans the handler opens later,
    e.g. ``ps/apply`` after draining a streamed push, join the caller's
    trace), and :meth:`finish` on the way out.  adopt/finish must run on
    the handler's thread (they do: gRPC drains the request iterator inside
    the handler call)."""

    __slots__ = ("name", "args", "_t0", "_span_id", "_trace_id",
                 "_parent_id", "_pushed")

    def __init__(self, name: str, **args: Any):
        self.name = name
        self.args = args
        self._t0 = time.time() if _enabled else 0.0
        self._span_id = _new_id() if _enabled else ""
        self._trace_id: str | None = None
        self._parent_id = ""
        self._pushed = False

    def adopt(self, ctx: bytes | str) -> None:
        if not _enabled or self._pushed:
            return
        parsed = parse_context(ctx)
        if parsed is None:
            return
        self._trace_id, self._parent_id = parsed
        _stack().append((self._trace_id, self._span_id))
        self._pushed = True

    def finish(self) -> None:
        if not _enabled:
            return
        if self._pushed:
            stack = _stack()
            if stack and stack[-1][1] == self._span_id:
                stack.pop()
            self._pushed = False
        _record(self.name, self._trace_id or _new_id(), self._span_id,
                self._parent_id, self._t0, time.time() - self._t0,
                self.args)


# ----------------------------------------------------------------- export
def spans() -> list[dict]:
    """Snapshot of the recorded spans (oldest first)."""
    with _lock:
        return list(_buffer)


def clear() -> None:
    with _lock:
        _buffer.clear()


def chrome_trace_events(recorded: list[dict] | None = None) -> list[dict]:
    """Spans -> Chrome-trace (catapult) complete events: ``ph="X"``,
    microsecond ``ts``/``dur``, pid/tid lanes.  The trace/span ids ride in
    ``args`` so Perfetto's query/filter view can group one distributed
    step across processes by ``trace_id``."""
    events = []
    for s in (spans() if recorded is None else recorded):
        events.append({
            "name": s["name"], "ph": "X", "cat": "psdt",
            "ts": s["ts"] * 1e6, "dur": max(s["dur"], 1e-7) * 1e6,
            "pid": s["pid"], "tid": s["tid"],
            "args": {"trace_id": s["trace_id"], "span_id": s["span_id"],
                     "parent_id": s["parent_id"], **s.get("args", {})},
        })
    return events


def export_chrome_trace(path: str,
                        recorded: list[dict] | None = None) -> str:
    """Write this process's spans as a Chrome-trace JSON file; returns the
    path.  Open in chrome://tracing or https://ui.perfetto.dev."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"traceEvents": chrome_trace_events(recorded),
                   "displayTimeUnit": "ms"}, fh)
    return path


def merge_chrome_traces(paths: list[str], out_path: str) -> str:
    """Concatenate several per-process Chrome-trace files (written by
    :func:`export_chrome_trace` / PSDT_TRACE_FILE) into one.  Events keep
    their pid lanes; spans of one step stay correlated by args.trace_id."""
    events: list[dict] = []
    for path in paths:
        with open(path) as fh:
            doc = json.load(fh)
        events.extend(doc["traceEvents"] if isinstance(doc, dict) else doc)
    events.sort(key=lambda e: e.get("ts", 0.0))
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return out_path


# Env wiring: PSDT_TRACE=1 records; PSDT_TRACE_FILE=path also dumps at
# process exit (the zero-code path for real multi-process cluster runs).
if os.environ.get("PSDT_TRACE", "").lower() in ("1", "true", "yes"):
    enable()
_TRACE_FILE = os.environ.get("PSDT_TRACE_FILE", "")
if _TRACE_FILE:
    enable()
    atexit.register(
        lambda: export_chrome_trace(
            _TRACE_FILE.replace("%d", str(os.getpid()))))

    def _dump_on_sigterm(signum, frame):
        # servers (PS/coordinator) normally die by SIGTERM, which skips
        # atexit — without this their halves of every cross-process trace
        # vanish.  Only claims the signal when nobody else has a handler.
        export_chrome_trace(_TRACE_FILE.replace("%d", str(os.getpid())))
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    try:
        if signal.getsignal(signal.SIGTERM) is signal.SIG_DFL:
            signal.signal(signal.SIGTERM, _dump_on_sigterm)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass
