"""PS scale-out: primary/backup replication, hot failover, live resharding.

The parameter-server tier used to be the run's single point of failure:
every shard held the only copy of its partition and the shard map was
frozen at launch (ROADMAP open item 2).  This subsystem makes the tier
fault-tolerant and elastically resizable:

- ``replicator.py`` — primary-side :class:`Replicator` streams post-apply
  striped state to a backup PS after each barrier close; backup-side
  :class:`ReplicaSink` installs it and tracks ``(iteration,
  params_version)`` so the backup can be promoted at any instant.
- ``failover.py`` — worker-side :class:`ShardMapClient` over the
  coordinator's epoch-numbered shard map; ``ShardedPSClient`` uses it to
  promote a dead shard's backup mid-push/pull and retry the same
  iteration against the replica with zero failed steps.
- ``resharding.py`` — coordinator-orchestrated live split/merge: moving
  stripes are snapshotted at a version fence, copied to their new owner,
  and the shard-map epoch bumps; workers repartition on the next
  ``stale shard map`` rejection.
- ``messages.py`` — the extension RPC messages.  They live HERE, not in
  ``rpc/messages.py``: the wire-compat manifest pins the reference
  contract and must not change; a reference peer answers these methods
  UNIMPLEMENTED and every client downgrades permanently (the PR-2
  fallback discipline).

Knobs: ``PSDT_REPLICATION`` / ``--backup`` (docs/training.md
"replication & failover"); metrics ``ps.replica.*`` / ``ps.reshard.*``
(docs/observability.md).
"""
