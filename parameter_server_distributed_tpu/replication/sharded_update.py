"""Cross-replica sharded arena close (ISSUE 18): partition the close,
all-gather the fresh slabs.

PR 17's free-running pipeline left the barrier close itself replicated
by SHIPPING: the primary runs every fused arena stage (core/arena.py)
over the whole store, then sync replication pushes the full post-apply
state — params plus every optimizer slot — to each backup.  With R
replicas the primary both COMPUTES R times the work it needs to and
SENDS O(R * state) bytes per close while R-1 accelerators sit idle.

``PSDT_SHARDED_UPDATE=1`` turns the replica set into a compute surface
instead (the reducer-sharding shape of arXiv:2004.13336, run over the
replication RPC channel rather than a collective fabric): the primary
and every in-sync backup agree on a deterministic slice assignment over
the PackingTable stripe slabs — replica ``r`` of ``R`` owns
``[size*r//R, size*(r+1)//R)`` of every stripe, epoch-fenced by the
table's ``plan_epoch`` — the primary streams each peer the fold SUMS
for its owned slices (``ShardedApplySlices``), every replica runs the
fused per-stage arena kernels ONLY over its own slices
(device_optimizer.apply_arena_range — elementwise stages, so a
slice-of-apply is bit-identical to the apply-of-slice), and the fresh
param/slot slices all-gather back: peers answer with their slices, the
primary assembles the full slabs and broadcasts each peer the slices it
does NOT own (``InstallSlabSlices``).  Per close the wire then carries
sums out plus params/slots back — ~(2..3)/R of the state per peer —
instead of the full optimizer state per peer, and every accelerator
computes ~1/R of the close.

Exchange dtype (``PSDT_SHARDED_UPDATE_DTYPE``): ``raw`` (default)
moves exact f32 bits everywhere — the sharded close is then
BIT-IDENTICAL to the single-node arena close.  ``bf16``/``int8``
quantize the sums and param legs through the PR-6 codec (EQuARX-style:
each replica's OWN slices stay full precision end to end), with PR-9
error feedback accumulating the sums-leg quantization residual per
(peer, slice) so the lossy leg's error stays bounded instead of
compounding; optimizer slot slices always ride raw (they never
re-enter a lossy path and their bits ARE the next close's state).

Downgrade matrix (the close NEVER fails for sharding reasons):

- one replica / no in-sync peer / replication degraded -> local full
  apply (``ps.apply.sharded_fallback`` + ``shard.update.degrade``);
- any peer failure or refusal mid-exchange (death, zombie refusal,
  version skew) -> the WHOLE sharded close aborts and the local full
  apply runs against the untouched sums and slot slabs — the range
  apply is pure (slot commits are deferred to the point of no return),
  so the retry is bit-exact;
- an install-leg failure for one peer commits everywhere else: that
  peer just misses ``note_shipped`` and heals through the ordinary
  flat state ship;
- UNIMPLEMENTED (an older peer) downgrades that address permanently.

Both ends must run the same ``PSDT_ARENA_ALIGN`` (the packing table is
rebuilt independently per replica from the signature — alignment skew
would shear the slice offsets; the per-slice length checks catch the
gross cases loudly).

Backup caveat: a sharded close advances a backup's params and its OWN
slot slices; slot ranges owned by OTHER replicas go stale on it by
design (they are re-sharded fresh every close).  A promoted backup
therefore runs its first local closes from exact params but
possibly-stale foreign slot ranges — the same staleness window a
mid-flight async ship already leaves, healed by the next flat ship.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import grpc
import numpy as np

from ..analysis.lock_order import checked_lock
from ..core import arena as arena_mod
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc.data_plane import stream_chunk_bytes
from ..rpc.service import RpcClient
from ..tiers.ef import ErrorFeedback
from . import messages as rmsg

log = logging.getLogger("pst.sharded_update")

ENV_SHARDED = "PSDT_SHARDED_UPDATE"
ENV_DTYPE = "PSDT_SHARDED_UPDATE_DTYPE"

EXCHANGE_DTYPES = {"raw": m.WIRE_RAW_F32, "bf16": m.WIRE_BF16,
                   "int8": m.WIRE_INT8}
_WIRE_BYTES = {m.WIRE_RAW_F32: 4.0, m.WIRE_BF16: 2.0, m.WIRE_INT8: 1.0}


def enabled() -> bool:
    """Process-level opt-in; default off (every replication path is
    byte-identical with the flag unset)."""
    return os.environ.get(ENV_SHARDED, "") not in ("", "0")


def exchange_wire_dtype(name: str | None = None) -> int:
    """The exchange encoding for the sums and param legs (slots are
    always raw f32)."""
    key = (name if name is not None
           else os.environ.get(ENV_DTYPE, "raw") or "raw").lower()
    if key not in EXCHANGE_DTYPES:
        raise ValueError(
            f"unknown sharded-update dtype {key!r}; options: "
            f"{sorted(EXCHANGE_DTYPES)}")
    return EXCHANGE_DTYPES[key]


def slice_ranges(size: int, replicas: int) -> list[tuple[int, int]]:
    """Replica ``r``'s owned ``[lo, hi)`` of one stripe slab: contiguous
    near-equal ranges, deterministic on both ends (index 0 is the
    primary).  ``size*r//R`` keeps every element owned exactly once for
    any (size, R), including R > size (empty ranges)."""
    return [(size * r // replicas, size * (r + 1) // replicas)
            for r in range(replicas)]


def sharded_client(address: str) -> RpcClient:
    """A PS-peer client with the replication AND sharded-update
    extension methods bound alongside the reference table."""
    return RpcClient(address, m.PARAMETER_SERVER_SERVICE,
                     {**m.PARAMETER_SERVER_METHODS,
                      **rmsg.REPLICATION_PS_METHODS,
                      **rmsg.SHARDED_UPDATE_PS_METHODS})


# --------------------------------------------------------------- segments
def _segment_elems() -> int:
    """Elements per wire segment: the data-plane stream chunk budget in
    f32 elements (a slice larger than the budget rides as ordered
    ``index`` segments of one logical slice)."""
    budget = stream_chunk_bytes() or (32 << 20)
    return max(1, budget // 4)


def _slice_segments(arr: np.ndarray):
    """(index, segment) pairs for one flat f32 slice."""
    seg = _segment_elems()
    for i, lo in enumerate(range(0, len(arr), seg)):
        yield i, arr[lo:lo + seg]


class _DecodedConcat:
    """``tensor``-shaped shim for ErrorFeedback.stage: ``to_array``
    materializes the value the RECEIVER decodes — the concatenation of
    the slice's per-segment wire decodes — so the staged residual is
    exactly (sent - received)."""

    __slots__ = ("_tensors",)

    def __init__(self, tensors: list):
        self._tensors = tensors

    def to_array(self) -> np.ndarray:
        if len(self._tensors) == 1:
            return self._tensors[0].to_array()
        return np.concatenate([t.to_array() for t in self._tensors])


def _assemble_parts(parts: dict) -> np.ndarray:
    """Ordered segment decode + concat for one received slice."""
    tensors = [parts[i] for i in sorted(parts)]
    if len(tensors) == 1:
        return np.asarray(tensors[0].to_array(), np.float32).reshape(-1)
    return np.concatenate([
        np.asarray(t.to_array(), np.float32).reshape(-1)
        for t in tensors])


def _full_cover(ranges, size: int) -> bool:
    """True when sorted ``[lo, hi)`` ranges tile ``[0, size)``."""
    spans = sorted(r for r in ranges if r[1] > r[0])
    if not spans:
        return size == 0
    if spans[0][0] != 0 or spans[-1][1] != size:
        return False
    return all(spans[i][1] == spans[i + 1][0]
               for i in range(len(spans) - 1))


class _PeerRefused(RuntimeError):
    """The peer answered an in-band refusal (``error`` chunk)."""


# ==========================================================================
# primary side
# ==========================================================================

class ShardedUpdater:
    """Primary-side driver, installed via ``core.set_sharded_updater``.
    ``try_close`` runs from the barrier closer under ``_apply_lock`` —
    blocking RPC is legal there (the sync-replication precedent) and
    applies stay serialized.  It NEVER raises and returns None to
    decline, leaving the sums and slot slabs untouched so the caller's
    local full apply is bit-identical to an unsharded close."""

    def __init__(self, core, replicator, *, dtype: str | None = None,
                 timeout_s: float = 60.0):
        self._core = core
        self._replicator = replicator
        self._wire_dtype = exchange_wire_dtype(dtype)
        self._timeout_s = float(timeout_s)
        # rank 47 (analysis/lock_order.py, BLOCKING_ALLOWED): fences the
        # lazily-built per-address clients and the downgrade set against
        # stop(); the exchange itself runs on the closer thread plus
        # short-lived per-peer threads that touch only local state
        self._lock = checked_lock("ShardedUpdater._lock")
        self._clients: dict[str, RpcClient] = {}
        self._downgraded: set[str] = set()
        # PR-9 error feedback, sums leg only (the one lossy leg that
        # enters the training dynamics): one instance per peer address,
        # keys "stripe:lo:hi" — residuals for ranges orphaned by a
        # replica-count change linger unread, bounded by the range
        # vocabulary
        self._ef: dict[str, ErrorFeedback] = {}
        self._stopped = False
        self._obs_sharded = obs_stats.counter("ps.apply.sharded")
        self._obs_fallback = obs_stats.counter("ps.apply.sharded_fallback")
        self._obs_bytes = obs_stats.counter("ps.replica.sharded_bytes")

    # ------------------------------------------------------------ lifecycle
    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            clients, self._clients = dict(self._clients), {}
        for client in clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                log.exception("sharded-update client close failed")

    def _client(self, address: str) -> RpcClient | None:
        with self._lock:
            if self._stopped:
                return None
            client = self._clients.get(address)
            if client is None:
                client = self._clients[address] = sharded_client(address)
            return client

    def _decline(self, reason: str, iteration: int) -> None:
        self._obs_fallback.add()
        flight.record("shard.update.degrade", iteration=iteration,
                      note=reason[:48])

    # ------------------------------------------------------------- close
    def try_close(self, prev, table, param_slabs, sums, iteration: int):
        """Attempt one sharded close; ``(new_slabs, host_slabs)`` on
        success, None to decline.  Caller holds ``_apply_lock``; sums
        are already contributor-means; ``opt.tick()`` has run."""
        try:
            return self._try_close(prev, table, param_slabs, sums,
                                   iteration)
        except Exception as exc:  # noqa: BLE001 — the close must never
            # fail for sharding reasons; the local apply is always right
            log.exception("sharded close aborted; applying locally")
            self._decline(f"{type(exc).__name__}: {exc}", iteration)
            return None

    def _try_close(self, prev, table, param_slabs, sums, iteration: int):
        import jax.numpy as jnp

        core = self._core
        opt = core._optimizer
        if not hasattr(opt, "apply_arena_range"):
            self._decline("optimizer lacks range apply", iteration)
            return None
        repl = self._replicator
        base_version = core.params_version
        peers = [a for a in repl.live_addresses()
                 if a not in self._downgraded
                 and repl.shipped_version(a) == base_version]
        if not peers:
            self._decline("no in-sync peer", iteration)
            return None
        stripes = sorted(param_slabs)
        if any(s not in sums.slabs for s in stripes):
            self._decline("sums missing a stripe", iteration)
            return None
        R = 1 + len(peers)
        plan = {s: slice_ranges(int(table.stripe_sizes[s]), R)
                for s in stripes}
        opt.ensure_arena_slots(table)
        new_version = base_version + 1
        epoch = core.epoch
        step = int(getattr(opt, "step", 0))
        t0 = time.perf_counter()

        # ---- peer exchange threads: stream sums out, gather slices back
        results: dict[str, dict] = {}
        errors: dict[str, BaseException] = {}

        def exchange(address: str, rindex: int) -> None:
            try:
                results[address] = self._exchange_with_peer(
                    address, rindex, table, plan, sums, iteration,
                    base_version, new_version, epoch, step, R)
            except BaseException as exc:  # noqa: BLE001 — joined below
                errors[address] = exc

        threads = [threading.Thread(
            target=exchange, args=(address, r), daemon=True,
            name=f"ps-shard-xchg-{r}")
            for r, address in enumerate(peers, start=1)]
        for t in threads:
            t.start()

        # ---- own slices on the closer thread, overlapping the RPCs.
        # apply_arena_range is PURE (slices in, slices out; slot slabs
        # untouched), so an abort below leaves the local-apply world
        # unmodified.
        own_params: dict[int, object] = {}
        own_slots: dict[int, dict] = {}
        for s in stripes:
            lo, hi = plan[s][0]
            if lo == hi:
                continue
            new_p, slots = opt.apply_arena_range(
                table, s, param_slabs[s][lo:hi], sums.slabs[s][lo:hi],
                lo, hi)
            own_params[s] = new_p
            own_slots[s] = slots
        for t in threads:
            t.join(timeout=self._timeout_s + 5.0)
        alive = [t for t in threads if t.is_alive()]
        if alive or errors or len(results) != len(peers):
            for address, exc in errors.items():
                self._note_peer_error(address, exc)
            if alive:
                self._decline("exchange timeout", iteration)
            elif errors:
                self._decline("peer exchange failed", iteration)
            else:
                self._decline("exchange incomplete", iteration)
            return None

        # ---- point of no return: assemble full slabs, commit slots
        host_slabs: dict[int, np.ndarray] = {}
        new_slabs: dict[int, object] = {}
        slot_kinds = tuple(opt.arena_slot_kinds())
        for s in stripes:
            size = int(table.stripe_sizes[s])
            host = np.empty(size, np.float32)
            lo, hi = plan[s][0]
            if lo < hi:
                host[lo:hi] = np.asarray(own_params[s])
            for r, address in enumerate(peers, start=1):
                lo, hi = plan[s][r]
                if lo < hi:
                    host[lo:hi] = results[address]["params"][(s, lo, hi)]
            pieces: dict[str, list] = {k: [] for k in slot_kinds}
            for kind, arr in own_slots.get(s, {}).items():
                plo, phi = plan[s][0]
                pieces[kind].append((plo, phi, arr))
            for r, address in enumerate(peers, start=1):
                lo, hi = plan[s][r]
                for kind, arr in results[address]["slots"].get(
                        (s, lo, hi), {}).items():
                    pieces[kind].append((lo, hi, arr))
            opt.commit_arena_ranges(
                table, s, {k: v for k, v in pieces.items() if v})
            host_slabs[s] = host
            new_slabs[s] = jnp.asarray(host)

        # ---- install leg: each peer gets every slice it does NOT own;
        # a failure here is per-peer (the close is already committed) —
        # the peer misses note_shipped and heals via the flat ship
        shipped = []
        for r, address in enumerate(peers, start=1):
            if self._install_to_peer(address, r, table, plan, host_slabs,
                                     stripes, iteration, base_version,
                                     new_version, epoch, step, R):
                shipped.append(address)
            else:
                self._obs_fallback.add()
                flight.record("shard.update.degrade", iteration=iteration,
                              note="install leg failed")
        repl.note_shipped(new_version, shipped)
        for address in shipped:
            ef = self._ef.get(address)
            if ef is not None:
                ef.commit()

        wire_bytes = self._exchange_bytes(table, plan, stripes,
                                          slot_kinds, peers, shipped)
        self._obs_sharded.add()
        self._obs_bytes.add(wire_bytes)
        flight.record("apply.sharded", iteration=iteration, a=R,
                      b=wire_bytes,
                      note=f"{int(1e6 * (time.perf_counter() - t0))}us")
        return new_slabs, host_slabs

    # --------------------------------------------------------- peer legs
    def _exchange_with_peer(self, address: str, rindex: int, table, plan,
                            sums, iteration: int, base_version: int,
                            new_version: int, epoch: int, step: int,
                            replicas: int) -> dict:
        client = self._client(address)
        if client is None:
            raise RuntimeError("updater stopped")
        lossy = self._wire_dtype != m.WIRE_RAW_F32
        ef = None
        if lossy:
            ef = self._ef.get(address)
            if ef is None:
                ef = self._ef[address] = ErrorFeedback()
            ef.begin()

        def header(**kw):
            return rmsg.ShardedSliceChunk(
                plan_epoch=table.epoch, epoch=epoch, iteration=iteration,
                base_version=base_version, new_version=new_version,
                step=step, replicas=replicas, stripes=table.stripes, **kw)

        def request_chunks():
            for s in sorted(plan):
                lo, hi = plan[s][rindex]
                if lo == hi:
                    continue
                sums_host = np.asarray(sums.slabs[s][lo:hi])
                if lossy and ef.on:
                    key = f"{s}:{lo}:{hi}"
                    adjusted = ef.adjust(key, sums_host)
                    tensors = [
                        m.Tensor.from_array(f"{key}#{i}", seg,
                                            wire_dtype=self._wire_dtype)
                        for i, seg in _slice_segments(adjusted)]
                    ef.stage(key, adjusted, _DecodedConcat(tensors))
                    segments = list(enumerate(tensors))
                else:
                    segments = [
                        (i, m.Tensor.from_array(f"{s}:{lo}:{hi}#{i}", seg,
                                                wire_dtype=self._wire_dtype))
                        for i, seg in _slice_segments(sums_host)]
                for i, tensor in segments:
                    yield header(kind=rmsg.SLICE_SUMS, stripe=s, lo=lo,
                                 hi=hi, index=i, payload=tensor)
            # trailer: marks end of the sums leg (and covers the
            # degenerate no-owned-range assignment)
            yield header(kind=rmsg.SLICE_SUMS, last=True)

        try:
            responses = client.call("ShardedApplySlices", request_chunks(),
                                    timeout=self._timeout_s)
            params: dict[tuple, dict] = {}
            slots: dict[tuple, dict] = {}
            for resp in responses:
                if resp.error:
                    raise _PeerRefused(f"{address}: {resp.error}")
                key = (int(resp.stripe), int(resp.lo), int(resp.hi))
                if resp.payload is not None and resp.hi > resp.lo:
                    if resp.kind == rmsg.SLICE_PARAMS:
                        params.setdefault(key, {})[int(resp.index)] = \
                            resp.payload
                    elif resp.kind == rmsg.SLICE_SLOT:
                        slots.setdefault(key, {}).setdefault(
                            str(resp.slot), {})[int(resp.index)] = \
                            resp.payload
                if resp.last:
                    break
        except grpc.RpcError as exc:
            code = getattr(exc, "code", None)
            if callable(code) and code() == grpc.StatusCode.UNIMPLEMENTED:
                raise _PeerRefused("UNIMPLEMENTED") from exc
            raise
        out_params = {}
        for key, parts in params.items():
            arr = _assemble_parts(parts)
            if len(arr) != key[2] - key[1]:
                raise _PeerRefused(
                    f"{address}: param slice {key} length {len(arr)}")
            out_params[key] = arr
        out_slots: dict[tuple, dict] = {}
        for key, by_kind in slots.items():
            out_slots[key] = {}
            for kind, parts in by_kind.items():
                arr = _assemble_parts(parts)
                if len(arr) != key[2] - key[1]:
                    raise _PeerRefused(
                        f"{address}: slot slice {key}/{kind} length "
                        f"{len(arr)}")
                out_slots[key][kind] = arr
        # every owned non-empty range must have come back
        for s in sorted(plan):
            lo, hi = plan[s][rindex]
            if lo < hi and (s, lo, hi) not in out_params:
                raise _PeerRefused(
                    f"{address}: missing param slice ({s}, {lo}, {hi})")
        return {"params": out_params, "slots": out_slots}

    def _install_to_peer(self, address: str, rindex: int, table, plan,
                         host_slabs, stripes, iteration: int,
                         base_version: int, new_version: int, epoch: int,
                         step: int, replicas: int) -> bool:
        client = self._client(address)
        if client is None:
            return False

        def header(**kw):
            return rmsg.ShardedSliceChunk(
                plan_epoch=table.epoch, epoch=epoch, iteration=iteration,
                base_version=base_version, new_version=new_version,
                step=step, replicas=replicas, stripes=table.stripes, **kw)

        def install_chunks():
            for s in stripes:
                for r in range(replicas):
                    if r == rindex:
                        continue  # the peer's own slices: already exact
                    lo, hi = plan[s][r]
                    if lo == hi:
                        continue
                    # param leg: quantized without error feedback — the
                    # slices never re-enter an update (each replica
                    # applies only its own full-precision ranges)
                    for i, seg in _slice_segments(host_slabs[s][lo:hi]):
                        yield header(kind=rmsg.SLICE_PARAMS, stripe=s,
                                     lo=lo, hi=hi, index=i,
                                     payload=m.Tensor.from_array(
                                         f"{s}:{lo}:{hi}#{i}", seg,
                                         wire_dtype=self._wire_dtype))
            yield header(kind=rmsg.SLICE_PARAMS, last=True)

        try:
            ack = client.call("InstallSlabSlices", install_chunks(),
                              timeout=self._timeout_s)
        except grpc.RpcError as exc:
            code = getattr(exc, "code", None)
            if callable(code) and code() == grpc.StatusCode.UNIMPLEMENTED:
                self._note_peer_error(address, _PeerRefused("UNIMPLEMENTED"))
                return False
            log.exception("sharded install to %s failed", address)
            return False
        except Exception:  # noqa: BLE001 — per-peer containment
            log.exception("sharded install to %s failed", address)
            return False
        if not ack.success:
            log.warning("backup %s refused sharded install: %s", address,
                        ack.message)
            return False
        return True

    def _note_peer_error(self, address: str, exc: BaseException) -> None:
        if isinstance(exc, _PeerRefused) and "UNIMPLEMENTED" in str(exc):
            log.warning("peer %s does not implement the sharded update; "
                        "downgrading that address permanently", address)
            with self._lock:
                self._downgraded.add(address)
        else:
            log.warning("sharded exchange with %s failed: %s", address, exc)

    def _exchange_bytes(self, table, plan, stripes, slot_kinds, peers,
                        shipped) -> int:
        """Approximate exchange payload bytes for the rollup counter
        (true wire bytes live in the rpc.client.* counters): sums out +
        params back at the exchange dtype, slots back raw, install legs
        at the exchange dtype."""
        per = _WIRE_BYTES[self._wire_dtype]
        total = 0.0
        R = 1 + len(peers)
        for s in stripes:
            for r in range(1, R):
                lo, hi = plan[s][r]
                n = hi - lo
                total += n * per            # sums out
                total += n * per            # params back
                total += n * 4.0 * len(slot_kinds)  # slots back, raw
        for address in shipped:
            for s in stripes:
                size = int(table.stripe_sizes[s])
                r = peers.index(address) + 1
                lo, hi = plan[s][r]
                total += (size - (hi - lo)) * per   # install leg
        return int(total)


# ==========================================================================
# backup side
# ==========================================================================

class ShardedUpdateSink:
    """Backup-side handlers for the two sharded-update RPCs, bound on
    the PS service next to :class:`replication.replicator.ReplicaSink`
    (whose high-water bookkeeping this sink advances — rank 15 so the
    sink lock may take the replica sink's rank-16 lock inside)."""

    def __init__(self, core, replica_sink):
        self._core = core
        self._replica_sink = replica_sink
        # rank 15, BLOCKING_ALLOWED: held across the range applies
        # (device dispatch) and the install (core locks 20.. nest
        # inside); serializes sharded closes against each other
        self._lock = checked_lock("ShardedUpdateSink._lock")
        self._table = None
        # FULL host param slabs at `_slabs_version` (the primary's
        # version this replica provably holds) — rebuilt from the live
        # store after any flat install, advanced in place by each
        # sharded install
        self._host_slabs: dict[int, np.ndarray] | None = None
        self._slabs_version = -2
        self._pending: dict | None = None
        # satellite: 1 while this backup replicates by flat SHIPPING
        # (its accelerator idle through every close), 0 once it computes
        # sharded close slices
        self._obs_idle = obs_stats.gauge("ps.replica.idle_accelerator")
        self._obs_applies = obs_stats.counter("ps.replica.sharded_applies")

    # ------------------------------------------------------------- helpers
    def _refuse(self, reason: str):
        flight.record("shard.update.degrade", note=reason[:48])
        return rmsg.ShardedSliceChunk(error=reason, last=True)

    def _ensure_table(self, params, stripes: int, plan_epoch: int):
        """The slice-assignment table, built locally from the replica's
        own (bit-identical) store — deterministic given the signature,
        the stripe count, and PSDT_ARENA_ALIGN, which both ends must
        share."""
        table = self._table
        sig = arena_mod.store_signature(params)
        if (table is None or table.stripes != stripes
                or table.epoch != plan_epoch or table.signature != sig):
            table = arena_mod.PackingTable(params, stripes, plan_epoch)
            self._table = table
        return table

    def _ensure_base_slabs(self, params, table, base_version: int) -> bool:
        """Host param slabs for the base store; False when they cannot
        be built (empty store)."""
        if self._slabs_version == base_version \
                and self._host_slabs is not None:
            return True
        if (isinstance(params, arena_mod.ArenaStore)
                and params.layout.stripes == table.stripes
                and params.layout.signature == table.signature):
            # a previous sharded install published an ArenaStore whose
            # slabs ARE the full host slabs under the same layout
            self._host_slabs = {s: np.asarray(h, np.float32)
                                for s, h in params.slabs.items()}
            self._slabs_version = base_version
            return True
        slabs: dict[int, np.ndarray] = {}
        for stripe in range(table.stripes):
            size = int(table.stripe_sizes[stripe])
            if not size:
                continue
            host = np.zeros(size, np.float32)
            for name in table.stripe_names[stripe]:
                e = table.entries[name]
                host[e.offset:e.offset + e.length] = np.asarray(
                    np.asarray(params[name]), np.float32).reshape(-1)
            slabs[stripe] = host
        if not slabs:
            return False
        self._host_slabs = slabs
        self._slabs_version = base_version
        return True

    def _store_params(self):
        with self._core._params_lock:
            return self._core._params

    # ---------------------------------------------------------- apply leg
    def apply_slices(self, chunks, context=None):
        """``ShardedApplySlices`` handler (stream_stream): consume the
        sums leg, run the fused range applies over the owned slices,
        stream the fresh param/slot slices back, and hold the results
        pending the install leg."""
        header = None
        parts: dict[tuple, dict] = {}
        for c in chunks:
            if header is None:
                header = c
            if (c.kind == rmsg.SLICE_SUMS and c.payload is not None
                    and int(c.hi) > int(c.lo)):
                parts.setdefault(
                    (int(c.stripe), int(c.lo), int(c.hi)),
                    {})[int(c.index)] = c.payload
        if header is None:
            yield self._refuse("empty sharded stream")
            return
        core = self._core
        opt = core._optimizer
        if not hasattr(opt, "apply_arena_range"):
            yield self._refuse("optimizer lacks range apply")
            return
        base_version = int(header.base_version)
        iteration = int(header.iteration)
        with self._lock:
            rs = self._replica_sink
            with rs._lock:
                primary_version = rs.primary_version
                primary_iteration = rs.primary_iteration
                installed_any = rs._installed_any
            if installed_any and core.current_iteration > primary_iteration:
                # promoted: local aggregation moved past the replication
                # mark — the sender is a zombie ex-primary
                yield self._refuse("replica promoted; sharded apply "
                                   "refused")
                return
            if primary_version != base_version:
                yield self._refuse(
                    f"base version skew: hold v{primary_version}, "
                    f"primary closes from v{base_version}")
                return
            params = self._store_params()
            if not params:
                yield self._refuse("replica store empty")
                return
            try:
                table = self._ensure_table(params, int(header.stripes),
                                           int(header.plan_epoch))
            except Exception as exc:  # noqa: BLE001 — refuse, not raise
                yield self._refuse(f"table build failed: {exc}")
                return
            if not self._ensure_base_slabs(params, table, base_version):
                yield self._refuse("no packable base slabs")
                return
            for (stripe, lo, hi) in parts:
                if stripe >= table.stripes \
                        or hi > int(table.stripe_sizes[stripe]):
                    yield self._refuse(
                        f"slice ({stripe}, {lo}, {hi}) outside the "
                        f"local layout (PSDT_ARENA_ALIGN skew?)")
                    return
            wire_dtype = m.WIRE_RAW_F32
            for seg in parts.values():
                t = next(iter(seg.values()))
                wire_dtype = int(getattr(t, "packed_dtype", 0)) \
                    or m.WIRE_F32
                break
            try:
                responses = self._apply_owned(
                    opt, table, header, parts, wire_dtype, iteration,
                    base_version)
            except Exception as exc:  # noqa: BLE001 — refuse, not raise
                log.exception("sharded range apply failed")
                yield self._refuse(f"range apply failed: {exc}")
                return
            self._obs_idle.set(0)
            self._obs_applies.add()
        for resp in responses:
            yield resp

    def _apply_owned(self, opt, table, header, parts, wire_dtype: int,
                     iteration: int, base_version: int) -> list:
        import jax.numpy as jnp

        opt.ensure_arena_slots(table)
        # mirror the primary's post-tick logical step (Adam/AdamW bias
        # corrections must agree bit-for-bit)
        opt.step = int(header.step)
        own_params: dict[tuple, np.ndarray] = {}
        own_slots: dict[tuple, dict] = {}
        responses: list = []

        def reply(**kw):
            return rmsg.ShardedSliceChunk(
                plan_epoch=table.epoch, epoch=int(header.epoch),
                iteration=iteration, base_version=base_version,
                new_version=int(header.new_version),
                stripes=table.stripes, replicas=int(header.replicas),
                **kw)

        for (stripe, lo, hi) in sorted(parts):
            g_host = _assemble_parts(parts[(stripe, lo, hi)])
            if len(g_host) != hi - lo:
                raise ValueError(
                    f"sums slice ({stripe}, {lo}, {hi}) decoded to "
                    f"{len(g_host)} elements")
            p = jnp.asarray(self._host_slabs[stripe][lo:hi])
            g = jnp.asarray(g_host)
            new_p, slots = opt.apply_arena_range(table, stripe, p, g,
                                                 lo, hi)
            host_p = np.asarray(new_p, np.float32).reshape(-1)
            own_params[(stripe, lo, hi)] = host_p
            own_slots[(stripe, lo, hi)] = {
                kind: np.asarray(arr, np.float32).reshape(-1)
                for kind, arr in slots.items()}
            for i, seg in _slice_segments(host_p):
                responses.append(reply(
                    kind=rmsg.SLICE_PARAMS, stripe=stripe, lo=lo, hi=hi,
                    index=i, payload=m.Tensor.from_array(
                        f"{stripe}:{lo}:{hi}#{i}", seg,
                        wire_dtype=wire_dtype)))
            for kind, host_s in own_slots[(stripe, lo, hi)].items():
                for i, seg in _slice_segments(host_s):
                    responses.append(reply(
                        kind=rmsg.SLICE_SLOT, stripe=stripe, slot=kind,
                        lo=lo, hi=hi, index=i,
                        payload=m.Tensor.from_array(
                            f"{stripe}:{lo}:{hi}/{kind}#{i}", seg,
                            wire_dtype=m.WIRE_RAW_F32)))
        responses.append(reply(kind=rmsg.SLICE_PARAMS, last=True))
        # latest-only pending: a newer exchange supersedes one whose
        # install leg never arrived (that close healed via flat ship)
        self._pending = {
            "iteration": iteration,
            "new_version": int(header.new_version),
            "base_version": base_version,
            "epoch": int(header.epoch),
            "table": table,
            "params": own_params,
            "slots": own_slots,
        }
        return responses

    # -------------------------------------------------------- install leg
    def install_slices(self, chunks, context=None) -> rmsg.ShardedSliceAck:
        """``InstallSlabSlices`` handler (stream_unary): assemble the
        full fresh slabs from this replica's own pending slices plus the
        gathered ones, swap them in as the store's next version, and
        commit the OWN slot ranges."""
        header = None
        parts: dict[tuple, dict] = {}
        for c in chunks:
            if header is None:
                header = c
            if (c.kind == rmsg.SLICE_PARAMS and c.payload is not None
                    and int(c.hi) > int(c.lo)):
                parts.setdefault(
                    (int(c.stripe), int(c.lo), int(c.hi)),
                    {})[int(c.index)] = c.payload
        if header is None:
            return rmsg.ShardedSliceAck(success=False,
                                        message="empty install stream")
        with self._lock:
            pending = self._pending
            if (pending is None
                    or pending["new_version"] != int(header.new_version)
                    or pending["iteration"] != int(header.iteration)):
                return rmsg.ShardedSliceAck(
                    success=False,
                    message="no matching pending sharded apply")
            table = pending["table"]
            received: dict[tuple, np.ndarray] = {}
            for key, seg in parts.items():
                arr = _assemble_parts(seg)
                if len(arr) != key[2] - key[1]:
                    return rmsg.ShardedSliceAck(
                        success=False,
                        message=f"param slice {key} decoded to "
                                f"{len(arr)} elements")
                received[key] = arr
            # coverage: own + received ranges must tile every stripe
            by_stripe: dict[int, list] = {}
            for (stripe, lo, hi) in list(pending["params"]) \
                    + list(received):
                by_stripe.setdefault(stripe, []).append((lo, hi))
            new_host: dict[int, np.ndarray] = {}
            for stripe, base in self._host_slabs.items():
                size = int(table.stripe_sizes[stripe])
                if not _full_cover(by_stripe.get(stripe, []), size):
                    return rmsg.ShardedSliceAck(
                        success=False,
                        message=f"stripe {stripe} slice coverage "
                                f"incomplete")
                new_host[stripe] = np.empty(size, np.float32)
            for (stripe, lo, hi), arr in pending["params"].items():
                new_host[stripe][lo:hi] = arr
            for (stripe, lo, hi), arr in received.items():
                new_host[stripe][lo:hi] = arr
            per_stripe = {s: table.views(s, h)
                          for s, h in new_host.items()}
            values = {}
            for stripe in range(table.stripes):
                for name in table.stripe_names[stripe]:
                    values[name] = per_stripe[stripe][name]
            store = arena_mod.ArenaStore(values, table, new_host)
            # the replica-sink lock is held ACROSS the core install (the
            # push_delta discipline — rank 16 before core ranks 20..40):
            # a concurrent flat ship must never observe the advanced
            # core.current_iteration before primary_iteration catches
            # up, or its zombie check misreads the window as a promotion
            rs = self._replica_sink
            with rs._lock:
                version = self._core.install_sharded_close(
                    store, epoch=pending["epoch"],
                    iteration=pending["iteration"])
                # own slot ranges only: foreign slot ranges are
                # re-sharded fresh every close and go stale here by
                # design (the promoted-backup caveat in the module
                # docstring)
                opt = self._core._optimizer
                by_stripe_slots: dict[int, dict] = {}
                for (stripe, lo, hi), by_kind in pending["slots"].items():
                    for kind, arr in by_kind.items():
                        by_stripe_slots.setdefault(
                            stripe, {}).setdefault(
                            kind, []).append((lo, hi, arr))
                for stripe, pieces in by_stripe_slots.items():
                    opt.commit_arena_ranges(table, stripe, pieces)
                self._host_slabs = new_host
                self._slabs_version = pending["new_version"]
                self._pending = None
                rs.primary_version = pending["new_version"]
                # monotone: a flat ship may already have recorded the
                # primary's max-SEEN worker iteration (which runs ahead
                # of its closes under racing pushers) — regressing the
                # mark to this close's iteration would misread the gap
                # as a promotion and zombie-refuse the next exchange
                rs.primary_iteration = max(rs.primary_iteration,
                                           pending["iteration"])
                rs._installed_any = True
        return rmsg.ShardedSliceAck(success=True, message="installed",
                                    params_version=version)
