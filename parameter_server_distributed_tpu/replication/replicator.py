"""Primary/backup replication of the PS tensor store (ISSUE 7).

**Primary side** — :class:`Replicator`: after each streaming barrier
close the core's replication hook fires (core/ps_core.py
``set_replication_hook``) and the post-apply state ships to the backup
over the ``PushReplicaDelta`` extension RPC as striped chunks of PR-6
codec frames (``rpc.messages.Tensor`` payloads — lossless WIRE_RAW_F32,
so the replica store is bit-identical to the primary's).  Two modes
(``PSDT_REPLICATION`` / ``ParameterServerConfig.replication``):

A note on "delta": a post-apply delta on a parameter server IS the full
striped state — every barrier's optimizer apply touches every tensor, so
"changed since the last ship" equals the whole store in steady state.
What bounds the cost is COALESCING, not diffing: consecutive versions
collapse to one latest-snapshot ship when the backup lags (async mode),
and the stripe ordering keeps chunks aligned with the PS's unit of
parallelism.

- ``async`` (default): the hook just wakes the ship thread — barrier
  close pays a condition-variable notify; consecutive versions coalesce
  (the ship always sends the LATEST snapshot), so a slow backup lags but
  never stalls training.  ``ps.replica.lag_bytes`` surfaces the gap.
- ``sync``: the hook ships inline BEFORE the barrier publishes (it runs
  under ``_apply_lock``, which is BLOCKING_ALLOWED for exactly this):
  once a worker sees an iteration complete, the backup provably holds
  it — a primary death can never lose an applied step, at the cost of
  one replication round per barrier close.

Downgrade discipline (PR-2/PR-6): a backup that answers UNIMPLEMENTED
(reference PS) or rejects the delta downgrades replication PERMANENTLY
for this process; transient transport errors retry on the reconcile
cadence and degrade permanently after ``_MAX_TRANSIENT_FAILURES``
consecutive failures — the primary's training hot path must never wedge
on a dead backup.

**Backup side** — :class:`ReplicaSink`: installs each delta atomically
(core ``install_tensors``), tracks the primary's ``(iteration,
params_version)`` high-water mark, and — after a promotion — refuses
regressions from a zombie primary (the replica's own aggregation having
advanced past the sink's mark is the promotion signal).

Optimizer slot state rides the same stream as tensors under the
``__opt__/`` name prefix (momentum/Adam moments survive a failover);
scalars flatten under ``__opt__/__scalar__/``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Mapping

import grpc
import numpy as np

from ..analysis.lock_order import checked_lock
from ..core.stripes import stripe_of
from ..core.tensor import TensorStore, from_wire, store_nbytes, to_wire
from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc.data_plane import split_tensors, stream_chunk_bytes
from ..rpc.service import RpcClient
from . import messages as rmsg

log = logging.getLogger("pst.replication")

OPT_PREFIX = "__opt__/"
_OPT_SCALAR = "__scalar__"

# consecutive transient ship failures before replication degrades
# permanently (an UNIMPLEMENTED/refused answer degrades immediately)
_MAX_TRANSIENT_FAILURES = 5


def flatten_optimizer_state(state: dict) -> TensorStore:
    """Optimizer state dict -> flat named arrays for the wire: slot dicts
    become ``__opt__/<slot>/<name>``, scalars ``__opt__/__scalar__/<k>``
    (same flattening as the checkpoint sidecar, checkpoint/manager.py)."""
    flat: TensorStore = {}
    for slot, value in state.items():
        if isinstance(value, dict):
            for name, arr in value.items():
                flat[f"{OPT_PREFIX}{slot}/{name}"] = np.asarray(arr)
        else:
            flat[f"{OPT_PREFIX}{_OPT_SCALAR}/{slot}"] = np.asarray(value)
    return flat


def split_replica_store(store: Mapping[str, np.ndarray]
                        ) -> tuple[TensorStore, dict | None]:
    """(parameter tensors, optimizer state dict | None) — the inverse of
    :func:`flatten_optimizer_state` applied to a decoded delta stream."""
    params: TensorStore = {}
    opt: dict = {}
    for name, arr in store.items():
        if not name.startswith(OPT_PREFIX):
            params[name] = arr
            continue
        slot, _, leaf = name[len(OPT_PREFIX):].partition("/")
        if slot == _OPT_SCALAR:
            value = np.asarray(arr)
            opt[leaf] = value.item() if value.ndim == 0 else value
        else:
            opt.setdefault(slot, {})[leaf] = arr
    return params, (opt or None)


def replication_client(address: str) -> RpcClient:
    """An RpcClient for a PS peer with the replication extension methods
    bound alongside the reference method table."""
    return RpcClient(address, m.PARAMETER_SERVER_SERVICE,
                     {**m.PARAMETER_SERVER_METHODS,
                      **rmsg.REPLICATION_PS_METHODS})


def delta_chunks(epoch: int, iteration: int, version: int, kind: int,
                 store: Mapping[str, np.ndarray], stripes: int = 1,
                 chunk_bytes: int | None = None):
    """The delta stream for one ship: tensors ordered by owning stripe
    (core/stripes.py — the stripe partition is the replication unit, so a
    chunk never interleaves stripes), greedily grouped to the stream
    chunk budget, each group one :class:`rmsg.ReplicaDeltaChunk` of
    lossless WIRE_RAW_F32 codec frames.  An empty store still yields one
    (empty) header chunk."""
    budget = chunk_bytes if chunk_bytes is not None \
        else (stream_chunk_bytes() or (32 << 20))
    ordered = sorted(store, key=lambda n: (stripe_of(n, max(1, stripes)), n))
    tensors = to_wire({n: store[n] for n in ordered},
                      wire_dtype=m.WIRE_RAW_F32)
    sent = False
    for group in split_tensors(tensors, budget):
        sent = True
        yield rmsg.ReplicaDeltaChunk(epoch=epoch, iteration=iteration,
                                     params_version=version, kind=kind,
                                     tensors=group)
    if not sent:
        yield rmsg.ReplicaDeltaChunk(epoch=epoch, iteration=iteration,
                                     params_version=version, kind=kind,
                                     tensors=[])


def state_chunks(epoch: int, iteration: int, version: int,
                 store: Mapping[str, np.ndarray],
                 chunk_bytes: int | None = None):
    """Server-streamed :class:`rmsg.ReplicaStateChunk` frames for a state
    fetch / stripe retirement — always at least one chunk (the header
    rides every chunk; the final one carries ``last=True``)."""
    budget = chunk_bytes if chunk_bytes is not None \
        else (stream_chunk_bytes() or (32 << 20))
    tensors = to_wire(store, wire_dtype=m.WIRE_RAW_F32)
    groups = list(split_tensors(tensors, budget)) or [[]]
    for i, group in enumerate(groups):
        yield rmsg.ReplicaStateChunk(epoch=epoch, iteration=iteration,
                                     params_version=version, tensors=group,
                                     last=(i == len(groups) - 1))


class _Peer:
    """Per-backup ship state (one Replicator may fan out to several
    backups — ``backup_address`` is a comma-separated list)."""

    __slots__ = ("address", "client", "shipped_version",
                 "transient_failures", "degraded")

    def __init__(self, address: str):
        self.address = address
        self.client = replication_client(address)
        self.shipped_version = -1
        self.transient_failures = 0
        self.degraded = False


class _ShipIncomplete(RuntimeError):
    """A ship left at least one live backup behind (transient failure):
    the caller retries on its own cadence."""


class Replicator:
    """Primary-side shipper.  ``on_apply`` is installed as the core's
    replication hook; :meth:`start`/:meth:`stop` manage the reconcile
    thread (which also covers restores/initializations and the buffered
    aggregation mode, where the close-path hook never fires).

    ``backup_address`` may name SEVERAL backups (comma-separated): each
    gets its own client, ship watermark, and downgrade state, so one
    dead backup never stalls or degrades the others.  ``degraded`` means
    every backup is gone."""

    def __init__(self, core, backup_address: str, mode: str = "async",
                 poll_s: float = 0.25, include_optimizer: bool = True,
                 timeout_s: float = 60.0):
        if mode not in ("async", "sync"):
            raise ValueError(f"unknown replication mode {mode!r}; "
                             f"options: async, sync")
        addresses = [a.strip() for a in backup_address.split(",")
                     if a.strip()]
        if not addresses:
            raise ValueError("Replicator needs at least one backup address")
        self._core = core
        self.backup_address = backup_address
        self.addresses = tuple(addresses)
        self.mode = mode
        self._poll_s = float(poll_s)
        self._include_optimizer = include_optimizer
        self._timeout_s = float(timeout_s)
        self._peers = {a: _Peer(a) for a in addresses}
        # wake flag for the reconcile thread (leaf; tiny critical
        # sections only, so an in-flight ship never blocks the hook)
        self._lock = checked_lock("Replicator._lock")
        self._cv = threading.Condition(self._lock)
        self._pending = False
        # serializes one ship end to end (encode + RPC + ack): sync-mode
        # ships run on barrier-closer threads, the reconcile thread runs
        # its own — version monotonicity to the sink needs an order
        self._ship_lock = checked_lock("Replicator._ship_lock")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._obs_lag = obs_stats.gauge("ps.replica.lag_bytes")
        self._obs_shipped = obs_stats.counter("ps.replica.shipped_bytes")
        self._obs_ship_s = obs_stats.histogram("ps.replica.ship_s")
        self._obs_fallback = obs_stats.counter("ps.replica.fallback")

    @property
    def degraded(self) -> bool:
        return all(p.degraded for p in self._peers.values())

    @property
    def last_shipped_version(self) -> int:
        """The version every LIVE backup provably holds (the sync-mode
        guarantee floor); with no live backup, the high-water mark of
        whatever was ever shipped."""
        live = [p.shipped_version for p in self._peers.values()
                if not p.degraded]
        if live:
            return min(live)
        return max((p.shipped_version for p in self._peers.values()),
                   default=-1)

    # ----------------------------------------- sharded-update interface
    def live_addresses(self) -> tuple:
        """Backups still in the replication set, in configured order."""
        return tuple(a for a in self.addresses
                     if not self._peers[a].degraded)

    def shipped_version(self, address: str) -> int:
        peer = self._peers.get(address)
        return -1 if peer is None else peer.shipped_version

    def note_shipped(self, version: int, addresses) -> None:
        """Advance per-backup watermarks for state delivered OUTSIDE the
        flat ship (the sharded-update exchange IS the replication for a
        close): the next flat ship coalesces for those backups, which is
        exactly where the bandwidth win lands."""
        with self._ship_lock:
            for address in addresses:
                peer = self._peers.get(address)
                if peer is not None and version > peer.shipped_version:
                    peer.shipped_version = version
                    peer.transient_failures = 0

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._core.set_replication_hook(self.on_apply)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ps-replicator")
        self._thread.start()

    def stop(self) -> None:
        self._core.set_replication_hook(None)
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for peer in self._peers.values():
            peer.client.close()

    # ------------------------------------------------------------------ hook
    def on_apply(self) -> None:
        """The core's post-apply hook.  MUST NOT raise: the optimizer
        apply has already landed, so failing the close here would
        double-apply on its retry.  Sync mode instead retries the ship
        INLINE (bounded exponential backoff — the barrier stays
        unpublished while it does, so workers cannot observe an
        iteration the backup does not hold); if every retry fails,
        replication degrades permanently — loudly, with
        ``ps.replica.fallback`` counts — and THIS close (plus all later
        ones) publishes unreplicated rather than wedging training on a
        dead backup.  The sync guarantee is therefore exact up to the
        moment of explicit degradation."""
        if self.degraded:
            return
        if self.mode == "sync":
            delay = 0.1
            # caller holds _apply_lock: snapshot via the in-close path
            snapshot = self._core.replica_snapshot(in_close=True)
            while not self.degraded:
                try:
                    self._ship(snapshot)
                    return
                except Exception:  # noqa: BLE001 — retried, then degraded
                    log.exception("sync replication ship failed; retrying")
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
            return
        with self._cv:
            self._pending = True
            self._cv.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Ship the current state to every live backup that is behind;
        True when they all hold the primary's current version on
        return."""
        if self.degraded:
            return False
        try:
            self._ship(self._core.replica_snapshot())
        except Exception:  # noqa: BLE001 — reported via return value
            log.exception("replication flush failed")
            return False
        return not self.degraded

    # ------------------------------------------------------------- internals
    def _note_peer_failure(self, peer: _Peer) -> None:
        peer.transient_failures += 1
        self._obs_fallback.add()
        if peer.transient_failures >= _MAX_TRANSIENT_FAILURES:
            log.warning(
                "replication to %s degraded permanently after %d "
                "consecutive failures%s",
                peer.address, peer.transient_failures,
                "" if any(not p.degraded and p is not peer
                          for p in self._peers.values())
                else " — training continues UNREPLICATED")
            flight.record("repl.degrade", a=peer.transient_failures,
                          note="transport failures")
            peer.degraded = True

    def _degrade_peer(self, peer: _Peer, note: str) -> None:
        flight.record("repl.degrade", note=note)
        self._obs_fallback.add()
        peer.degraded = True

    def _behind(self) -> bool:
        version = self._core.params_version
        return any(not p.degraded and p.shipped_version < version
                   for p in self._peers.values())

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                if not self._pending:
                    self._cv.wait(self._poll_s)
                self._pending = False
            if self._stop.is_set() or self.degraded:
                if self.degraded:
                    return
                continue
            if not self._behind():
                continue
            try:
                self._ship(self._core.replica_snapshot())
            except Exception:  # noqa: BLE001 — retried next wake
                log.exception("replication ship failed; will retry")

    def _ship(self, snapshot) -> None:
        """One coalesced ship round: encode once, push to every live
        backup whose watermark is behind ``snapshot``'s version.  Peer
        failures are contained per peer (transient counting, permanent
        downgrade on UNIMPLEMENTED/refusal); raises
        :class:`_ShipIncomplete` when a live backup is still behind on
        return so callers retry on their own cadence."""
        epoch, iteration, version, params, opt_state = snapshot
        with self._ship_lock:
            todo = [p for p in self._peers.values()
                    if not p.degraded and version > p.shipped_version]
            if not todo:
                return  # coalesced: newer ships already covered this
            store = dict(params)
            if self._include_optimizer and opt_state:
                store.update(flatten_optimizer_state(opt_state))
            nbytes = store_nbytes(store)
            self._obs_lag.set(nbytes * len(todo))
            t0 = time.perf_counter()
            flight.record("repl.ship.start", iteration=iteration,
                          a=nbytes, b=version)
            stripes = getattr(self._core, "stripes", 1)
            incomplete = False
            for peer in todo:
                try:
                    ack = peer.client.call(
                        "PushReplicaDelta",
                        delta_chunks(epoch, iteration, version,
                                     rmsg.DELTA_STATE, store,
                                     stripes=stripes),
                        timeout=self._timeout_s)
                except grpc.RpcError as exc:
                    code = getattr(exc, "code", None)
                    if (callable(code)
                            and code() == grpc.StatusCode.UNIMPLEMENTED):
                        # reference PS as backup: no replication, ever
                        log.warning(
                            "backup %s does not implement replication; "
                            "degrading permanently", peer.address)
                        self._degrade_peer(peer, "UNIMPLEMENTED")
                        continue
                    log.exception("replication ship to %s failed",
                                  peer.address)
                    self._note_peer_failure(peer)
                    incomplete = True
                    continue
                except Exception:  # noqa: BLE001 — contained per peer
                    log.exception("replication ship to %s failed",
                                  peer.address)
                    self._note_peer_failure(peer)
                    incomplete = True
                    continue
                if not ack.success:
                    # the sink refused (e.g. the replica was promoted and
                    # has advanced past us — we are the zombie): stop
                    # shipping to it
                    log.warning("backup %s refused delta: %s — degrading "
                                "permanently", peer.address, ack.message)
                    flight.record("repl.ack", iteration=iteration, a=0,
                                  b=version, note=ack.message)
                    self._degrade_peer(peer, "sink refused")
                    continue
                flight.record("repl.ack", iteration=iteration, a=1,
                              b=version)
                self._obs_shipped.add(nbytes)
                peer.shipped_version = version
                peer.transient_failures = 0
            self._obs_ship_s.observe(time.perf_counter() - t0)
            flight.record("repl.ship.end", iteration=iteration,
                          a=int(1e6 * (time.perf_counter() - t0)), b=version)
            if not incomplete:
                self._obs_lag.set(0)
            if incomplete and not self.degraded:
                raise _ShipIncomplete(
                    f"replication ship v{version} left a live backup "
                    f"behind")


class ReplicaSink:
    """Backup-side installer for ``PushReplicaDelta`` streams.  One per
    PS service; tracks the primary's high-water mark so ``ReplicaStatus``
    and a promotion decision can read it."""

    def __init__(self, core):
        self._core = core
        # held across core.install_tensors (ranks 20..40 — sink rank 16
        # comes first): serializes delta installs against each other so
        # two racing ships can never interleave their version bookkeeping
        self._lock = checked_lock("ReplicaSink._lock")
        self.primary_version = -1
        self.primary_iteration = -1
        self._installed_any = False
        self._obs_installed = obs_stats.counter("ps.replica.installed_bytes")
        # 1 while this backup replicates by flat SHIPPING — its
        # accelerator idle through every close; the sharded-update sink
        # (replication/sharded_update.py) zeroes it when the backup
        # starts computing close slices
        self._obs_idle = obs_stats.gauge("ps.replica.idle_accelerator")

    def push_delta(self, chunks) -> rmsg.ReplicaAck:
        header = None
        wire_tensors: list = []
        for chunk in chunks:
            if header is None:
                header = (int(chunk.epoch), int(chunk.iteration),
                          int(chunk.params_version), int(chunk.kind))
            wire_tensors.extend(chunk.tensors)
        if header is None:
            return rmsg.ReplicaAck(success=False,
                                   message="empty delta stream")
        epoch, iteration, version, kind = header
        store = from_wire(wire_tensors)
        params, opt_state = split_replica_store(store)
        with self._lock:
            if kind == rmsg.DELTA_STATE:
                if self._installed_any and version <= self.primary_version:
                    # an out-of-order/duplicate ship: the newer state is
                    # already installed — idempotent success
                    return rmsg.ReplicaAck(
                        success=True, message="stale delta ignored",
                        params_version=self.primary_version,
                        iteration=self.primary_iteration)
                if (self._installed_any
                        and self._core.current_iteration
                        > self.primary_iteration):
                    # this replica has aggregated past the replication
                    # mark on its own — it was PROMOTED; the sender is a
                    # zombie ex-primary whose state would rewind live
                    # training
                    flight.record("repl.refuse", iteration=iteration,
                                  b=version, note="zombie delta")
                    return rmsg.ReplicaAck(
                        success=False,
                        message="replica promoted (local aggregation "
                                "advanced past the replication mark); "
                                "delta refused",
                        params_version=self.primary_version,
                        iteration=self._core.current_iteration)
            self._core.install_tensors(
                params, epoch=epoch, iteration=iteration,
                optimizer_state=opt_state,
                # a reshard stripe handoff MERGES its slot entries into
                # this shard's optimizer state; a replication state ship
                # replaces it wholesale (bit-identical replica)
                optimizer_merge=(kind == rmsg.DELTA_INSTALL),
                mark_aggregated=True,
                replace=(kind == rmsg.DELTA_STATE))
            if kind == rmsg.DELTA_STATE:
                self.primary_version = version
                self.primary_iteration = iteration
                self._installed_any = True
                self._obs_idle.set(1)
        self._obs_installed.add(store_nbytes(params))
        return rmsg.ReplicaAck(success=True, message="installed",
                               params_version=version, iteration=iteration)
