"""Coordinator-orchestrated live resharding (split/merge) of the PS tier.

The worker-side partition is pure arithmetic — ``shard_owner(name, N)``
(crc32 % N, worker/ps_shards.py) — so changing the shard COUNT moves a
deterministic subset of tensor names to new owners.  The controller
performs the move live, with training running:

1. **census** — each current shard lists its tensor names
   (``ReplicaStatus``; names only, no values).
2. **fence + copy** — for every shard losing names, ``RetireTensors``
   atomically removes the moving tensors from its store, tombstones them
   at the upcoming map epoch, and returns their values — all under one
   lock hold, so the copied stripe is exactly the last state that shard
   applied to it (the "version fence").  From this instant a push
   touching a moved name is rejected with the ``stale shard map`` marker
   and the pushing worker parks in
   ``ShardMapClient.wait_for_epoch_above`` — zero failed steps, just a
   bounded pause for the handoff.
3. **install** — the values land on their new owners via
   ``PushReplicaDelta`` (kind=DELTA_INSTALL: merge, don't replace), each
   marked with the source's iteration so the new owner's aggregated
   watermark makes retried pushes idempotent.
4. **publish** — ``CoordinatorCore.set_shard_map`` replaces the layout
   and bumps the epoch; parked workers see it, rebuild their shard
   connections, repartition, and replay the rejected round (per-(worker,
   tensor) dedup on the unchanged shards absorbs the replay).

``ps.reshard.moved_bytes`` counts the handoff volume.
"""

from __future__ import annotations

import logging
from typing import Sequence

from ..core.coordinator_core import CoordinatorCore, ShardMapEntry
from ..core.tensor import TensorStore, from_wire, store_nbytes
from ..obs import stats as obs_stats
from ..worker.ps_shards import shard_owner
from . import messages as rmsg
from .replicator import (OPT_PREFIX, delta_chunks, replication_client,
                         split_replica_store)

log = logging.getLogger("pst.reshard")

_obs_moved = obs_stats.counter("ps.reshard.moved_bytes")
_obs_ops = obs_stats.counter("ps.reshard.ops")


class ReshardError(RuntimeError):
    pass


def _as_entries(layout: Sequence) -> list[ShardMapEntry]:
    entries: list[ShardMapEntry] = []
    for item in layout:
        if isinstance(item, ShardMapEntry):
            entries.append(ShardMapEntry(primary=item.primary,
                                         backup=item.backup))
        elif isinstance(item, (tuple, list)):
            entries.append(ShardMapEntry(
                primary=item[0], backup=item[1] if len(item) > 1 else ""))
        else:
            entries.append(ShardMapEntry(primary=str(item)))
    return entries


class ReshardController:
    """One-shot orchestration of a shard-count change.  Runs wherever the
    coordinator core is reachable in-process (the coordinator itself, an
    admin CLI, a test)."""

    def __init__(self, coordinator_core: CoordinatorCore,
                 timeout_s: float = 60.0):
        self._core = coordinator_core
        self._timeout_s = float(timeout_s)

    def reshard(self, new_layout: Sequence) -> dict:
        """Move to ``new_layout`` (addresses or (primary, backup) pairs).
        Returns a stats dict: moved_bytes, moved_tensors, epoch.  The new
        shards' PS processes must already be running and reachable; a
        shard present in both layouts keeps its non-moving tensors in
        place (only ownership DIFFS travel)."""
        new_entries = _as_entries(new_layout)
        if not new_entries:
            raise ReshardError("new layout must have at least one shard")
        old_epoch, old_entries = self._core.get_shard_map()
        old_primaries = [e.primary for e in old_entries]
        new_primaries = [e.primary for e in new_entries]
        n_new = len(new_primaries)
        fence_epoch = old_epoch + 1  # the epoch set_shard_map will publish

        clients = {addr: replication_client(addr)
                   for addr in set(old_primaries) | set(new_primaries)}
        try:
            # 1. census: names per current shard, and the fence mark —
            # the highest iteration any shard has seen.  Every shard in
            # the new layout gets its aggregated watermark raised to it
            # (step 3), so an iteration that was mid-flight at the fence
            # can never strand a barrier on a shard the not-yet-
            # repartitioned workers will never push to (its gradients for
            # the transition iteration are simply skipped there — the
            # bounded handoff gap).
            names_by_shard: dict[int, list[str]] = {}
            fence_mark = 0
            fence_epoch_max = 0
            for i, addr in enumerate(old_primaries):
                status = clients[addr].call("ReplicaStatus",
                                            rmsg.ReplicaStatusRequest(),
                                            timeout=self._timeout_s)
                names_by_shard[i] = list(status.names)
                fence_mark = max(fence_mark, int(status.iteration))
                fence_epoch_max = max(fence_epoch_max, int(status.epoch))

            # which names leave which shard, and where they land
            transfers: dict[str, TensorStore] = {}  # new addr -> tensors
            moved_tensors = 0
            moved_bytes = 0
            for i, addr in enumerate(old_primaries):
                moving = [n for n in names_by_shard[i]
                          if new_primaries[shard_owner(n, n_new)] != addr]
                if not moving:
                    continue
                # 2. fence + copy (atomic on the source); the retired
                # payload carries the moved tensors AND their optimizer
                # slot entries (__opt__/<slot>/<name>), each routed to
                # its parameter's new owner so the optimization
                # trajectory survives the move
                retired: TensorStore = {}
                for chunk in clients[addr].call(
                        "RetireTensors",
                        rmsg.RetireTensorsRequest(names=moving,
                                                  map_epoch=fence_epoch),
                        timeout=self._timeout_s):
                    fence_epoch_max = max(fence_epoch_max, int(chunk.epoch))
                    fence_mark = max(fence_mark, int(chunk.iteration))
                    retired.update(from_wire(chunk.tensors))
                params, moved_opt = split_replica_store(retired)
                for name, value in params.items():
                    dest = new_primaries[shard_owner(name, n_new)]
                    transfers.setdefault(dest, {})[name] = value
                for slot, entries in (moved_opt or {}).items():
                    if not isinstance(entries, dict):
                        continue  # scalars (step counts) never move
                    for name, value in entries.items():
                        dest = new_primaries[shard_owner(name, n_new)]
                        transfers.setdefault(dest, {})[
                            f"{OPT_PREFIX}{slot}/{name}"] = value
                moved_tensors += len(params)
                moved_bytes += store_nbytes(params)
                log.info("reshard: %d tensors (%.1f MB) leave %s",
                         len(params), store_nbytes(params) / 1e6, addr)

            # 3. install on the new owners, then broadcast the fence mark
            # to EVERY shard of the new layout (an empty marker install
            # raises the aggregated watermark, see step 1) — shards with
            # transfers get it implicitly with their tensors
            for dest, tensors in transfers.items():
                ack = clients[dest].call(
                    "PushReplicaDelta",
                    delta_chunks(fence_epoch_max, fence_mark, 0,
                                 rmsg.DELTA_INSTALL, tensors),
                    timeout=self._timeout_s)
                if not ack.success:
                    raise ReshardError(
                        f"install on {dest} refused: {ack.message}")
            for dest in new_primaries:
                if dest in transfers:
                    continue
                ack = clients[dest].call(
                    "PushReplicaDelta",
                    delta_chunks(fence_epoch_max, fence_mark, 0,
                                 rmsg.DELTA_INSTALL, {}),
                    timeout=self._timeout_s)
                if not ack.success:
                    raise ReshardError(
                        f"fence mark on {dest} refused: {ack.message}")

            # 4. publish the new map (bumps the epoch; parked workers
            # repartition)
            epoch = self._core.set_shard_map(new_entries)
            _obs_moved.add(moved_bytes)
            _obs_ops.add()
            log.info("reshard complete: %d -> %d shards at epoch %d "
                     "(%d tensors, %.1f MB moved)", len(old_primaries),
                     n_new, epoch, moved_tensors, moved_bytes / 1e6)
            return {"epoch": epoch, "moved_tensors": moved_tensors,
                    "moved_bytes": moved_bytes,
                    "old_shards": len(old_primaries), "new_shards": n_new}
        finally:
            for client in clients.values():
                client.close()
