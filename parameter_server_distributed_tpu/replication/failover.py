"""Worker-side shard-map client: hot failover + reshard repartitioning.

:class:`ShardMapClient` is the worker's view of the coordinator's
epoch-numbered shard map (core/coordinator_core.py).  ``ShardedPSClient``
(worker/ps_shards.py) consults it twice:

- **failover** — a shard RPC dies mid-push/pull (transport error, not
  UNIMPLEMENTED): :meth:`report_failure` tells the coordinator, which
  promotes the shard's backup idempotently (first reporter wins; everyone
  else reads the fresh map) and the client retries the SAME iteration
  against the replica.  The dead primary is never revisited — the PR-2
  permanent per-connection downgrade discipline, lifted to addresses.
- **resharding** — a push comes back with the ``stale shard map`` marker
  (replication/messages.py): :meth:`wait_for_epoch_above` polls the
  coordinator until the reshard controller publishes the new layout,
  then the client rebuilds its shard connections and repartitions.

A reference coordinator answers ``GetShardMap`` UNIMPLEMENTED;
:meth:`refresh` then returns False and the worker stays on the static
discovery topology (no failover, exactly the pre-replication behavior).
"""

from __future__ import annotations

import logging
import threading
import time

import grpc

from ..obs import flight
from ..obs import stats as obs_stats
from ..rpc import messages as m
from ..rpc.service import RpcClient
from ..rpc.service import status_code as _status_code
from . import messages as rmsg

log = logging.getLogger("pst.failover")


class ShardMapClient:
    """Cached (epoch, entries) + the promotion/refresh RPCs.  Thread-safe:
    the sharded client's fan-out threads may report failures and read the
    map concurrently (plain lock — leaf, no other lock acquired under
    it, and this object lives in the worker process, outside the ranked
    PS/coordinator lock tables)."""

    def __init__(self, coordinator_address: str, worker_id: int = 0,
                 client: RpcClient | None = None):
        self._client = client or RpcClient(
            coordinator_address, m.COORDINATOR_SERVICE,
            {**m.COORDINATOR_METHODS, **rmsg.REPLICATION_COORD_METHODS})
        self.worker_id = int(worker_id)
        self._lock = threading.Lock()
        self.epoch = 0
        self.entries: list[rmsg.WireShardMapEntry] = []
        self._supported: bool | None = None
        # failover attempts only — actual promotions are counted at the
        # coordinator (CoordinatorCore.promote_shard), which is the one
        # place that knows whether a report really swapped a primary (N
        # racing reporters see the address change but only one caused it)
        self._obs_failovers = obs_stats.counter("ps.replica.failovers")

    @property
    def supported(self) -> bool:
        """True once the coordinator has answered ``GetShardMap`` (a
        reference coordinator never will — permanent downgrade)."""
        return self._supported is True

    def close(self) -> None:
        self._client.close()

    def _adopt(self, resp: rmsg.ShardMapResponse) -> None:
        with self._lock:
            if resp.epoch >= self.epoch:
                self.epoch = int(resp.epoch)
                self.entries = list(resp.entries)

    def refresh(self, timeout: float = 5.0) -> bool:
        """Fetch the current map.  False = coordinator does not speak the
        extension (reference peer; remembered) or is unreachable."""
        if self._supported is False:
            return False
        try:
            resp = self._client.call("GetShardMap", rmsg.ShardMapRequest(),
                                     timeout=timeout)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                self._supported = False
            return False
        self._supported = True
        self._adopt(resp)
        return True

    def primaries(self) -> list[str]:
        with self._lock:
            return [e.primary for e in self.entries]

    def has_backups(self) -> bool:
        with self._lock:
            return any(e.backup for e in self.entries)

    def report_failure(self, shard_index: int, observed_primary: str,
                       timeout: float = 10.0) -> str | None:
        """Report a dead primary; returns the shard's CURRENT primary
        from the post-promotion map (None when the coordinator cannot
        help — no extension, no backup, unreachable).  Counts a failover
        attempt always and a promotion when the primary actually
        changed."""
        if self._supported is False:
            return None
        self._obs_failovers.add()
        flight.record("failover.report", worker=self.worker_id,
                      a=shard_index, note=observed_primary)
        with self._lock:
            epoch = self.epoch
        try:
            resp = self._client.call(
                "ReportShardFailure",
                rmsg.ShardFailureReport(shard_index=shard_index,
                                        observed_primary=observed_primary,
                                        epoch=epoch,
                                        worker_id=self.worker_id),
                timeout=timeout)
        except grpc.RpcError as exc:
            if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
                self._supported = False
            log.warning("shard-failure report for %s failed: %s",
                        observed_primary, exc)
            return None
        self._supported = True
        self._adopt(resp)
        with self._lock:
            if shard_index >= len(self.entries):
                return None
            current = self.entries[shard_index].primary
        if current == observed_primary:
            return None  # nothing to promote: the shard really is gone
        return current

    def wait_for_epoch_above(self, epoch: int, timeout: float = 15.0,
                             poll_s: float = 0.1) -> bool:
        """Poll the coordinator until the map epoch exceeds ``epoch``
        (a reshard/promotion published) or the timeout lapses."""
        deadline = time.monotonic() + timeout
        while True:
            if self.refresh() and self.epoch > epoch:
                return True
            if time.monotonic() >= deadline:
                return self.epoch > epoch
            time.sleep(poll_s)
