"""Replication/failover/resharding extension RPC messages.

Deliberately NOT in ``rpc/messages.py``: the analyzer's wire manifest
pins the reference contract (field tags, method tables) and this
subsystem must leave it byte-unchanged.  These are extra method names on
the two existing gRPC services — a reference peer simply never calls
them and answers UNIMPLEMENTED, which every caller treats as a permanent
per-connection downgrade (the PR-2/PR-6 fallback discipline).

Tensor payloads reuse :class:`rpc.messages.Tensor` — the PR-6 codec
frames (``ArrayPayload`` packed encodings, native fast path included)
carry replication traffic exactly as they carry the training data plane.
"""

from __future__ import annotations

from ..rpc.messages import TRACE_FIELD_NUMBER, Tensor
from ..rpc.wire import Field, Message

# Marker the PS embeds in a push rejection when the push touched tensors
# that a live reshard moved to another owner; ShardedPSClient matches on
# it, refreshes the shard map (waiting for the epoch to advance), and
# replays the round against the new partition.
STALE_SHARD_MAP = "stale shard map"

# ReplicaDeltaChunk.kind values
DELTA_STATE = 0    # full post-apply state ship (primary -> backup): the
                   # receiver REPLACES its store (bit-identical replica)
DELTA_INSTALL = 1  # stripe handoff (resharding): the receiver MERGES the
                   # tensors into its store


# --------------------------------------------------------------------------
# parameter-server service extensions
# --------------------------------------------------------------------------

class ReplicaDeltaChunk(Message):
    """One chunk of a replication ship (client-streamed).  Header fields
    ride every chunk (a handful of bytes); ``params_version`` is the
    SENDER's store version, which the sink tracks as the replication
    high-water mark.  Optimizer slot state rides as tensors under the
    ``__opt__/`` name prefix (replicator.flatten_optimizer_state)."""
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "iteration", "int32"),
        Field(3, "params_version", "int64"),
        Field(4, "kind", "int32"),
        Field(5, "tensors", "message", message_type=Tensor, repeated=True),
        # span propagation (obs/trace.py): the primary's replication ship
        # joins the barrier-close trace, so failover/replication legs
        # render in the merged Chrome trace.  Same field number as the
        # reference-message extension; these messages are NOT in the wire
        # manifest (extension RPC), so adding it is compat-free.
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class ReplicaAck(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "params_version", "int64"),
        Field(4, "iteration", "int32"),
    )


class ReplicaStateRequest(Message):
    """``names`` empty = the full store."""
    FIELDS = (
        Field(1, "names", "string", repeated=True),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class ReplicaStateChunk(Message):
    """One chunk of a state fetch / stripe retirement (server-streamed).
    The first chunk always goes out (header even for an empty subset);
    ``last`` marks the final chunk."""
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "iteration", "int32"),
        Field(3, "params_version", "int64"),
        Field(4, "tensors", "message", message_type=Tensor, repeated=True),
        Field(5, "last", "bool"),
    )


class RetireTensorsRequest(Message):
    """Atomically remove ``names`` from the serving store and tombstone
    them at ``map_epoch``: later pushes touching them are rejected with
    the ``stale shard map`` marker.  The response streams the retired
    tensors — snapshotted under the same lock hold as the removal, the
    resharding version fence."""
    FIELDS = (
        Field(1, "names", "string", repeated=True),
        Field(2, "map_epoch", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class ReplicaStatusRequest(Message):
    FIELDS = (Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),)


class ReplicaStatusResponse(Message):
    """``primary_version``/``primary_iteration`` are the replication
    high-water mark a backup tracks (-1 = never shipped to); ``names``
    lists the store's tensor names (the resharding controller's cheap
    ownership census — values stay put)."""
    FIELDS = (
        Field(1, "iteration", "int32"),
        Field(2, "params_version", "int64"),
        Field(3, "primary_version", "int64"),
        Field(4, "primary_iteration", "int32"),
        Field(5, "names", "string", repeated=True),
        Field(6, "epoch", "int32"),
    )


REPLICATION_PS_METHODS = {
    "PushReplicaDelta": (ReplicaDeltaChunk, ReplicaAck, "stream_unary"),
    "FetchReplicaState": (ReplicaStateRequest, ReplicaStateChunk,
                          "unary_stream"),
    "RetireTensors": (RetireTensorsRequest, ReplicaStateChunk,
                      "unary_stream"),
    "ReplicaStatus": (ReplicaStatusRequest, ReplicaStatusResponse),
}


# --------------------------------------------------------------------------
# cross-replica sharded update (arXiv:2004.13336 over the replication link)
# --------------------------------------------------------------------------

# ShardedSliceChunk.kind values
SLICE_SUMS = 0    # mirrored fold sums for the receiver's owned slice
SLICE_PARAMS = 1  # fresh parameter slab slice (post-apply)
SLICE_SLOT = 2    # fresh optimizer slot slab slice (always raw f32)


class ShardedSliceChunk(Message):
    """One slab-slice segment of a sharded arena close.

    The same message rides both exchange legs: the primary streams
    ``SLICE_SUMS`` chunks for a peer's owned ``[lo, hi)`` ranges and the
    peer answers with ``SLICE_PARAMS``/``SLICE_SLOT`` chunks for the
    freshly applied slices (``ShardedApplySlices``, stream-stream); the
    primary then broadcasts every peer's missing param slices plus the
    commit header (``InstallSlabSlices``, stream-unary).

    Header fields ride every chunk.  ``plan_epoch`` is the PackingTable
    epoch both sides must agree on (the slice-assignment fence, like the
    shard-map epoch); ``base_version``/``new_version`` pin the store
    version the apply starts from and the one the close commits.
    ``payload`` is a single Tensor whose flat f32 payload is one
    contiguous segment of the slab slice — ``index`` orders segments
    inside a (kind, stripe, slot, lo, hi) slice when it exceeds the
    stream chunk budget.  A non-empty ``error`` aborts the exchange (the
    receiver's refusal reason); the sender degrades that close to the
    local full apply."""
    FIELDS = (
        Field(1, "plan_epoch", "int32"),
        Field(2, "epoch", "int32"),
        Field(3, "iteration", "int32"),
        Field(4, "base_version", "int64"),
        Field(5, "new_version", "int64"),
        Field(6, "kind", "int32"),
        Field(7, "stripe", "int32"),
        Field(8, "slot", "string"),
        Field(9, "lo", "int64"),
        Field(10, "hi", "int64"),
        Field(11, "payload", "message", message_type=Tensor),
        Field(12, "last", "bool"),
        Field(13, "step", "int64"),
        Field(14, "index", "int32"),
        Field(15, "replicas", "int32"),
        Field(16, "stripes", "int32"),
        Field(17, "error", "string"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


class ShardedSliceAck(Message):
    FIELDS = (
        Field(1, "success", "bool"),
        Field(2, "message", "string"),
        Field(3, "params_version", "int64"),
    )


# Extra method names on the parameter-server service, merged into the
# extension table at bind time.  UNIMPLEMENTED from an older peer is a
# permanent per-connection downgrade to the flat-ship path.
SHARDED_UPDATE_PS_METHODS = {
    "ShardedApplySlices": (ShardedSliceChunk, ShardedSliceChunk,
                           "stream_stream"),
    "InstallSlabSlices": (ShardedSliceChunk, ShardedSliceAck,
                          "stream_unary"),
}


# --------------------------------------------------------------------------
# coordinator service extensions
# --------------------------------------------------------------------------

class WireShardMapEntry(Message):
    """One shard of the epoch-numbered map (core.coordinator_core
    ShardMapEntry on the wire)."""
    FIELDS = (
        Field(1, "primary", "string"),
        Field(2, "backup", "string"),
        Field(3, "epoch", "int32"),
    )


class ShardMapRequest(Message):
    # trace context (obs/trace.py): a worker's map refresh during a
    # failover joins the step trace that triggered it
    FIELDS = (Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),)


class ShardMapResponse(Message):
    FIELDS = (
        Field(1, "epoch", "int32"),
        Field(2, "entries", "message", message_type=WireShardMapEntry,
              repeated=True),
    )


class ShardFailureReport(Message):
    """A worker observed ``observed_primary`` (shard ``shard_index``)
    dead at map epoch ``epoch``.  The coordinator promotes the shard's
    backup — idempotently: a report against an address that is no longer
    the primary (another worker already promoted) is a no-op — and
    returns the current map either way."""
    FIELDS = (
        Field(1, "shard_index", "int32"),
        Field(2, "observed_primary", "string"),
        Field(3, "epoch", "int32"),
        Field(4, "worker_id", "int32"),
        Field(TRACE_FIELD_NUMBER, "trace_context", "bytes"),
    )


REPLICATION_COORD_METHODS = {
    "GetShardMap": (ShardMapRequest, ShardMapResponse),
    "ReportShardFailure": (ShardFailureReport, ShardMapResponse),
}
