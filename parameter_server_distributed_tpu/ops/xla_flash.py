"""Blockwise online-softmax causal attention in plain XLA ops.

The same flash-attention recurrence as the pallas kernels
(ops/pallas/flash_attention.py) — running max / rescaled accumulator /
denominator per K/V block — but expressed as a ``lax.scan`` over key
blocks so XLA compiles it natively on EVERY backend.  Three uses:

- the robust long-context path anywhere pallas is unavailable or the
  shapes don't fit its tiling (the pallas kernels fall back to interpret
  mode off-TPU, which is orders of magnitude slower than compiled code);
- an apples-to-apples A/B contender for the pallas kernels on TPU (XLA's
  fused scan body is often competitive — `PSDT_BENCH_ATTENTION=xla_flash`);
- the CPU proxy for long-sequence benchmarking: dense attention
  materializes the [B, H, S, S] probability tensor (4 GB at S=8192,
  H=16, f32) while this streams O(S * block) working sets.

Memory: forward residuals are O(S) (out, running stats) — the scan body
is wrapped in ``jax.checkpoint`` so the backward pass recomputes each
block's probabilities instead of saving them, exactly the flash backward
trade.  GQA K/V stay UNexpanded: query-head groups contract against the
[B, S, KV, D] cache directly (no materialized repeat), mirroring
models/generation.decode_block.

No reference analogue (the reference has no model layer — SURVEY.md §1).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

Array = jax.Array


@functools.partial(jax.jit, static_argnames=("block_k",))
def xla_flash_attention(q: Array, k: Array, v: Array,
                        block_k: int = 512) -> Array:
    """Causal attention, blockwise-streamed over keys.

    q: [B, S, H, D]; k/v: [B, S, H, D] or GQA [B, S, KV, D] (unexpanded).
    Returns [B, S, H, D] in q's dtype.  S must divide by ``block_k``
    (callers pick block_k = min(block_k, S); see :func:`auto_block`).
    """
    b, s, h, d = q.shape
    kv = k.shape[2]
    if h % kv:
        raise ValueError(f"query heads {h} must divide by kv heads {kv}")
    g = h // kv
    if s % block_k:
        raise ValueError(f"seq {s} must divide by block_k {block_k}")
    nk = s // block_k
    scale = 1.0 / math.sqrt(d)

    # head h = kv_head * G + group (repeat_kv convention, matching
    # expand_gqa / flash_attention_gqa)
    qg = q.reshape(b, s, kv, g, d)
    kb = k.reshape(b, nk, block_k, kv, d)
    vb = v.reshape(b, nk, block_k, kv, d)
    q_pos = jnp.arange(s, dtype=jnp.int32)

    def block_update(carry, xs):
        acc, m, l = carry                     # [B,KV,G,S,D], [B,KV,G,S], ...
        j, k_j, v_j = xs                      # k_j/v_j: [B, block_k, KV, D]
        scores = jnp.einsum("bqegd,bjed->begqj", qg, k_j,
                            preferred_element_type=jnp.float32) * scale
        k_pos = j * block_k + jnp.arange(block_k, dtype=jnp.int32)
        mask = q_pos[:, None] >= k_pos[None, :]           # [S, block_k]
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # fully-masked rows keep m == -inf; exp(-inf - -inf) must be 0,
        # not nan, so clamp the shift for those rows
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(m - shift)
        p = jnp.exp(scores - shift[..., None])            # [B,KV,G,S,Bk]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("begqj,bjed->begqd", p, v_j,
                        preferred_element_type=jnp.float32)
        acc_new = acc * alpha[..., None] + pv
        return (acc_new, m_new, l_new), None

    init = (jnp.zeros((b, kv, g, s, d), jnp.float32),
            jnp.full((b, kv, g, s), -jnp.inf, jnp.float32),
            jnp.zeros((b, kv, g, s), jnp.float32))
    xs = (jnp.arange(nk, dtype=jnp.int32),
          jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0))
    # checkpoint: backward recomputes each block's probabilities instead
    # of keeping S^2 residuals — the flash backward memory trade
    (acc, _, l), _ = jax.lax.scan(jax.checkpoint(block_update), init, xs)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.moveaxis(out, 3, 1).reshape(b, s, h, d).astype(q.dtype)


def auto_block(seq: int, block_k: int = 512) -> int:
    """Largest divisor-of-seq block not exceeding ``block_k``."""
    block = min(block_k, seq)
    while seq % block:
        block -= 1
    return block


def make_xla_flash_attention(block_k: int = 512):
    """Attention-fn factory matching the Transformer contract
    (models/transformer.py attention_fn: q [B,S,H,D], k/v [B,S,KV,D])."""
    def attend(q: Array, k: Array, v: Array) -> Array:
        return xla_flash_attention(q, k, v,
                                   block_k=auto_block(q.shape[1], block_k))
    return attend
