"""Fused optimizer-update pallas kernels.

The PS update path (reference: the aggregation loop + `param -= avg_grad`
at src/parameter_server.cpp:40-91, single-threaded C++ over every element)
becomes one pallas pass per tensor: read param/grad (and slots), write the
updated values, all in VMEM-resident tiles with in-place aliasing — no
intermediate HBM round-trips between optimizer sub-ops.

Arrays are processed as (rows, 128) tiles (padded as needed).  On non-TPU
backends kernels run in interpret mode so the same code path is tested on
CPU.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SUBLANE = 8


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _sgd_kernel(lr_ref, p_ref, g_ref, out_ref):
    out_ref[:] = p_ref[:] - lr_ref[0] * g_ref[:]


def _momentum_kernel(scalar_ref, p_ref, g_ref, vel_ref, p_out, vel_out):
    lr, mu = scalar_ref[0], scalar_ref[1]
    v_new = mu * vel_ref[:] + g_ref[:]
    vel_out[:] = v_new
    p_out[:] = p_ref[:] - lr * v_new


def _adam_kernel(scalar_ref, p_ref, g_ref, m_ref, v_ref, p_out, m_out, v_out):
    lr, b1, b2, eps, bc1, bc2 = (scalar_ref[0], scalar_ref[1], scalar_ref[2],
                                 scalar_ref[3], scalar_ref[4], scalar_ref[5])
    g = g_ref[:]
    m_new = b1 * m_ref[:] + (1.0 - b1) * g
    v_new = b2 * v_ref[:] + (1.0 - b2) * g * g
    m_out[:] = m_new
    v_out[:] = v_new
    m_hat = m_new / bc1
    v_hat = v_new / bc2
    p_out[:] = p_ref[:] - lr * m_hat / (jnp.sqrt(v_hat) + eps)


def _as_tiles(arr: jax.Array) -> tuple[jax.Array, int]:
    """Flatten + pad to a (rows, LANE) float32 tile layout."""
    flat = arr.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    rows = -(-n // LANE)
    rows = -(-rows // SUBLANE) * SUBLANE  # round rows to sublane multiple
    padded = jnp.zeros((rows * LANE,), jnp.float32).at[:n].set(flat)
    return padded.reshape(rows, LANE), n


def _from_tiles(tiles: jax.Array, n: int, shape, dtype) -> jax.Array:
    return tiles.reshape(-1)[:n].reshape(shape).astype(dtype)


def fused_sgd(params: Mapping[str, jax.Array],
              grads: Mapping[str, jax.Array], lr: float,
              interpret: bool | None = None) -> dict[str, jax.Array]:
    """param <- param - lr * grad, one fused pass per tensor."""
    interpret = _interpret_default() if interpret is None else interpret
    scalars = jnp.asarray([lr], jnp.float32)
    out = {}
    for name, p in params.items():
        if name not in grads:
            out[name] = p
            continue
        tiles_p, n = _as_tiles(p)
        tiles_g, _ = _as_tiles(grads[name])
        rows = tiles_p.shape[0]
        block = pl.BlockSpec((rows, LANE), lambda: (0, 0))
        (res,) = pl.pallas_call(
            _sgd_kernel,
            out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)],
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), block, block],
            out_specs=[block],
            interpret=interpret,
        )(scalars, tiles_p, tiles_g)
        out[name] = _from_tiles(res, n, np.shape(p), p.dtype)
    return out


def fused_momentum(params: Mapping[str, jax.Array],
                   grads: Mapping[str, jax.Array],
                   velocity: Mapping[str, jax.Array], lr: float,
                   mu: float = 0.9, interpret: bool | None = None):
    """Fused momentum SGD: returns (new_params, new_velocity)."""
    interpret = _interpret_default() if interpret is None else interpret
    scalars = jnp.asarray([lr, mu], jnp.float32)
    new_p, new_v = {}, {}
    for name, p in params.items():
        if name not in grads:
            new_p[name], new_v[name] = p, velocity.get(name)
            continue
        tiles = [_as_tiles(x) for x in (p, grads[name], velocity[name])]
        n = tiles[0][1]
        rows = tiles[0][0].shape[0]
        block = pl.BlockSpec((rows, LANE), lambda: (0, 0))
        res = pl.pallas_call(
            _momentum_kernel,
            out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)] * 2,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [block] * 3,
            out_specs=[block] * 2,
            interpret=interpret,
        )(scalars, *[t for t, _ in tiles])
        new_p[name] = _from_tiles(res[0], n, np.shape(p), p.dtype)
        new_v[name] = _from_tiles(res[1], n, np.shape(p), jnp.float32)
    return new_p, new_v


def fused_adam(params: Mapping[str, jax.Array],
               grads: Mapping[str, jax.Array],
               m: Mapping[str, jax.Array], v: Mapping[str, jax.Array],
               step: int, lr: float = 1e-3, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               interpret: bool | None = None):
    """Fused Adam: returns (new_params, new_m, new_v)."""
    interpret = _interpret_default() if interpret is None else interpret
    bc1 = 1.0 - b1 ** step
    bc2 = 1.0 - b2 ** step
    scalars = jnp.asarray([lr, b1, b2, eps, bc1, bc2], jnp.float32)
    new_p, new_m, new_v = {}, {}, {}
    for name, p in params.items():
        if name not in grads:
            new_p[name], new_m[name], new_v[name] = p, m.get(name), v.get(name)
            continue
        tiles = [_as_tiles(x) for x in
                 (p, grads[name], m[name], v[name])]
        n = tiles[0][1]
        rows = tiles[0][0].shape[0]
        block = pl.BlockSpec((rows, LANE), lambda: (0, 0))
        res = pl.pallas_call(
            _adam_kernel,
            out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)] * 3,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [block] * 4,
            out_specs=[block] * 3,
            interpret=interpret,
        )(scalars, *[t for t, _ in tiles])
        new_p[name] = _from_tiles(res[0], n, np.shape(p), p.dtype)
        new_m[name] = _from_tiles(res[1], n, np.shape(p), jnp.float32)
        new_v[name] = _from_tiles(res[2], n, np.shape(p), jnp.float32)
    return new_p, new_m, new_v
