"""Fused optimizer-update pallas kernels.

The PS update path (reference: the aggregation loop + `param -= avg_grad`
at src/parameter_server.cpp:40-91, single-threaded C++ over every element)
becomes one pallas pass per tensor: read param/grad (and slots), write the
updated values, all in VMEM-resident tiles — no intermediate HBM
round-trips between optimizer sub-ops.

Production caller: async_sgd.PallasOptimizer (the device-resident PS
optimizer selected via ``optimizer=pallas_sgd|pallas_momentum|pallas_adam``)
— see async_sgd/device_optimizer.py.

Hyperparameters that are constant for a run (lr, betas, eps) are
compile-time constants baked into the kernel; Adam's per-step bias
corrections change every update, so they enter as SMEM scalars — zero
recompiles across steps.

Arrays are processed as (rows, 128) tiles (padded as needed).  On non-TPU
backends kernels run in interpret mode so the same code path is tested on
CPU.
"""

from __future__ import annotations

import functools
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SUBLANE = 8


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _sgd_kernel(p_ref, g_ref, out_ref, *, lr: float):
    out_ref[:] = p_ref[:] - lr * g_ref[:]


def _momentum_kernel(p_ref, g_ref, vel_ref, p_out, vel_out, *, lr: float,
                     mu: float):
    v_new = mu * vel_ref[:] + g_ref[:]
    vel_out[:] = v_new
    p_out[:] = p_ref[:] - lr * v_new


def _adam_kernel(bc_ref, p_ref, g_ref, m_ref, v_ref, p_out, m_out, v_out, *,
                 lr: float, b1: float, b2: float, eps: float):
    # bc_ref (SMEM) holds the per-step bias corrections [1-b1^t, 1-b2^t] so
    # the kernel compiles once per shape, not once per step.
    bc1, bc2 = bc_ref[0], bc_ref[1]
    g = g_ref[:]
    m_new = b1 * m_ref[:] + (1.0 - b1) * g
    v_new = b2 * v_ref[:] + (1.0 - b2) * g * g
    m_out[:] = m_new
    v_out[:] = v_new
    p_out[:] = p_ref[:] - lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)


def _as_tiles(arr: jax.Array) -> tuple[jax.Array, int]:
    """Flatten + pad to a (rows, LANE) float32 tile layout."""
    flat = arr.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    rows = -(-n // LANE)
    rows = -(-rows // SUBLANE) * SUBLANE  # round rows to sublane multiple
    padded = jnp.zeros((rows * LANE,), jnp.float32).at[:n].set(flat)
    return padded.reshape(rows, LANE), n


def _from_tiles(tiles: jax.Array, n: int, shape, dtype) -> jax.Array:
    return tiles.reshape(-1)[:n].reshape(shape).astype(dtype)


def _run(kernel, arrays: list[jax.Array], num_outputs: int,
         interpret: bool, scalars: jax.Array | None = None) -> list[jax.Array]:
    rows = arrays[0].shape[0]
    block = pl.BlockSpec((rows, LANE), lambda: (0, 0))
    in_specs = [block] * len(arrays)
    operands = list(arrays)
    if scalars is not None:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        operands = [scalars] + operands
    out = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((rows, LANE), jnp.float32)] * num_outputs,
        in_specs=in_specs,
        out_specs=[block] * num_outputs,
        interpret=interpret,
    )(*operands)
    return list(out)


def fused_sgd(params: Mapping[str, jax.Array],
              grads: Mapping[str, jax.Array], lr: float,
              interpret: bool | None = None) -> dict[str, jax.Array]:
    """param <- param - lr * grad, one fused pass per tensor."""
    interpret = _interpret_default() if interpret is None else interpret
    kernel = functools.partial(_sgd_kernel, lr=float(lr))
    out = {}
    for name, p in params.items():
        if name not in grads:
            out[name] = p
            continue
        tiles_p, n = _as_tiles(p)
        tiles_g, _ = _as_tiles(grads[name])
        (res,) = _run(kernel, [tiles_p, tiles_g], 1, interpret)
        out[name] = _from_tiles(res, n, np.shape(p), p.dtype)
    return out


def fused_momentum(params: Mapping[str, jax.Array],
                   grads: Mapping[str, jax.Array],
                   velocity: Mapping[str, jax.Array], lr: float,
                   mu: float = 0.9, interpret: bool | None = None):
    """Fused momentum SGD: returns (new_params, new_velocity)."""
    interpret = _interpret_default() if interpret is None else interpret
    kernel = functools.partial(_momentum_kernel, lr=float(lr), mu=float(mu))
    new_p, new_v = {}, {}
    for name, p in params.items():
        if name not in grads:
            new_p[name], new_v[name] = p, velocity.get(name)
            continue
        tiles = [_as_tiles(x) for x in (p, grads[name], velocity[name])]
        n = tiles[0][1]
        res = _run(kernel, [t for t, _ in tiles], 2, interpret)
        new_p[name] = _from_tiles(res[0], n, np.shape(p), p.dtype)
        new_v[name] = _from_tiles(res[1], n, np.shape(p), jnp.float32)
    return new_p, new_v


def fused_adam(params: Mapping[str, jax.Array],
               grads: Mapping[str, jax.Array],
               m: Mapping[str, jax.Array], v: Mapping[str, jax.Array],
               step: int | jax.Array, lr: float = 1e-3, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               interpret: bool | None = None):
    """Fused Adam: returns (new_params, new_m, new_v).  ``step`` (1-based)
    may be a Python int or a traced scalar — bias corrections enter the
    kernel as SMEM data, so stepping never recompiles."""
    interpret = _interpret_default() if interpret is None else interpret
    kernel = functools.partial(_adam_kernel, lr=float(lr), b1=float(b1),
                               b2=float(b2), eps=float(eps))
    step_f = jnp.asarray(step, jnp.float32)
    bc = jnp.stack([1.0 - jnp.float32(b1) ** step_f,
                    1.0 - jnp.float32(b2) ** step_f])
    new_p, new_m, new_v = {}, {}, {}
    for name, p in params.items():
        if name not in grads:
            new_p[name], new_m[name], new_v[name] = p, m.get(name), v.get(name)
            continue
        tiles = [_as_tiles(x) for x in (p, grads[name], m[name], v[name])]
        n = tiles[0][1]
        res = _run(kernel, [t for t, _ in tiles], 3, interpret, scalars=bc)
        new_p[name] = _from_tiles(res[0], n, np.shape(p), p.dtype)
        new_m[name] = _from_tiles(res[1], n, np.shape(p), jnp.float32)
        new_v[name] = _from_tiles(res[2], n, np.shape(p), jnp.float32)
    return new_p, new_m, new_v
