"""Pallas TPU flash attention (causal, forward) with custom VJP.

Blockwise attention computed entirely in VMEM with online softmax — the
single-device analogue of ring attention (ops/ring_attention.py): same
accumulation math, but blocks stream from HBM instead of rotating over ICI.
Grid: (batch*heads, q-blocks); inner fori_loop walks K/V blocks up to the
causal frontier, so the wasted upper-triangle work of the dense einsum path
is skipped entirely.

Backward currently recomputes dense attention under the standard JAX VJP
(O(S^2) memory in the backward only); a blockwise backward kernel is the
known next step.  On non-TPU backends the kernel runs in interpret mode, so
tests exercise identical code paths on CPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int,
                      block_k: int, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [block_q, D]
    d = q.shape[-1]
    q_start = qi * block_q
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    # walk K/V blocks only up to the causal frontier
    num_kb = (q_start + block_q + block_k - 1) // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = q_pos >= k_pos                          # [block_q, block_k]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, block_q: int,
               block_k: int, interpret: bool) -> jax.Array:
    """q,k,v: [BH, S, D] -> [BH, S, D]."""
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q,
                               block_k=block_k, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


def _dense_reference(q, k, v):
    """Dense causal attention used by the VJP backward (recompute)."""
    d = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    s_q, s_k = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_))
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, block_q, block_k, interpret), (q, k, v)


def _flash_vjp_bwd(block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(_dense_reference, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Causal flash attention, [B, S, H, D] -> [B, S, H, D] (drop-in for
    models.transformer.causal_attention)."""
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must divide by blocks "
                         f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def fold(x):  # [B,S,H,D] -> [B*H, S, D]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)

    out = _flash(fold(q), fold(k), fold(v), block_q, block_k, interpret)
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))
