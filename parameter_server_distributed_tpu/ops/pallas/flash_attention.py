"""Pallas TPU flash attention (causal) — blockwise forward AND backward.

Blockwise attention computed entirely in VMEM with online softmax — the
single-device analogue of ring attention (ops/ring_attention.py): same
accumulation math, but blocks stream from HBM instead of rotating over ICI.

All kernels stream K/V (or Q/dO) through the innermost grid dimension, so
VMEM residency per step is O(block^2) regardless of sequence length — no
full-sequence tensor is ever resident.  Running state (online-softmax
m/l/acc, grad accumulators) lives in f32 VMEM scratch that persists across
the sequential TPU grid; outputs are written once in the stream's final
step, in the input dtype.  Blocks entirely outside the causal triangle are skipped twice
over: `pl.when` skips the compute, and the streaming index_map CLAMPS the
block index to the causal frontier so consecutive out-of-range steps
revisit the same resident block and trigger no HBM DMA — block fetch count
matches the old per-kernel fori_loop frontier exactly.

Backward is the standard two-kernel flash decomposition: the forward saves
only O and the per-row logsumexp (O(S) residuals, not the O(S^2) attention
matrix), probabilities are recomputed blockwise from them (the
softmax-jacobian delta row term is recomputed in-kernel from O/dO rather
than materialized in HBM):

- dQ kernel: grid (BH, q-blocks, k-blocks), K/V streaming innermost;
- dK/dV kernel: grid (BH, k-blocks, q-blocks), Q/dO streaming innermost.

On non-TPU backends the kernels run in interpret mode, so tests exercise
identical code paths on CPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _iota_pos(start, rows: int, cols: int, axis: int):
    return start + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), axis)


def _kv_frontier_spec(block: int, block_q: int, block_k: int, d: int,
                      bps: int):
    """BlockSpec for a K/V operand streamed over inner grid dim j, with the
    block index clamped to the causal frontier of q block i: steps past the
    frontier revisit the resident block (no DMA) and `pl.when` skips their
    compute.

    ``bps`` = q blocks per sequence SEGMENT: under the GQA fold
    (:func:`flash_attention_gqa`) the q-rows axis is G segments of S rows
    sharing one K/V sequence, so the frontier depends on i's position
    WITHIN its segment (i % bps), not on i itself.  bps == total q blocks
    reduces to the plain single-segment layout."""
    def clamp(i, j):
        i_pos = jax.lax.rem(i, bps)
        return jnp.minimum(j, ((i_pos + 1) * block_q - 1) // block_k)

    return pl.BlockSpec((1, block, d), lambda b, i, j: (b, clamp(i, j), 0))


def _q_frontier_spec(block: int, block_q: int, block_k: int, *,
                     bps: int, d: int | None = None):
    """BlockSpec for a Q/dO operand streamed over inner grid dim j in the
    dK/dV kernel: indices before this k block's first attending q block are
    clamped up to it — per SEGMENT under the GQA fold (the clamp floor
    repeats every ``bps`` q blocks, so within-segment pre-frontier steps
    revisit the resident block while segment boundaries restart the
    stream).  d=None selects the lane-major per-row layout
    (lse: (BH, 1, S) blocked (1, 1, block), see _flash_fwd)."""
    def clamp(i, j):
        j_seg = jax.lax.rem(j, bps)
        seg = j // bps
        return seg * bps + jnp.maximum(j_seg, (i * block_k) // block_q)

    if d is None:
        return pl.BlockSpec((1, 1, block), lambda b, i, j: (b, 0, clamp(i, j)))
    return pl.BlockSpec((1, block, d), lambda b, i, j: (b, clamp(i, j), 0))


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                      l_ref, *, block_q: int, block_k: int, scale: float,
                      bps: int):
    qi, kj = pl.program_id(1), pl.program_id(2)
    # q position is segment-relative: under the GQA fold the q-rows axis
    # is G segments of S rows sharing one K/V sequence (bps blocks each)
    q_start, k_start = jax.lax.rem(qi, bps) * block_q, kj * block_k

    @pl.when(kj == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(k_start < q_start + block_q)  # block touches causal triangle
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # [block_q, D]
        k = k_ref[0].astype(jnp.float32)               # [block_k, D]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = (_iota_pos(q_start, block_q, 1, 0)
                >= _iota_pos(k_start, 1, block_k, 1))
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[...]                            # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # lse rows live along lanes in HBM (see _flash_fwd layout note)
        lse_ref[0] = (m_ref[...] + jnp.log(l)).T      # [1, block_q]


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, block_q: int,
               block_k: int, interpret: bool,
               bps: int = 0) -> tuple[jax.Array, jax.Array]:
    """q,k,v: [BH, S, D] -> (o [BH, S, D], lse [BH, 1, S]).

    lse layout: one logsumexp per q row, stored LANE-major as (BH, 1, S)
    and blocked (1, 1, block_q).  The naive (BH, S) array blocked
    (1, block_q) violates Mosaic's last-two-dims tiling rule, and the
    sublane-major (BH, S, 1) alternative satisfies it but lane-pads 1->128
    (a 128x HBM expansion — 2 GB at batch 256).  Lane-major costs one
    (block_q, 1)->(1, block_q) transpose per q-block finalize and pads
    only sublanes (1->8)."""
    bh, s, d = q.shape
    sk = k.shape[1]          # K/V sequence (= s unless GQA-folded)
    bps = bps or s // block_q
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_flash_fwd_kernel, block_q=block_q,
                               block_k=block_k, scale=scale, bps=bps)
    qblk = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    qrow = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    kblk = _kv_frontier_spec(block_k, block_q, block_k, d, bps)
    o, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((bh, s, d), q.dtype),      # o
                   jax.ShapeDtypeStruct((bh, 1, s), jnp.float32)],  # lse
        grid=(bh, s // block_q, sk // block_k),
        in_specs=[qblk, kblk, kblk],
        out_specs=[qblk, qrow],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),   # acc
                        pltpu.VMEM((block_q, 1), jnp.float32),   # m
                        pltpu.VMEM((block_q, 1), jnp.float32)],  # l
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, g_ref, lse_ref,
                         dq_ref, acc_ref, delta_ref, *, block_q: int,
                         block_k: int, scale: float, bps: int):
    """dQ for one q block, K/V streaming over the inner grid dimension.
    ds = p * (dp - delta); dq = scale * ds @ K.  Accumulates in f32 VMEM
    scratch and writes the (possibly bf16) output once at stream end —
    an f32 output array would double the HBM footprint (and pad 2x when
    D=64).  delta (softmax-jacobian row correction sum_d g*o) is computed
    here from the resident o/g blocks rather than materialized in HBM."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    q_start, k_start = jax.lax.rem(qi, bps) * block_q, kj * block_k

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        delta_ref[...] = jnp.sum(
            g_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
            axis=-1, keepdims=True)

    @pl.when(k_start < q_start + block_q)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        lse = lse_ref[0].T                             # [block_q, 1]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        mask = (_iota_pos(q_start, block_q, 1, 0)
                >= _iota_pos(k_start, 1, block_k, 1))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[...])
        acc_ref[...] += jnp.dot(ds, k,
                                preferred_element_type=jnp.float32) * scale

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, g_ref, lse_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                          block_k: int, scale: float, bps: int):
    """dK/dV for one k block, Q/dO streaming over the inner grid dimension.
    dv = p^T @ dO; dk = scale * ds^T @ Q.  Same scratch-accumulate /
    write-once layout as the dQ kernel; delta is recomputed per streamed
    q block (one [block_q, D] elementwise reduce — cheap next to the four
    matmuls)."""
    ki, qj = pl.program_id(1), pl.program_id(2)
    k_start = ki * block_k
    q_start = jax.lax.rem(qj, bps) * block_q

    @pl.when(qj == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(q_start + block_q > k_start)  # q block reaches this k block
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        lse = lse_ref[0].T                             # [block_q, 1]
        delta = jnp.sum(
            g * o_ref[0].astype(jnp.float32), axis=-1, keepdims=True)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        mask = (_iota_pos(q_start, block_q, 1, 0)
                >= _iota_pos(k_start, 1, block_k, 1))
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)   # [block_q, block_k]
        dv_acc[...] += jnp.dot(p.T, g, preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[...] += jnp.dot(ds.T, q,
                               preferred_element_type=jnp.float32) * scale

    @pl.when(qj == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, block_q: int, block_k: int,
               interpret: bool, bps: int = 0):
    bh, s, d = q.shape
    sk = k.shape[1]          # K/V sequence (= s unless GQA-folded)
    bps = bps or s // block_q
    scale = 1.0 / math.sqrt(d)

    qblk = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    qrow = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    kblk = _kv_frontier_spec(block_k, block_q, block_k, d, bps)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, scale=scale, bps=bps),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        grid=(bh, s // block_q, sk // block_k),
        in_specs=[qblk, kblk, kblk, qblk, qblk, qrow],
        out_specs=qblk,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=interpret,
    )(q, k, v, o, g, lse)

    # streaming roles swap: k blocks are the outer (revisited) dimension;
    # under the GQA fold every k block streams ALL G segments' q blocks,
    # so dK/dV come back kv_heads-sized with the group sum built in
    kout = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    qstream = _q_frontier_spec(block_q, block_q, block_k, bps=bps, d=d)
    qstream_row = _q_frontier_spec(block_q, block_q, block_k, bps=bps)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, scale=scale, bps=bps),
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
        grid=(bh, sk // block_k, s // block_q),
        in_specs=[qstream, kout, kout, qstream, qstream, qstream_row],
        out_specs=[kout, kout],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, o, g, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, block_q, block_k, interpret, bps=0):
    o, _ = _flash_fwd(q, k, v, block_q, block_k, interpret, bps)
    return o


def _flash_vjp_fwd(q, k, v, block_q, block_k, interpret, bps=0):
    o, lse = _flash_fwd(q, k, v, block_q, block_k, interpret, bps)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(block_q, block_k, interpret, bps, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_bwd(q, k, v, o, lse, g, block_q, block_k, interpret, bps)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Causal flash attention, [B, S, H, D] -> [B, S, H, D] (drop-in for
    models.transformer.causal_attention)."""
    b, s, h, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must divide by blocks "
                         f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def fold(x):  # [B,S,H,D] -> [B*H, S, D]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)

    out = _flash(fold(q), fold(k), fold(v), block_q, block_k, interpret)
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))


def flash_attention_gqa(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_q: int = 128, block_k: int = 128,
                        interpret: bool | None = None) -> jax.Array:
    """Causal flash attention with UNexpanded GQA K/V: q [B, S, H, D],
    k/v [B, S, KV, D] -> [B, S, H, D].

    Instead of repeating K/V up to H heads (G x the HBM capacity and
    expand-materialization traffic of :func:`flash_attention` after
    expand_gqa), the G query heads of each kv head fold into the q-rows
    axis: q becomes [B*KV, G*S, D] against k/v [B*KV, S, D].  The kernels
    treat the folded axis as G causal SEGMENTS sharing one K/V sequence
    (segment-relative positions + frontier clamps, ``bps`` = blocks per
    segment), and the dK/dV kernel streams all G segments' q blocks per k
    block — so dK/dV come back kv_heads-sized with the group reduction
    built in, never materializing H-sized K/V gradients."""
    b, s, h, d = q.shape
    kv = k.shape[2]
    if h % kv:
        raise ValueError(f"query heads {h} must divide by kv heads {kv}")
    groups = h // kv
    if groups == 1:
        return flash_attention(q, k, v, block_q, block_k, interpret)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must divide by blocks "
                         f"({block_q}, {block_k})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # head h = kv_head * G + group (repeat_kv convention)
    qf = jnp.transpose(q.reshape(b, s, kv, groups, d),
                       (0, 2, 3, 1, 4)).reshape(b * kv, groups * s, d)

    def fold_kv(x):  # [B,S,KV,D] -> [B*KV, S, D]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * kv, s, d)

    out = _flash(qf, fold_kv(k), fold_kv(v), block_q, block_k, interpret,
                 s // block_q)
    out = out.reshape(b, kv, groups, s, d)
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s, h, d)
