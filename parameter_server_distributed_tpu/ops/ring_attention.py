"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context training shards the sequence axis across the mesh's ``seq``
axis.  Causal attention then needs cross-device K/V:

- **Ring attention** (`make_ring_attention`): K/V blocks rotate around the
  ring via `ppermute` while each device accumulates its queries' output
  with an online (flash-style) softmax — O(seq/N) activation memory per
  device and compute overlapped with ICI transfers.  The blockwise-
  parallel-transformer / ring-attention construction, in shard_map.
- **Ulysses all-to-all** (`make_ulysses_attention`): `all_to_all` swaps the
  sharded axis from sequence to heads, each device runs dense causal
  attention on the full sequence for its head subset, then swaps back.
  Cheaper at moderate sequence lengths, needs heads % seq_axis == 0.

Both return an ``attention_fn(q, k, v) -> out`` with the same signature as
`models.transformer.causal_attention` ([B, S, H, D] -> [B, S, H, D]), so the
Transformer takes them as drop-in `attention_fn`.  K/V may arrive with the
GQA kv_heads-sized head axis: the ring rotates and Ulysses all-to-alls the
SMALL unexpanded tensors (n_heads/kv_heads fewer bytes on ICI) and expands
only at the math.  There is no reference analogue — the reference has no
model, no sequence axis (SURVEY.md §5); this is required TPU-native scale
capability.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # avoid true -inf: exp/where arithmetic stays NaN-free


def _prepare_gqa_kv(q, k, v, n_tp: int):
    """models.transformer.prepare_gqa_kv, imported lazily (the transformer
    module is the single home for the GQA-vs-tensor-axis rule)."""
    from ..models.transformer import prepare_gqa_kv

    return prepare_gqa_kv(q, k, v, n_tp)


def _block_attention_update(q32, k_blk, v_blk, q_pos, k_pos, m, l, acc):
    """One online-softmax accumulation step over a K/V block.

    q32 [B,H,Sq,D] f32; k_blk/v_blk [B,Sk,H,D] or the GQA [B,Sk,KV,D]
    (expanded here — the ring rotates the small unexpanded tensors);
    m,l [B,H,Sq]; acc [B,H,Sq,D].
    """
    d = q32.shape[-1]
    groups = q32.shape[1] // k_blk.shape[2]
    if groups > 1:
        k_blk = jnp.repeat(k_blk, groups, axis=2)
        v_blk = jnp.repeat(v_blk, groups, axis=2)
    k32 = k_blk.astype(jnp.float32)
    v32 = v_blk.astype(jnp.float32)
    scores = jnp.einsum("bhqd,bkhd->bhqk", q32, k32) / math.sqrt(d)
    mask = q_pos[:, None] >= k_pos[None, :]           # causal [Sq, Sk]
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    s_max = jnp.max(scores, axis=-1)                   # [B,H,Sq]
    m_new = jnp.maximum(m, s_max)
    # rows with no visible keys yet keep m == NEG_INF; exp underflows to 0
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    alpha = jnp.exp(m - m_new)                         # [B,H,Sq]
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v32)
    return m_new, l_new, acc_new


def _finalize(acc, l):
    out = acc / jnp.maximum(l, 1e-30)[..., None]       # [B,H,Sq,D]
    return jnp.transpose(out, (0, 2, 1, 3))            # -> [B,Sq,H,D]


def make_ring_attention(mesh: Mesh, seq_axis: str = "seq",
                        batch_axes: tuple[str, ...] = ("data", "fsdp"),
                        head_axis: str = "tensor"):
    """Causal ring attention over ``mesh``'s sequence axis."""
    n = mesh.shape[seq_axis]
    heads_spec = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    spec = P(batch_axes, seq_axis, heads_spec, None)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # remat each block update: the [B,H,Sq,Sk] score tile is recomputed in
    # the backward pass instead of saved — per-step backward residuals
    # shrink to the O(Sq*D) carries, the whole point of ring attention's
    # O(S/N) activation-memory claim at long context
    block_update = jax.checkpoint(_block_attention_update)
    n_tp = mesh.shape.get(head_axis, 1)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def ring(q, k, v):
        b, s_loc, h, d = q.shape
        my = jax.lax.axis_index(seq_axis)
        q32 = jnp.transpose(q.astype(jnp.float32), (0, 2, 1, 3))  # [B,H,Sq,D]
        q_pos = my * s_loc + jnp.arange(s_loc)
        m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
        l = jnp.zeros((b, h, s_loc), jnp.float32)
        acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
        k_cur, v_cur = k, v
        for step in range(n):
            src = (my - step) % n                      # origin of k_cur block
            k_pos = src * s_loc + jnp.arange(s_loc)
            # blocks from future shards (src > my) are entirely above the
            # causal diagonal: skip their update (the rotation must still
            # happen so later steps see the right block).  Saves ~half the
            # attention FLOPs across the ring for causal LM training.
            m, l, acc = jax.lax.cond(
                src <= my,
                lambda ops: block_update(q32, *ops, q_pos, k_pos,
                                         m, l, acc),
                lambda ops: (m, l, acc),
                (k_cur, v_cur))
            if step < n - 1:
                k_cur = jax.lax.ppermute(k_cur, seq_axis, perm)
                v_cur = jax.lax.ppermute(v_cur, seq_axis, perm)
        return _finalize(acc, l).astype(q.dtype)

    def ring_gqa(q, k, v):
        k, v = _prepare_gqa_kv(q, k, v, n_tp)
        return ring(q, k, v)

    return ring_gqa


def make_ulysses_attention(mesh: Mesh, seq_axis: str = "seq",
                           batch_axes: tuple[str, ...] = ("data", "fsdp"),
                           head_axis: str = "tensor",
                           inner=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism: swap the
    sharded axis seq -> heads, run causal attention over the full
    sequence, swap back.  Heads (after any tensor sharding) must divide by
    the seq-axis size.

    ``inner`` is the per-device full-sequence attention kernel (default
    dense einsum).  After the gather each device holds [B, S, H/n, D] at
    aligned positions — exactly the pallas flash kernel's contract — so
    passing ``flash_attention_auto`` (the ``ulysses_flash`` CLI choice)
    runs the O(block^2)-VMEM kernel on the full sequence per head shard."""
    if inner is None:
        from ..models.transformer import causal_attention
        inner = causal_attention

    n = mesh.shape[seq_axis]
    n_tp = mesh.shape.get(head_axis, 1)
    heads_spec = head_axis if n_tp > 1 else None
    spec = P(batch_axes, seq_axis, heads_spec, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def ulysses(q, k, v):
        def gather_seq(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
            return jax.lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def scatter_seq(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
            return jax.lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        # GQA: all-to-all the small kv_heads-sized K/V when kv_heads
        # divides the seq axis (groups/n fewer bytes on the wire) and let
        # the inner kernel expand; otherwise expand first (correct for
        # any head count, at the old expanded-transfer cost)
        if k.shape[2] % n == 0:
            out = inner(gather_seq(q), gather_seq(k), gather_seq(v))
        else:
            from ..models.transformer import expand_gqa
            ke, ve = expand_gqa(q, k, v)
            out = inner(gather_seq(q), gather_seq(ke), gather_seq(ve))
        return scatter_seq(out)

    def ulysses_gqa(q, k, v):
        k, v = _prepare_gqa_kv(q, k, v, n_tp)
        return ulysses(q, k, v)

    return ulysses_gqa
