"""Worker runtime: discovery, registration, heartbeats, train loop.

Re-design of the reference `Worker` (reference: src/worker.cpp,
include/worker.h:25-33).  Protocol behavior preserved:

- discovery: ask the coordinator for the PS address, then register
  (reference: src/worker.cpp:108-122, 141-186)
- `query_with_retry`: up to 5 attempts, exponential backoff 100 ms * 2^n
  (reference: src/worker.cpp:129-139)
- heartbeat thread every 5 s reporting WorkerStatus
  (reference: src/worker.cpp:231-238)
- run_iteration: pull -> compute -> push -> poll sync status every 50 ms up
  to 200 polls, 3 outer retries (reference: src/worker.cpp:331-406)
- `reconnect()` re-runs discovery+registration (reference: src/worker.cpp:124-127)
- checkpoint restore request at startup (reference: src/worker.cpp:289-314)

Departures:

- gradients come from a real jitted model step (Trainer), not the 0.01 stub;
- when the PS holds no parameters yet, the worker seeds it with a
  deterministic model init instead of fabricating a dummy 10x10 tensor
  (reference: src/worker.cpp:346-353);
- one persistent channel per peer instead of a fresh channel per call.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Iterator

import grpc
import numpy as np

from ..config import WorkerConfig
from ..core.tensor import TensorStore, from_wire, to_wire
from ..obs import stats as obs_stats
from ..obs import trace as obs_trace
from ..obs.export import snapshot_blob
from ..rpc import messages as m
from ..rpc.data_plane import PSClient
from ..rpc.service import RpcClient
from ..utils.metrics import MetricsLogger, StepTimer

log = logging.getLogger("pst.worker")


class WorkerError(RuntimeError):
    pass


class Worker:
    def __init__(self, config: WorkerConfig, trainer,
                 batches: Iterator, start_heartbeat: bool = True):
        if config.wire_dtype not in m.WIRE_DTYPE_NAMES:
            raise ValueError(
                f"unknown wire_dtype {config.wire_dtype!r}; "
                f"options: {sorted(m.WIRE_DTYPE_NAMES)}")
        if not 0.0 < config.topk_density <= 1.0:
            # a percent-style typo (--topk-density=2) would otherwise
            # emit a k larger than the serialized pairs
            raise ValueError(f"topk_density must be in (0, 1], "
                             f"got {config.topk_density}")
        self.config = config
        self.trainer = trainer
        self.batches = batches
        self.status = m.WorkerStatus.IDLE
        self.iteration = -1  # last completed iteration
        self.last_loss = float("nan")
        metrics_path = os.environ.get("PSDT_METRICS_FILE") or None
        self.metrics = MetricsLogger(
            metrics_path and metrics_path.replace("%d", str(config.worker_id)))
        self.step_timer = StepTimer()
        # step-phase breakdown + retry accounting (obs registry; snapshots
        # ride heartbeats to the coordinator — obs/export.py)
        self._obs_phase = {name: obs_stats.histogram(f"worker.{name}_s")
                           for name in ("step", "data", "pull", "compute",
                                        "push", "barrier_wait")}
        self._obs_retries = obs_stats.counter("rpc.client.retries")
        # uncompressed f32 size of pushed gradients: the denominator of
        # the wire-compression ratio in the status rollup
        self._obs_push_payload = obs_stats.counter(
            "rpc.client.push.payload_bytes")
        self._coordinator = RpcClient(config.coordinator_address,
                                      m.COORDINATOR_SERVICE, m.COORDINATOR_METHODS)
        self._ps: RpcClient | None = None
        self._ps_address: str | None = None
        self._total_workers = 0
        self._requested_wire_dtype = m.WIRE_DTYPE_NAMES[config.wire_dtype]
        self._reset_wire_negotiation()
        self.last_bootstrap = False  # True iff the last iteration seeded the PS
        self._stop = threading.Event()
        self._heartbeat_thread: threading.Thread | None = None
        if start_heartbeat:
            self._heartbeat_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"worker-{config.worker_id}-heartbeat")
            self._heartbeat_thread.start()

    # ------------------------------------------------------------ lifecycle
    def initialize(self) -> None:
        """Discover PS + register (reference: src/worker.cpp:108-122)."""
        self._discover_parameter_server()
        self._register()

    def reconnect(self) -> None:
        """reference: src/worker.cpp:124-127."""
        self.initialize()

    def shutdown(self) -> None:
        self._stop.set()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=2.0)
        # one parting heartbeat: runs shorter than heartbeat_period_s
        # would otherwise never deliver a metric snapshot, and even long
        # runs would leave the coordinator's rollup missing the tail
        # since the last periodic beat (obs/export.py piggyback)
        self.send_heartbeat()
        self._coordinator.close()
        if self._ps is not None:
            self._ps.close()

    # ------------------------------------------------------------ discovery
    def _discover_parameter_server(self) -> None:
        resp = self.query_with_retry(
            lambda: self._coordinator.call("GetParameterServerAddress",
                                           m.GetPSAddressRequest(), timeout=5.0))
        self._ps_address = f"{resp.address}:{resp.port}"
        if self._ps is not None:
            self._ps.close()
        if len(resp.shards) > 1:
            # sharded store (extension field 3): fan pushes/pulls out per
            # tensor owner across all PS shards (worker/ps_shards.py)
            from .ps_shards import ShardedPSClient
            self._ps = ShardedPSClient(list(resp.shards))
            log.info("worker %d: %d PS shards at %s", self.config.worker_id,
                     len(resp.shards), list(resp.shards))
        else:
            # PSClient: chunk-stream data plane with automatic unary
            # fallback against a reference PS (rpc/data_plane.py)
            self._ps = PSClient(self._ps_address)
            log.info("worker %d: PS at %s", self.config.worker_id,
                     self._ps_address)
        self._reset_wire_negotiation()  # a new PS must re-prove packed support

    def _reset_wire_negotiation(self) -> None:
        """Packed pushes start only after the connected PS proves it honors
        the packed extension (first non-empty pull served packed).  A
        reference PS skips the extension fields entirely, so pushing packed
        at it would silently aggregate empty gradients; the replacement PS
        after a crash may not honor what the previous one did."""
        self._wire_dtype = self._requested_wire_dtype
        self._peer_packed_ok = self._wire_dtype == m.WIRE_F32
        # int8 pushes carry quantization error forward (error feedback);
        # residuals are per-PS-connection state
        self._ef_residual: dict[str, np.ndarray] = {}

    def _pull_wire_dtype(self) -> int:
        """Encoding requested for served parameters.  The lossy encodings
        (int8, topk) are for gradient pushes only — error feedback corrects
        their bias push-over-push, but repeatedly compressing the
        *parameters* on every pull would compound irrecoverable error, so
        those workers pull bf16."""
        if self._wire_dtype in (m.WIRE_INT8, m.WIRE_TOPK):
            return m.WIRE_BF16
        return self._wire_dtype

    def _register(self) -> None:
        info = m.WorkerInfo(worker_id=self.config.worker_id,
                            address=self.config.address,
                            port=self.config.port,
                            hostname=socket.gethostname())
        resp = self.query_with_retry(
            lambda: self._coordinator.call("RegisterWorker", info, timeout=5.0))
        if not resp.success:
            raise WorkerError(f"registration rejected: {resp.message}")
        self._total_workers = resp.total_workers
        log.info("worker %d registered (%d total)", self.config.worker_id,
                 resp.total_workers)

    # -------------------------------------------------------------- retries
    def query_with_retry(self, fn: Callable, attempts: int | None = None):
        """Exponential backoff wrapper (reference: src/worker.cpp:129-139)."""
        attempts = attempts or self.config.retry_max_attempts
        delay = self.config.retry_base_delay_s
        last_exc: Exception | None = None
        for attempt in range(attempts):
            try:
                return fn()
            except grpc.RpcError as exc:
                last_exc = exc
                self._obs_retries.add()
                if attempt < attempts - 1:
                    time.sleep(delay)
                    delay *= 2
        raise WorkerError(f"RPC failed after {attempts} attempts: {last_exc}")

    # ------------------------------------------------------------ heartbeat
    def _heartbeat_loop(self) -> None:
        """reference: src/worker.cpp:231-238.  Extension: if the coordinator
        no longer knows this worker (evicted after a long jit compile or a
        coordinator restart), re-register so the elastic barrier counts us
        again — the reference never calls its own reconnect()."""
        while not self._stop.wait(self.config.heartbeat_period_s):
            ok = self.send_heartbeat()
            if ok is False and self._total_workers > 0:
                log.warning("worker %d: heartbeat rejected, re-registering",
                            self.config.worker_id)
                try:
                    self._register()
                except WorkerError as exc:
                    log.warning("worker %d: re-registration failed: %s",
                                self.config.worker_id, exc)

    def send_heartbeat(self) -> bool | None:
        """True = accepted, False = coordinator rejected (unknown worker),
        None = coordinator unreachable."""
        try:
            resp = self._coordinator.call(
                "Heartbeat",
                m.HeartbeatRequest(worker_id=self.config.worker_id,
                                   status=self.status,
                                   # metric snapshot piggyback (extension
                                   # field; reference coordinators skip it)
                                   obs_snapshot=snapshot_blob(
                                       worker_id=self.config.worker_id)),
                timeout=5.0)
            return resp.success
        except grpc.RpcError:
            return None

    # ------------------------------------------------------------ data plane
    def pull_parameters(self, iteration: int) -> tuple[int, TensorStore]:
        """reference: src/worker.cpp:240-252."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/pull", iteration=iteration):
            result = self._pull_parameters(iteration)
        self._obs_phase["pull"].observe(time.perf_counter() - t0)
        return result

    def _pull_parameters(self, iteration: int) -> tuple[int, TensorStore]:
        def attempt():
            # a FRESH store per attempt: after a sharded-pull failure,
            # the other shards' fan-out threads may still be streaming
            # chunks of the FAILED attempt — they write into the old
            # dict, never into this retry's
            local: TensorStore = {}

            def convert_chunk(tensors) -> None:
                # f32 conversion per chunk AS IT ARRIVES, overlapping the
                # transport of later chunks (rpc/data_plane.py on_chunk)
                local.update(from_wire(tensors))

            resp = self._ps.pull_parameters(
                m.PullRequest(worker_id=self.config.worker_id,
                              iteration=iteration,
                              wire_dtype=self._pull_wire_dtype()),
                timeout=30.0, on_chunk=convert_chunk)
            return resp, local

        resp, store = self.query_with_retry(attempt)
        if not self._peer_packed_ok and resp.parameters:
            if any(t.packed_dtype != m.WIRE_F32 for t in resp.parameters):
                self._peer_packed_ok = True
            else:
                # Server ignored the extension (reference PS): stay on the
                # reference-compatible f32 encoding rather than pushing
                # payloads the server cannot see.
                log.warning(
                    "worker %d: PS does not support wire_dtype=%s, "
                    "falling back to f32", self.config.worker_id,
                    self.config.wire_dtype)
                self._wire_dtype = m.WIRE_F32
                self._peer_packed_ok = True
        elif self._peer_packed_ok and self._wire_dtype != m.WIRE_F32:
            # Negotiation was proven against the PREVIOUS process at this
            # address.  A PS that crashed and restarted is reached again via
            # transparent gRPC channel reconnection — never re-entering
            # _discover_parameter_server — so stale proof must be dropped
            # whenever a pull stops looking packed: an empty pull (restarted
            # PS lost its store; our next push may seed it and must not be
            # quantized) or a non-empty pull served entirely unpacked (a
            # replacement PS that ignores the extension would silently see
            # empty gradients in our packed pushes).
            if not resp.parameters or all(
                    t.packed_dtype == m.WIRE_F32 for t in resp.parameters):
                log.warning(
                    "worker %d: pull no longer packed (PS restart?), "
                    "re-negotiating wire encoding", self.config.worker_id)
                self._reset_wire_negotiation()
        return resp.iteration, store

    def push_gradients(self, iteration: int, grads: TensorStore) -> m.PushResponse:
        """reference: src/worker.cpp:254-272."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/push", iteration=iteration):
            resp = self._push_gradients(iteration, grads)
        self._obs_phase["push"].observe(time.perf_counter() - t0)
        return resp

    def _push_gradients(self, iteration: int, grads: TensorStore) -> m.PushResponse:
        self._obs_push_payload.add(
            sum(4 * int(np.asarray(g).size) for g in grads.values()))
        push_dtype = self._wire_dtype if self._peer_packed_ok else m.WIRE_F32
        new_residual = None
        if push_dtype in (m.WIRE_INT8, m.WIRE_TOPK):
            tensors, new_residual = self._compress_with_feedback(
                grads, push_dtype)
        else:
            tensors = to_wire(grads, push_dtype)
        update = m.GradientUpdate(worker_id=self.config.worker_id,
                                  iteration=iteration, gradients=tensors)
        resp = self.query_with_retry(
            lambda: self._ps.push_gradients(update, timeout=30.0))
        if new_residual is not None and resp.success:
            # commit the carried error only for pushes the PS accepted — a
            # rejected (stale) push's gradient was discarded whole, so its
            # quantization error must not leak into the next push
            self._ef_residual = new_residual
        return resp

    def _compress_with_feedback(
            self, grads: TensorStore, wire_dtype: int) -> tuple[list, dict]:
        """Lossy gradient compression with error feedback (1-bit-SGD /
        EF-SGD / Deep-Gradient-Compression style): each push sends
        compress(grad + residual) and carries the un-sent part — rounding
        error under int8, the whole non-top-k mass under topk — into the
        next push, so compression bias cancels over time instead of
        accumulating.  The residual is what the PS did NOT see: decoding
        the wire tensor gives exactly the server's view."""
        adjusted = {}
        for name, g in grads.items():
            g = np.asarray(g, np.float32)
            prev = self._ef_residual.get(name)
            adjusted[name] = g + prev if prev is not None else g
        tensors = to_wire(adjusted, wire_dtype,
                          topk_density=self.config.topk_density)
        residual = {t.name: adjusted[t.name] - t.to_array() for t in tensors}
        return tensors, residual

    def check_sync_ready(self, iteration: int) -> m.SyncStatusResponse:
        """reference: src/worker.cpp:274-287."""
        return self.query_with_retry(
            lambda: self._ps.call("CheckSyncStatus",
                                  m.SyncStatusRequest(iteration=iteration),
                                  timeout=5.0))

    _expected_names: frozenset[str] | None = None

    def _expected_param_names(self) -> frozenset[str]:
        """The model's full parameter-name set (cached) — used to detect a
        PARTIAL pull under the sharded-PS topology, where one restarted
        shard loses its partition while the others still serve theirs."""
        if self._expected_names is None:
            self._expected_names = frozenset(self.trainer.init_params(seed=0))
        return self._expected_names

    # ------------------------------------------------------------ train loop
    def run_iteration(self, iteration: int) -> float:
        """One pull -> compute -> push -> barrier cycle
        (reference: src/worker.cpp:331-406).  Returns the loss."""
        self.status = m.WorkerStatus.TRAINING
        self.step_timer.__enter__()
        self.last_bootstrap = False
        t_step = time.perf_counter()
        # the step span roots the distributed trace: the pull/push/barrier
        # client spans nest under it, and their contexts ride the RPC
        # extension field so the PS-side handler spans share its trace id
        step_span = obs_trace.span("worker/step", iteration=iteration,
                                   worker=self.config.worker_id)
        step_span.__enter__()
        try:
            _, params = self.pull_parameters(iteration)
            missing = (self._expected_param_names() - set(params)
                       if params else set())
            if not params or missing:
                # PS store empty (or, under the sharded topology, one shard
                # restarted empty — the merged pull is then PARTIAL): every
                # worker pushes the same deterministic init for the missing
                # names; the PS bootstrap rule (first aggregated payload
                # *becomes* the parameters — reference
                # src/parameter_server.cpp:78-81) then lands exactly the
                # init on the empty shard(s).  Replaces the reference's
                # dummy 10x10 fallback (src/worker.cpp:346-353).
                init = self.trainer.init_params(seed=0)
                if missing:
                    # a replacement shard must also re-prove packed support
                    # before quantized pushes resume
                    self._reset_wire_negotiation()
                    init = {name: init[name] for name in missing}
                    log.warning(
                        "worker %d: pull missing %d tensors (shard "
                        "restart?), re-seeding deterministic init",
                        self.config.worker_id, len(missing))
                else:
                    log.info("worker %d: PS empty, pushing deterministic init",
                             self.config.worker_id)
                push = self.push_gradients(iteration, init)
                if not push.success:
                    raise WorkerError(f"bootstrap push rejected: {push.message}")
                if not push.aggregation_complete:
                    self._await_barrier(iteration)
                self.iteration = iteration
                self.last_bootstrap = True
                return float("nan")

            effective_it = iteration
            for attempt in range(3):
                t0 = time.perf_counter()
                batch = next(self.batches)
                t1 = time.perf_counter()
                self._obs_phase["data"].observe(t1 - t0)
                with obs_trace.span("worker/compute", iteration=effective_it):
                    grads, loss = self.trainer.compute_gradients(params, batch)
                self._obs_phase["compute"].observe(time.perf_counter() - t1)
                self.last_loss = loss

                push = self.push_gradients(effective_it, grads)
                if push.success:
                    break
                if "stale" in push.message and attempt < 2:
                    # bounded-staleness rejection (async mode): fast-forward
                    # to the PS's current iteration, re-pull fresh params,
                    # recompute, retry — no reference analogue (its protocol
                    # is strictly synchronous)
                    log.info("worker %d: stale at iteration %d, "
                             "fast-forwarding to %d", self.config.worker_id,
                             effective_it, push.iteration)
                    effective_it = max(push.iteration, effective_it + 1)
                    _, params = self.pull_parameters(effective_it)
                    continue
                raise WorkerError(f"push rejected: {push.message}")
            if not push.aggregation_complete:
                self._await_barrier(effective_it)
            self.iteration = effective_it
            return loss
        finally:
            step_span.__exit__(None, None, None)
            self._obs_phase["step"].observe(time.perf_counter() - t_step)
            self.status = m.WorkerStatus.IDLE
            self.step_timer.__exit__()
            self.metrics.log(step=self.iteration, loss=self.last_loss,
                             step_time_s=self.step_timer.summary().get("last_s"))

    def _await_barrier(self, iteration: int) -> None:
        """Poll CheckSyncStatus: 50 ms period, <=200 polls, 3 outer retries
        (reference: src/worker.cpp:372-389)."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/barrier_wait", iteration=iteration):
            try:
                self._await_barrier_inner(iteration)
            finally:
                self._obs_phase["barrier_wait"].observe(
                    time.perf_counter() - t0)

    def _await_barrier_inner(self, iteration: int) -> None:
        for outer in range(self.config.sync_outer_retries):
            for _ in range(self.config.sync_poll_max):
                resp = self.check_sync_ready(iteration)
                if resp.ready:
                    return
                time.sleep(self.config.sync_poll_period_s)
            log.warning("worker %d: barrier timeout at iteration %d "
                        "(%d/%d received), retry %d",
                        self.config.worker_id, iteration,
                        resp.workers_received, resp.total_workers, outer + 1)
            time.sleep(0.5)
        raise WorkerError(f"barrier never completed for iteration {iteration}")

    def run(self, iterations: int | None = None) -> None:
        """Full training run (reference: src/worker_main.cpp:40-43)."""
        total = iterations if iterations is not None else self.config.iterations
        for i in range(total):
            # async fast-forwards may skip numbers; never re-push a completed
            # iteration
            it = max(i, self.iteration + 1)
            loss = self.run_iteration(it)
            log.info("worker %d iteration %d loss %.4f",
                     self.config.worker_id, it, loss)

    # ------------------------------------------------------------ checkpoint
    def load_checkpoint_from_server(self, path: str) -> bool:
        """Ask the PS to load a checkpoint into itself
        (reference: src/worker.cpp:289-314 — the worker does not keep the
        returned parameter copy)."""
        self.status = m.WorkerStatus.CHECKPOINTING
        try:
            resp = self.query_with_retry(
                lambda: self._ps.call("LoadCheckpoint",
                                      m.LoadCheckpointRequest(path=path),
                                      timeout=60.0))
            if resp.success:
                log.info("worker %d: PS restored checkpoint %s (epoch %d)",
                         self.config.worker_id, path, resp.epoch)
            else:
                log.warning("worker %d: checkpoint restore failed: %s",
                            self.config.worker_id, resp.message)
            return resp.success
        finally:
            self.status = m.WorkerStatus.IDLE
