"""Worker runtime: discovery, registration, heartbeats, train loop.

Re-design of the reference `Worker` (reference: src/worker.cpp,
include/worker.h:25-33).  Protocol behavior preserved:

- discovery: ask the coordinator for the PS address, then register
  (reference: src/worker.cpp:108-122, 141-186)
- `query_with_retry`: up to 5 attempts, exponential backoff 100 ms * 2^n
  (reference: src/worker.cpp:129-139)
- heartbeat thread every 5 s reporting WorkerStatus
  (reference: src/worker.cpp:231-238)
- run_iteration: pull -> compute -> push -> poll sync status every 50 ms up
  to 200 polls, 3 outer retries (reference: src/worker.cpp:331-406).
  Against a framework PS the whole communication tail collapses into ONE
  fused ``PushPullStream`` round (push + barrier + pull — the server
  answers the instant aggregation completes instead of being polled), the
  gradients stream out in lazily-D2H-fetched buckets
  (trainer.GradientBuckets), the returned parameters are cached for the
  next iteration's "pull", and the next batch prefetches during
  communication.  All of it degrades to the reference-shaped serial
  protocol against a reference PS (per-connection UNIMPLEMENTED fallback,
  rpc/data_plane.py).
- `reconnect()` re-runs discovery+registration (reference: src/worker.cpp:124-127)
- checkpoint restore request at startup (reference: src/worker.cpp:289-314)

Departures:

- gradients come from a real jitted model step (Trainer), not the 0.01 stub;
- when the PS holds no parameters yet, the worker seeds it with a
  deterministic model init instead of fabricating a dummy 10x10 tensor
  (reference: src/worker.cpp:346-353);
- one persistent channel per peer instead of a fresh channel per call.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import socket
import threading
import time
from typing import Callable, Iterator

import grpc
import numpy as np

from ..config import WorkerConfig
from ..core.tensor import TensorStore, from_wire, to_wire
from ..obs import flight
from ..obs import stats as obs_stats
from ..obs import trace as obs_trace
from ..obs.export import snapshot_blob
from ..rpc import messages as m
from ..rpc.data_plane import PSClient
from ..rpc.service import RpcClient
# the per-tier error-feedback stage (tiers/ef.py, ISSUE 9): the PS-leg
# residual below and the tier legs (worker→leaf, leaf→PS) are all
# instances of the same stage — one residual per compression point.
# error_feedback_enabled is re-exported here for back-compat (it lived
# in this module through PR 8).
from ..tiers.ef import ErrorFeedback, error_feedback_enabled  # noqa: F401
from ..utils.metrics import MetricsLogger, StepTimer

log = logging.getLogger("pst.worker")


class WorkerError(RuntimeError):
    pass


def _is_stale_shard_map(push) -> bool:
    """A live-reshard rejection that escaped the sharded client's own
    repartition replay (replication/messages.py STALE_SHARD_MAP) — NOT
    the bounded-staleness 'stale push' rejection of async mode."""
    from ..replication.messages import STALE_SHARD_MAP
    return STALE_SHARD_MAP in (push.message or "")


class Worker:
    def __init__(self, config: WorkerConfig, trainer,
                 batches: Iterator, start_heartbeat: bool = True):
        if config.wire_dtype not in m.WIRE_DTYPE_NAMES:
            raise ValueError(
                f"unknown wire_dtype {config.wire_dtype!r}; "
                f"options: {sorted(m.WIRE_DTYPE_NAMES)}")
        if not 0.0 < config.topk_density <= 1.0:
            # a percent-style typo (--topk-density=2) would otherwise
            # emit a k larger than the serialized pairs
            raise ValueError(f"topk_density must be in (0, 1], "
                             f"got {config.topk_density}")
        self.config = config
        self.trainer = trainer
        self.batches = batches
        self.status = m.WorkerStatus.IDLE
        self.iteration = -1  # last completed iteration
        self.last_loss = float("nan")
        metrics_path = os.environ.get("PSDT_METRICS_FILE") or None
        self.metrics = MetricsLogger(
            metrics_path and metrics_path.replace("%d", str(config.worker_id)))
        self.step_timer = StepTimer()
        # step-phase breakdown + retry accounting (obs registry; snapshots
        # ride heartbeats to the coordinator — obs/export.py).  "fused" is
        # the single push→barrier→pull round of the pipelined data plane.
        self._obs_phase = {name: obs_stats.histogram(f"worker.{name}_s")
                           for name in ("step", "data", "pull", "compute",
                                        "push", "fused", "barrier_wait")}
        self._obs_retries = obs_stats.counter("rpc.client.retries")
        # uncompressed f32 size of pushed gradients — the NUMERATOR of the
        # wire-compression ratio in the status rollup ...
        self._obs_push_payload = obs_stats.counter(
            "rpc.client.push.payload_bytes")
        # ... and the matching denominator: the bytes those tensors
        # actually encode to on the wire (int8/topk shrink it), counted
        # uniformly across the unary/stream/fused push paths
        self._obs_push_wire = obs_stats.counter(
            "rpc.client.push.wire_bytes")
        self._coordinator = RpcClient(config.coordinator_address,
                                      m.COORDINATOR_SERVICE, m.COORDINATOR_METHODS)
        self._ps: RpcClient | None = None
        self._ps_address: str | None = None
        self._total_workers = 0
        self._requested_wire_dtype = m.WIRE_DTYPE_NAMES[config.wire_dtype]
        # PS-leg error-feedback stage (see _ef_residual property below);
        # must exist before _reset_wire_negotiation resets it
        self._push_ef = ErrorFeedback()
        # hierarchical aggregation (tiers/group_client.py): built at
        # discovery when enabled and the topology supports it
        self._tier = None
        self._reset_wire_negotiation()
        self.last_bootstrap = False  # True iff the last iteration seeded the PS
        # Parameters delivered by the previous iteration's fused round —
        # they ARE what a pull at the next iteration would return, so the
        # next step skips its pull entirely.
        self._next_params: TensorStore | None = None
        # one-shot note when the fused rounds start riding the same-host
        # shared-memory transport (rpc/shm_transport.py) instead of TCP
        self._shm_noted = False
        # single-slot batch prefetch: next(self.batches) runs on this
        # thread while the worker is blocked in communication
        self._prefetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"worker-{config.worker_id}-prefetch")
        self._prefetched: concurrent.futures.Future | None = None
        self._stop = threading.Event()
        # Elastic membership (elastic/, ISSUE 13): announce join after
        # registration, poll own state at heartbeat cadence (a
        # coordinator-side `pst-ctl drain` flips it to DRAINING), and
        # announce leave at shutdown so the barrier narrows immediately
        # instead of waiting out a stale-heartbeat reap.  None until
        # discovery; a reference coordinator latches it unsupported.
        self._membership = None
        # graceful-preemption latch (SIGTERM handler / drain poll): the
        # run loop finishes the in-flight iteration, then stops
        self._drain = threading.Event()
        if flight.enabled():
            # label this process's flight ring (real multi-process runs;
            # in-process test topologies share one ring, last label wins)
            flight.set_role(f"worker:{config.worker_id}")
        self._heartbeat_thread: threading.Thread | None = None
        if start_heartbeat:
            self._heartbeat_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"worker-{config.worker_id}-heartbeat")
            self._heartbeat_thread.start()

    # ------------------------------------------------------------ lifecycle
    def initialize(self) -> None:
        """Discover PS + register (reference: src/worker.cpp:108-122)."""
        self._discover_parameter_server()
        self._register()

    def reconnect(self) -> None:
        """reference: src/worker.cpp:124-127."""
        self.initialize()

    def request_drain(self) -> None:
        """Graceful-preemption request (SIGTERM handler, or the
        coordinator's DRAINING state seen by the heartbeat poll): finish
        the in-flight iteration, then stop.  Safe from any thread."""
        if not self._drain.is_set():
            self._drain.set()
            flight.record("elastic.drain", worker=self.config.worker_id,
                          note="worker")

    @property
    def drain_requested(self) -> bool:
        return self._drain.is_set()

    def shutdown(self) -> None:
        if self._stop.is_set():
            # idempotent: drain flows (graceful preemption) shut a
            # worker down as soon as it leaves, and the owning harness
            # routinely shuts everything down again on exit — a second
            # call must not touch the already-closed channels
            return
        self._stop.set()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=2.0)
        # one parting heartbeat: runs shorter than heartbeat_period_s
        # would otherwise never deliver a metric snapshot, and even long
        # runs would leave the coordinator's rollup missing the tail
        # since the last periodic beat (obs/export.py piggyback)
        self.send_heartbeat()
        if self._membership is not None:
            # graceful deregistration: the registry drops us NOW and the
            # elastic barrier narrows at the next width refresh (the
            # membership generation bump makes that immediate) instead
            # of a 30 s stale-heartbeat reap
            try:
                self._membership.leave()
            finally:
                self._membership.close()
                self._membership = None
        self._prefetch_pool.shutdown(wait=False)
        if self._tier is not None:
            self._tier.close()
            self._tier = None
        self._coordinator.close()
        if self._ps is not None:
            self._ps.close()

    # ------------------------------------------------------------ discovery
    def _discover_parameter_server(self) -> None:
        resp = self.query_with_retry(
            lambda: self._coordinator.call("GetParameterServerAddress",
                                           m.GetPSAddressRequest(), timeout=5.0))
        self._ps_address = f"{resp.address}:{resp.port}"
        if self._ps is not None:
            self._ps.close()
        # Replication extension (replication/failover.py): fetch the
        # epoch-numbered shard map.  A reference coordinator answers
        # UNIMPLEMENTED (shard_map.supported stays False) and the worker
        # keeps the static discovery topology — no failover, exactly the
        # pre-replication behavior.
        from ..replication.failover import ShardMapClient
        shard_map = ShardMapClient(self.config.coordinator_address,
                                   worker_id=self.config.worker_id)
        has_map = shard_map.refresh()
        primaries = shard_map.primaries() if has_map else []
        if has_map and primaries and (len(primaries) > 1
                                      or shard_map.has_backups()):
            # dynamic topology: the sharded client follows promotions and
            # reshards via the map (even at one shard, for hot failover)
            from .ps_shards import ShardedPSClient
            self._ps = ShardedPSClient(primaries, shard_map=shard_map)
            log.info("worker %d: %d PS shard(s) at %s (map epoch %d, "
                     "failover %s)", self.config.worker_id, len(primaries),
                     primaries, shard_map.epoch,
                     "armed" if shard_map.has_backups() else "unarmed")
        elif len(resp.shards) > 1:
            # sharded store (extension field 3): fan pushes/pulls out per
            # tensor owner across all PS shards (worker/ps_shards.py)
            from .ps_shards import ShardedPSClient
            shard_map.close()
            self._ps = ShardedPSClient(list(resp.shards))
            log.info("worker %d: %d PS shards at %s", self.config.worker_id,
                     len(resp.shards), list(resp.shards))
        else:
            # PSClient: chunk-stream data plane with automatic unary
            # fallback against a reference PS (rpc/data_plane.py)
            shard_map.close()
            self._ps = PSClient(self._ps_address)
            log.info("worker %d: PS at %s", self.config.worker_id,
                     self._ps_address)
        self._reset_wire_negotiation()  # a new PS must re-prove packed support
        self._next_params = None  # cached params were the OLD PS's
        self._setup_tier()

    def _setup_tier(self) -> None:
        """Build the hierarchical-aggregation runtime (tiers/, ISSUE 9)
        when enabled and the topology supports it: single-PS fused data
        plane only — the sharded client owns its own fan-out weighting,
        and the tier would sit between the partitioner and the shards."""
        from ..tiers.topology import tiers_enabled

        if self._tier is not None:
            self._tier.close()
            self._tier = None
        if not (tiers_enabled(getattr(self.config, "tiers", None))
                and self.config.fused_step
                and getattr(self._ps, "supports_tiers", False)):
            return
        from ..tiers.group_client import TierClient
        trainer = self.trainer
        self._tier = TierClient(
            self.config.coordinator_address, self.config.worker_id,
            self._ps_address,
            host_id=getattr(self.config, "tier_host_id", "") or None,
            init_params_fn=(
                (lambda: trainer.init_params(seed=0))
                if trainer is not None else None),
            topk_density=self.config.topk_density,
            # forward the config tri-state: --tiers must work without
            # PSDT_TIERS exported in the worker's own environment
            enabled=getattr(self.config, "tiers", None))

    # The PS-leg residual dict, kept as an attribute-shaped view over the
    # ErrorFeedback stage for back-compat (tests and older call sites
    # poke `worker._ef_residual` directly).
    @property
    def _ef_residual(self) -> dict[str, np.ndarray]:
        return self._push_ef.residual

    @_ef_residual.setter
    def _ef_residual(self, value: dict[str, np.ndarray]) -> None:
        self._push_ef.residual = dict(value)

    def _reset_wire_negotiation(self) -> None:
        """Packed pushes start only after the connected PS proves it honors
        the packed extension (first non-empty pull served packed).  A
        reference PS skips the extension fields entirely, so pushing packed
        at it would silently aggregate empty gradients; the replacement PS
        after a crash may not honor what the previous one did."""
        self._wire_dtype = self._requested_wire_dtype
        self._peer_packed_ok = self._wire_dtype == m.WIRE_F32
        # int8 pushes carry quantization error forward (error feedback);
        # residuals are per-PS-connection state
        self._ef_residual = {}

    def _pull_wire_dtype(self) -> int:
        """Encoding requested for served parameters.  The lossy encodings
        (int8, topk) are for gradient pushes only — error feedback corrects
        their bias push-over-push, but repeatedly compressing the
        *parameters* on every pull would compound irrecoverable error, so
        those workers pull bf16."""
        if self._wire_dtype in (m.WIRE_INT8, m.WIRE_TOPK):
            return m.WIRE_BF16
        return self._wire_dtype

    def _register(self) -> None:
        info = m.WorkerInfo(worker_id=self.config.worker_id,
                            address=self.config.address,
                            port=self.config.port,
                            hostname=socket.gethostname())
        resp = self.query_with_retry(
            lambda: self._coordinator.call("RegisterWorker", info, timeout=5.0))
        if not resp.success:
            raise WorkerError(f"registration rejected: {resp.message}")
        self._total_workers = resp.total_workers
        log.info("worker %d registered (%d total)", self.config.worker_id,
                 resp.total_workers)
        self._announce_join()

    def _announce_join(self) -> None:
        """Membership join announce (elastic/, ISSUE 13): JOINING ->
        ACTIVE at the coordinator.  Builds the client lazily; a
        reference coordinator answers UNIMPLEMENTED and the client
        latches unsupported — membership stays advisory."""
        if self._membership is None:
            from ..elastic.membership import MembershipClient
            self._membership = MembershipClient(
                self.config.coordinator_address, self.config.worker_id)
        self._membership.join()

    def _poll_drain(self) -> None:
        """Heartbeat-cadence membership poll: a coordinator-side
        ``pst-ctl drain`` marked us DRAINING — latch the graceful
        preemption so the run loop stops after the in-flight
        iteration."""
        if self._membership is None or self._membership.supported is False \
                or self._drain.is_set():
            return
        from ..elastic import messages as emsg
        state = self._membership.poll_state()
        if state == emsg.MEMBER_DRAINING:
            log.warning("worker %d: coordinator requested drain",
                        self.config.worker_id)
            self.request_drain()

    # -------------------------------------------------------------- retries
    def query_with_retry(self, fn: Callable, attempts: int | None = None):
        """Exponential backoff wrapper (reference: src/worker.cpp:129-139)."""
        attempts = attempts or self.config.retry_max_attempts
        delay = self.config.retry_base_delay_s
        last_exc: Exception | None = None
        for attempt in range(attempts):
            try:
                return fn()
            except grpc.RpcError as exc:
                last_exc = exc
                self._obs_retries.add()
                if attempt < attempts - 1:
                    time.sleep(delay)
                    delay *= 2
        raise WorkerError(f"RPC failed after {attempts} attempts: {last_exc}")

    # ------------------------------------------------------------ heartbeat
    def _heartbeat_loop(self) -> None:
        """reference: src/worker.cpp:231-238.  Extension: if the coordinator
        no longer knows this worker (evicted after a long jit compile or a
        coordinator restart), re-register so the elastic barrier counts us
        again — the reference never calls its own reconnect()."""
        while not self._stop.wait(self.config.heartbeat_period_s):
            ok = self.send_heartbeat()
            self._poll_drain()
            if ok is False and self._total_workers > 0:
                log.warning("worker %d: heartbeat rejected, re-registering",
                            self.config.worker_id)
                try:
                    self._register()
                except WorkerError as exc:
                    log.warning("worker %d: re-registration failed: %s",
                                self.config.worker_id, exc)

    def send_heartbeat(self) -> bool | None:
        """True = accepted, False = coordinator rejected (unknown worker),
        None = coordinator unreachable."""
        try:
            resp = self._coordinator.call(
                "Heartbeat",
                m.HeartbeatRequest(worker_id=self.config.worker_id,
                                   status=self.status,
                                   # metric snapshot piggyback (extension
                                   # field; reference coordinators skip it)
                                   obs_snapshot=snapshot_blob(
                                       worker_id=self.config.worker_id)),
                timeout=5.0)
            return resp.success
        except grpc.RpcError:
            return None

    # ------------------------------------------------------------ data plane
    def pull_parameters(self, iteration: int) -> tuple[int, TensorStore]:
        """reference: src/worker.cpp:240-252."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/pull", iteration=iteration):
            result = self._pull_parameters(iteration)
        self._obs_phase["pull"].observe(time.perf_counter() - t0)
        return result

    def _pull_parameters(self, iteration: int) -> tuple[int, TensorStore]:
        def attempt():
            # a FRESH store per attempt: after a sharded-pull failure,
            # the other shards' fan-out threads may still be streaming
            # chunks of the FAILED attempt — they write into the old
            # dict, never into this retry's
            local: TensorStore = {}

            def convert_chunk(tensors) -> None:
                # f32 conversion per chunk AS IT ARRIVES, overlapping the
                # transport of later chunks (rpc/data_plane.py on_chunk)
                local.update(from_wire(tensors))

            # Version-aware pull (delta/, ISSUE 10): advertise the held
            # version and let the PS answer O(changed bytes).  The
            # client returns None whenever the plain protocol must run
            # (disabled, reference PS, permanent downgrade).
            delta_fn = getattr(self._ps, "delta_pull", None)
            if delta_fn is not None:
                result = delta_fn(
                    m.PullRequest(worker_id=self.config.worker_id,
                                  iteration=iteration,
                                  wire_dtype=self._pull_wire_dtype()),
                    timeout=30.0)
                if result is not None and result.store is not None:
                    return result.update, result.store
            resp = self._ps.pull_parameters(
                m.PullRequest(worker_id=self.config.worker_id,
                              iteration=iteration,
                              wire_dtype=self._pull_wire_dtype()),
                timeout=30.0, on_chunk=convert_chunk)
            return resp, local

        resp, store = self.query_with_retry(attempt)
        if resp is not None:
            # a delta-served round carries no wire tensors (resp is None)
            # and leaves the proven packed negotiation untouched
            self._note_pull_tensors(resp.parameters)
            iteration = resp.iteration if resp.iteration else iteration
        return iteration, store

    def _note_pull_tensors(self, parameters) -> None:
        """Feed one pull response's tensor metadata into the packed-wire
        negotiation.  Called on every path that receives served parameters
        (unary/streamed pull AND the fused push-pull round)."""
        if not self._peer_packed_ok and parameters:
            if any(t.packed_dtype != m.WIRE_F32 for t in parameters):
                self._peer_packed_ok = True
            else:
                # Server ignored the extension (reference PS): stay on the
                # reference-compatible f32 encoding rather than pushing
                # payloads the server cannot see.
                log.warning(
                    "worker %d: PS does not support wire_dtype=%s, "
                    "falling back to f32", self.config.worker_id,
                    self.config.wire_dtype)
                self._wire_dtype = m.WIRE_F32
                self._peer_packed_ok = True
        elif self._peer_packed_ok and self._wire_dtype != m.WIRE_F32:
            # Negotiation was proven against the PREVIOUS process at this
            # address.  A PS that crashed and restarted is reached again via
            # transparent gRPC channel reconnection — never re-entering
            # _discover_parameter_server — so stale proof must be dropped
            # whenever a pull stops looking packed: an empty pull (restarted
            # PS lost its store; our next push may seed it and must not be
            # quantized) or a non-empty pull served entirely unpacked (a
            # replacement PS that ignores the extension would silently see
            # empty gradients in our packed pushes).
            if not parameters or all(
                    t.packed_dtype == m.WIRE_F32 for t in parameters):
                log.warning(
                    "worker %d: pull no longer packed (PS restart?), "
                    "re-negotiating wire encoding", self.config.worker_id)
                self._reset_wire_negotiation()

    def push_gradients(self, iteration: int, grads: TensorStore) -> m.PushResponse:
        """reference: src/worker.cpp:254-272."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/push", iteration=iteration):
            resp = self._push_gradients(iteration, grads)
        self._obs_phase["push"].observe(time.perf_counter() - t0)
        return resp

    def _push_gradients(self, iteration: int, grads: TensorStore) -> m.PushResponse:
        # Retry invariant the PS-side streaming aggregation depends on:
        # query_with_retry replays the SAME payload (same grads, same
        # error-feedback residual — committed only after acceptance), so
        # the server's per-(worker, tensor) dedup makes a retry of a push
        # that actually landed converge to exactly one contribution
        # (core/ps_core.py first-push-wins).
        self._obs_push_payload.add(
            sum(4 * int(np.asarray(g).size) for g in grads.values()))
        push_dtype = self._wire_dtype if self._peer_packed_ok else m.WIRE_F32
        new_residual = None
        if (push_dtype in (m.WIRE_INT8, m.WIRE_TOPK)
                and error_feedback_enabled()):
            tensors, new_residual = self._compress_with_feedback(
                grads, push_dtype)
        else:
            tensors = to_wire(grads, push_dtype,
                              topk_density=self.config.topk_density)
        # actual wire footprint of the payloads (packed encodings shrink
        # it) so the --metrics compression ratio is truthful
        self._obs_push_wire.add(sum(t.encoded_size() for t in tensors))
        update = m.GradientUpdate(worker_id=self.config.worker_id,
                                  iteration=iteration, gradients=tensors)
        resp = self.query_with_retry(
            lambda: self._ps.push_gradients(update, timeout=30.0))
        if new_residual is not None and resp.success:
            # commit the carried error only for pushes the PS accepted — a
            # rejected (stale) push's gradient was discarded whole, so its
            # quantization error must not leak into the next push
            self._ef_residual = new_residual
        return resp

    def _compress_with_feedback(
            self, grads: TensorStore, wire_dtype: int) -> tuple[list, dict]:
        """Lossy gradient compression with error feedback (1-bit-SGD /
        EF-SGD / Deep-Gradient-Compression style): each push sends
        compress(grad + residual) and carries the un-sent part — rounding
        error under int8, the whole non-top-k mass under topk — into the
        next push, so compression bias cancels over time instead of
        accumulating.  The residual is what the PS did NOT see: decoding
        the wire tensor gives exactly the server's view.  Implemented on
        the shared per-tier stage (tiers/ef.py) — this is the PS-leg
        instance; the caller commits the returned carry only after the
        PS accepts the push."""
        tensors = self._push_ef.compress(
            grads, wire_dtype, topk_density=self.config.topk_density)
        return tensors, self._push_ef.pending()

    # -------------------------------------------------------- fused data plane
    def _use_fused(self) -> bool:
        return (self.config.fused_step and self._ps is not None
                and hasattr(self._ps, "push_pull"))

    def _wire_tensors(self, grads, push_dtype: int | None = None,
                      ef: ErrorFeedback | None = None):
        """Lazy wire-tensor producer for the fused push.

        ``grads``: a mapping OR a lazy ``(name, array)`` iterable
        (trainer.GradientBuckets — each re-iteration replays from its
        host-side cache).  Returns ``(tensors_fn, ef_stage)``:
        ``tensors_fn()`` yields wire tensors one by one — compression +
        error-feedback adjustment happen per tensor AS the RPC sender
        consumes it, so D2H fetch ⊕ compress ⊕ encode ⊕ transport
        pipeline per bucket.  ``ef_stage`` (non-None under a lossy
        encoding with feedback on) holds the staged residual; the caller
        ``commit()``s it only after the receiver accepts the push.

        ``push_dtype``/``ef`` default to the PS-leg negotiation and the
        PS-leg stage; the tier rounds pass their own (tiers/, ISSUE 9 —
        one residual per compression point).

        Replays are payload-identical: a retry re-reads the same gradients
        (GradientBuckets' host-side cache) against the same committed
        residual, which is what lets the receiving aggregator dedup a
        retried push per (worker, tensor) instead of double-counting it
        (core/ps_core.py first-push-wins)."""
        if push_dtype is None:
            push_dtype = (self._wire_dtype if self._peer_packed_ok
                          else m.WIRE_F32)
        compress = push_dtype in (m.WIRE_INT8, m.WIRE_TOPK)
        stage = ef if ef is not None else self._push_ef
        use_ef = compress and stage.on()
        ef_stage: ErrorFeedback | None = stage if use_ef else None

        def tensors():
            if ef_stage is not None:
                ef_stage.begin()  # a retry replays from scratch
            payload = wire = 0
            pairs = grads.items() if hasattr(grads, "items") else grads
            for name, g in pairs:
                g = np.asarray(g, np.float32)
                payload += 4 * g.size
                if compress:
                    adjusted = (ef_stage.adjust(name, g) if ef_stage
                                else g)
                    t = m.Tensor.from_array(
                        name, adjusted, wire_dtype=push_dtype,
                        topk_density=self.config.topk_density)
                    if ef_stage is not None:
                        # what the receiver did NOT see carries into the
                        # next push
                        ef_stage.stage(name, adjusted, t)
                else:
                    t = m.Tensor.from_array(name, g, wire_dtype=push_dtype)
                wire += t.encoded_size()
                yield t
            self._obs_push_payload.add(payload)
            self._obs_push_wire.add(wire)

        return tensors, ef_stage

    def _tier_push_pull(self, tier, iteration: int, grads
                        ) -> tuple[m.PushResponse, TensorStore] | None:
        """One fused round via the group's leaf aggregator (tiers/,
        ISSUE 9): same wire protocol, the peer is the elected same-host
        leaf instead of the PS — this leg usually rides the shm rings.
        Returns None when the round did not deliver (the caller replays
        the SAME iteration on the flat path; the PS's member cover and
        per-(worker, tensor) dedup make that replay exact): a soft miss
        (leaf not armed yet / leaf barrier timeout) keeps the tier for
        the next round, a transport error (leaf death) or repeated
        misses downgrade it permanently."""
        tensors_fn, ef_stage = self._wire_tensors(
            grads, push_dtype=tier.push_dtype, ef=tier.push_ef)
        local: TensorStore = {}

        def convert_chunk(chunk_tensors) -> None:
            local.update(from_wire(chunk_tensors))

        t0 = time.perf_counter()
        flight.record("fused.start", iteration=iteration,
                      worker=self.config.worker_id)
        push = params = None
        try:
            with obs_trace.span("worker/tier_fused", iteration=iteration):
                push, params = tier.client.push_pull(
                    self.config.worker_id, iteration, tensors_fn,
                    pull_wire_dtype=self._pull_wire_dtype(),
                    timeout=self.config.fused_timeout_s,
                    on_chunk=convert_chunk)
        except grpc.RpcError as exc:
            tier.downgrade(f"leaf transport error: {exc.__class__.__name__}")
            return None
        finally:
            flight.record("fused.end", iteration=iteration,
                          worker=self.config.worker_id,
                          a=int(1e6 * (time.perf_counter() - t0)),
                          b=1 if params is not None else 0)
        if push.success and params is not None:
            self._obs_phase["fused"].observe(time.perf_counter() - t0)
            tier.note_success()
            if ef_stage is not None:
                ef_stage.commit()
            # deliberately NOT fed into _note_pull_tensors: the leaf
            # proving packed support says nothing about the PS this
            # worker would push to after a downgrade
            return push, local
        if not push.success and tier.is_soft_refusal(push.message):
            tier.soft_failure((push.message or "leaf refusal")[:80])
        elif push.success:
            tier.soft_failure("leaf barrier timeout")
        else:
            tier.downgrade(f"leaf rejected push: {push.message}")
        return None

    def _fused_push_pull(self, iteration: int,
                         grads) -> tuple[m.PushResponse, TensorStore | None]:
        """One fused push→barrier→pull round.  Returns the push verdict
        plus the fresh post-aggregation parameter store, or ``None`` for
        the store when the fused round did not deliver one (reference
        server, server-side barrier timeout) — the caller then falls back
        to the serial barrier-poll + pull.

        With an active tier assignment the round rides the group's leaf
        aggregator first; any miss there falls through to the flat round
        below for the SAME iteration (``grads`` is replayable by
        contract, and the PS-side dedup absorbs overlap)."""
        tier = self._tier
        if tier is not None and tier.maybe_activate():
            result = self._tier_push_pull(tier, iteration, grads)
            if result is not None:
                return result
        tensors_fn, residual_box = self._wire_tensors(grads)

        def attempt():
            # Version-aware fused round first (delta/, ISSUE 10): one
            # PushPullDeltaStream round whose response is O(changed
            # bytes) against the client's cached pull.  None = run the
            # plain fused round (disabled, downgraded, shm-preferred);
            # a mid-round downgrade also returns None and the plain
            # replay below is exact (PS-side per-(worker,tensor) dedup).
            delta_fn = getattr(self._ps, "delta_push_pull", None)
            if delta_fn is not None:
                result = delta_fn(
                    self.config.worker_id, iteration, tensors_fn,
                    pull_wire_dtype=self._pull_wire_dtype(),
                    timeout=self.config.fused_timeout_s)
                if result is not None:
                    push = (result.push if result.push is not None
                            else m.PushResponse(success=False,
                                                message="empty fused "
                                                        "response"))
                    return push, result.update, result.store

            # fresh store per attempt, same rationale as _pull_parameters
            local: TensorStore = {}

            def convert_chunk(chunk_tensors) -> None:
                local.update(from_wire(chunk_tensors))

            push, params = self._ps.push_pull(
                self.config.worker_id, iteration, tensors_fn,
                pull_wire_dtype=self._pull_wire_dtype(),
                timeout=self.config.fused_timeout_s,
                on_chunk=convert_chunk)
            return push, params, (local if params is not None else None)

        t0 = time.perf_counter()
        flight.record("fused.start", iteration=iteration,
                      worker=self.config.worker_id)
        try:
            with obs_trace.span("worker/fused", iteration=iteration):
                push, params, store = self.query_with_retry(attempt)
        except BaseException:
            flight.record("fused.end", iteration=iteration,
                          worker=self.config.worker_id,
                          a=int(1e6 * (time.perf_counter() - t0)), b=0)
            raise
        flight.record("fused.end", iteration=iteration,
                      worker=self.config.worker_id,
                      a=int(1e6 * (time.perf_counter() - t0)),
                      b=1 if params is not None else 0)
        self._obs_phase["fused"].observe(time.perf_counter() - t0)
        if not self._shm_noted and getattr(self._ps, "shm_active", False):
            # the PSClient negotiated the same-host shared-memory rings
            # (rpc/shm_transport.py); every later fused round bypasses TCP
            self._shm_noted = True
            log.info("worker %d: fused data plane riding shared memory",
                     self.config.worker_id)
        if residual_box is not None and push.success:
            residual_box.commit()
        if store is None:
            return push, None
        if params is not None:
            # a delta-served round carries no wire tensors (params is
            # None); the proven packed negotiation stands
            self._note_pull_tensors(params.parameters)
        return push, store

    # ---------------------------------------------------------- batch stream
    def _next_batch(self):
        """The prefetched batch when one is ready, else a synchronous
        ``next()`` on the loader."""
        if self._prefetched is not None:
            fut, self._prefetched = self._prefetched, None
            return fut.result()
        return next(self.batches)

    def _start_batch_prefetch(self) -> None:
        """Kick ``next(self.batches)`` on the prefetch thread so data
        loading runs under the step's communication phase.  Single-slot:
        the iterator is only ever advanced by one party at a time."""
        if self._prefetched is None and not self._stop.is_set():
            try:
                self._prefetched = self._prefetch_pool.submit(
                    next, self.batches)
            except RuntimeError:  # pool shut down mid-run
                self._prefetched = None

    def _refresh_topology_on_partial(self) -> bool:
        """A partial pull may mean a live reshard moved tensors to shards
        this client does not know yet (not a shard restart): refresh the
        shard map if the client has one.  True when a map-backed re-pull
        is worth attempting (the topology may have changed, or the
        publish is moments away); False = no dynamic map, go re-seed."""
        refresh = getattr(self._ps, "refresh_topology", None)
        if refresh is None:
            return False
        try:
            refresh()
        except Exception:  # noqa: BLE001 — fall through to the re-seed path
            log.warning("worker %d: topology refresh failed",
                        self.config.worker_id, exc_info=True)
            return False
        shard_map = getattr(self._ps, "_shard_map", None)
        return shard_map is not None and shard_map.supported

    def check_sync_ready(self, iteration: int) -> m.SyncStatusResponse:
        """reference: src/worker.cpp:274-287."""
        return self.query_with_retry(
            lambda: self._ps.call("CheckSyncStatus",
                                  m.SyncStatusRequest(iteration=iteration),
                                  timeout=5.0))

    _expected_names: frozenset[str] | None = None

    def _expected_param_names(self) -> frozenset[str]:
        """The model's full parameter-name set (cached) — used to detect a
        PARTIAL pull under the sharded-PS topology, where one restarted
        shard loses its partition while the others still serve theirs."""
        if self._expected_names is None:
            self._expected_names = frozenset(self.trainer.init_params(seed=0))
        return self._expected_names

    def _seed_bootstrap(self, iteration: int, missing) -> float:
        """PS store empty (or, under the sharded topology, one shard
        restarted empty — the merged pull is then PARTIAL): every worker
        pushes the same deterministic init for the missing names; the PS
        bootstrap rule (first aggregated payload *becomes* the parameters
        — reference src/parameter_server.cpp:78-81) then lands exactly
        the init on the empty shard(s).  Replaces the reference's dummy
        10x10 fallback (src/worker.cpp:346-353).  Rides the plain push
        path deliberately: the fused data plane refuses to seed an empty
        store (server/ps_service.py PushPullStream)."""
        init = self.trainer.init_params(seed=0)
        if missing:
            # a replacement shard must also re-prove packed support
            # before quantized pushes resume
            self._reset_wire_negotiation()
            init = {name: init[name] for name in missing}
            log.warning(
                "worker %d: pull missing %d tensors (shard "
                "restart?), re-seeding deterministic init",
                self.config.worker_id, len(missing))
        else:
            log.info("worker %d: PS empty, pushing deterministic init",
                     self.config.worker_id)
        flight.record("boot.seed", iteration=iteration,
                      worker=self.config.worker_id, a=len(init))
        push = self.push_gradients(iteration, init)
        if not push.success:
            raise WorkerError(f"bootstrap push rejected: {push.message}")
        if not push.aggregation_complete:
            self._await_barrier(iteration)
        self.iteration = iteration
        self.last_bootstrap = True
        return float("nan")

    # ------------------------------------------------------------ train loop
    def run_freerun_iteration(self, iteration: int) -> float:
        """One free-running step (freerun/, ISSUE 16): take whatever
        parameters the previous round delivered (or pull the published
        snapshot), compute, push — and never wait.  The free-run PS
        applies every push on arrival damped by ``beta^staleness`` and
        answers it ``aggregation_complete=True`` (a version-vector
        deduped RPC retry answers success too), so there is NO barrier
        to poll and deliberately no fallback to one: a worker here is
        bounded only by its own compute plus one RPC round.  The fused
        data plane still collapses push + pull into one round, but its
        legs are independent — the response parameters are simply the
        PS's current published version, not a post-barrier promise."""
        self.status = m.WorkerStatus.TRAINING
        self.step_timer.__enter__()
        self.last_bootstrap = False
        t_step = time.perf_counter()
        step_span = obs_trace.span("worker/step", iteration=iteration,
                                   worker=self.config.worker_id)
        step_span.__enter__()
        flight.record("step.start", iteration=iteration,
                      worker=self.config.worker_id)
        try:
            params, self._next_params = self._next_params, None
            if params is None:
                _, params = self.pull_parameters(iteration)
            missing = (self._expected_param_names() - set(params)
                       if params else set())
            if not params or missing:
                # rides the plain push; the free-run PS answers it
                # complete=True so no barrier poll runs inside
                return self._seed_bootstrap(iteration, missing)

            t0 = time.perf_counter()
            batch = self._next_batch()
            t1 = time.perf_counter()
            self._obs_phase["data"].observe(t1 - t0)
            fused = self._use_fused()
            incremental = fused and hasattr(self.trainer,
                                            "compute_gradient_buckets")
            with obs_trace.span("worker/compute", iteration=iteration):
                if incremental:
                    grads = self.trainer.compute_gradient_buckets(params,
                                                                  batch)
                    loss = grads.loss
                else:
                    grads, loss = self.trainer.compute_gradients(params,
                                                                 batch)
            self._obs_phase["compute"].observe(time.perf_counter() - t1)
            self.last_loss = loss
            self._start_batch_prefetch()

            if fused:
                push, fresh = self._fused_push_pull(iteration, grads)
                if fresh is not None:
                    self._next_params = fresh
            else:
                push = self.push_gradients(iteration, grads)
            if not push.success:
                raise WorkerError(f"push rejected: {push.message}")
            self.iteration = max(self.iteration, iteration)
            return loss
        finally:
            step_span.__exit__(None, None, None)
            flight.record("step.end", iteration=iteration,
                          worker=self.config.worker_id,
                          a=int(1e6 * (time.perf_counter() - t_step)))
            self._obs_phase["step"].observe(time.perf_counter() - t_step)
            self.status = m.WorkerStatus.IDLE
            self.step_timer.__exit__()
            self.metrics.log(step=self.iteration, loss=self.last_loss,
                             step_time_s=self.step_timer.summary().get("last_s"))

    def run_iteration(self, iteration: int) -> float:
        """One synchronous training step (reference: src/worker.cpp:331-406
        is pull -> compute -> push -> 50 ms barrier polls).  Returns the
        loss.  Against a framework PS the communication tail is ONE fused
        PushPullStream round whose response both closes the barrier and
        delivers the next iteration's parameters (cached, so the next
        step's pull is free); against a reference PS every leg degrades to
        the serial unary protocol.  Under ``config.freerun`` the step is
        the barrier-free loop above instead — routed here so every
        caller (run(), the CLI main, tests) picks the mode up from the
        config alone."""
        if getattr(self.config, "freerun", False):
            return self.run_freerun_iteration(iteration)
        self.status = m.WorkerStatus.TRAINING
        self.step_timer.__enter__()
        self.last_bootstrap = False
        t_step = time.perf_counter()
        # the step span roots the distributed trace: the pull/push/barrier
        # client spans nest under it, and their contexts ride the RPC
        # extension field so the PS-side handler spans share its trace id
        step_span = obs_trace.span("worker/step", iteration=iteration,
                                   worker=self.config.worker_id)
        step_span.__enter__()
        flight.record("step.start", iteration=iteration,
                      worker=self.config.worker_id)
        try:
            params, self._next_params = self._next_params, None
            if params is None:
                _, params = self.pull_parameters(iteration)
            missing = (self._expected_param_names() - set(params)
                       if params else set())
            for _ in range(3 if missing else 0):
                # the "missing" tensors may have moved in a live reshard
                # rather than been lost: refresh the shard map and
                # re-pull (a few times — the handoff publishes the new
                # map moments after the old owner stops serving) before
                # concluding a shard restarted empty and re-seeding
                if not self._refresh_topology_on_partial():
                    break
                _, params = self.pull_parameters(iteration)
                missing = (self._expected_param_names() - set(params)
                           if params else set())
                if not missing:
                    break
                time.sleep(0.3)
            if not params or missing:
                return self._seed_bootstrap(iteration, missing)

            effective_it = iteration
            fused = self._use_fused()
            incremental = fused and hasattr(self.trainer,
                                            "compute_gradient_buckets")
            fresh: TensorStore | None = None
            for attempt in range(3):
                t0 = time.perf_counter()
                batch = self._next_batch()
                t1 = time.perf_counter()
                self._obs_phase["data"].observe(t1 - t0)
                with obs_trace.span("worker/compute", iteration=effective_it):
                    if incremental:
                        # gradients stay on device; reading .loss blocks on
                        # the jitted step (+ bucket 0's D2H) while the
                        # remaining buckets fetch lazily INSIDE the fused
                        # RPC, overlapping encode/transport per bucket
                        grads = self.trainer.compute_gradient_buckets(
                            params, batch)
                        loss = grads.loss
                    else:
                        grads, loss = self.trainer.compute_gradients(params,
                                                                     batch)
                self._obs_phase["compute"].observe(time.perf_counter() - t1)
                self.last_loss = loss
                # the next batch loads while this thread blocks on the PS
                self._start_batch_prefetch()

                if fused:
                    push, fresh = self._fused_push_pull(effective_it, grads)
                else:
                    push = self.push_gradients(effective_it, grads)
                if push.success:
                    break
                if _is_stale_shard_map(push) and attempt < 2:
                    # a live reshard outran the client's map AND the
                    # client could not refresh it (coordinator
                    # unreachable / no map support): re-discover the
                    # topology from scratch and retry the iteration
                    log.warning(
                        "worker %d: shard map stale at iteration %d and "
                        "refresh failed; re-discovering topology",
                        self.config.worker_id, effective_it)
                    self._discover_parameter_server()
                    _, params = self.pull_parameters(effective_it)
                    continue
                if ("stale" in push.message
                        and not _is_stale_shard_map(push) and attempt < 2):
                    # bounded-staleness rejection (async mode): fast-forward
                    # to the PS's current iteration, re-pull fresh params,
                    # recompute, retry — no reference analogue (its protocol
                    # is strictly synchronous)
                    log.info("worker %d: stale at iteration %d, "
                             "fast-forwarding to %d", self.config.worker_id,
                             effective_it, push.iteration)
                    effective_it = max(push.iteration, effective_it + 1)
                    _, params = self.pull_parameters(effective_it)
                    continue
                if fused and "store empty" in push.message:
                    # the PS (or one shard) restarted empty under our cached
                    # params and refused to bootstrap from a fused gradient
                    # push.  Re-pull to see what is actually missing: empty
                    # or partial -> seed the deterministic init exactly like
                    # a start-of-step detection; complete -> another worker
                    # already re-seeded, retry with fresh params.
                    log.warning(
                        "worker %d: fused push refused (PS store empty — "
                        "restart?), re-pulling to re-seed",
                        self.config.worker_id)
                    self._reset_wire_negotiation()
                    _, params = self.pull_parameters(effective_it)
                    missing = (self._expected_param_names() - set(params)
                               if params else set())
                    if not params or missing:
                        return self._seed_bootstrap(effective_it, missing)
                    if attempt < 2:
                        continue
                raise WorkerError(f"push rejected: {push.message}")
            if fresh is not None:
                # the fused response IS the next iteration's pull
                self._next_params = fresh
            elif not push.aggregation_complete:
                self._await_barrier(effective_it)
            self.iteration = effective_it
            return loss
        finally:
            step_span.__exit__(None, None, None)
            flight.record("step.end", iteration=iteration,
                          worker=self.config.worker_id,
                          a=int(1e6 * (time.perf_counter() - t_step)))
            self._obs_phase["step"].observe(time.perf_counter() - t_step)
            self.status = m.WorkerStatus.IDLE
            self.step_timer.__exit__()
            self.metrics.log(step=self.iteration, loss=self.last_loss,
                             step_time_s=self.step_timer.summary().get("last_s"))

    def _await_barrier(self, iteration: int) -> None:
        """Poll CheckSyncStatus: 50 ms period, <=200 polls, 3 outer retries
        (reference: src/worker.cpp:372-389)."""
        t0 = time.perf_counter()
        with obs_trace.span("worker/barrier_wait", iteration=iteration):
            try:
                self._await_barrier_inner(iteration)
            finally:
                self._obs_phase["barrier_wait"].observe(
                    time.perf_counter() - t0)

    def _await_barrier_inner(self, iteration: int) -> None:
        # resp survives the poll loop: with sync_poll_max == 0 no poll ever
        # runs and the progress report below must not blow up unbound
        resp: m.SyncStatusResponse | None = None
        for outer in range(self.config.sync_outer_retries):
            for _ in range(self.config.sync_poll_max):
                resp = self.check_sync_ready(iteration)
                if resp.ready:
                    return
                time.sleep(self.config.sync_poll_period_s)
            log.warning("worker %d: barrier timeout at iteration %d "
                        "(%s), retry %d",
                        self.config.worker_id, iteration,
                        self._barrier_progress(resp), outer + 1)
            time.sleep(0.5)
        raise WorkerError(f"barrier never completed for iteration "
                          f"{iteration} ({self._barrier_progress(resp)})")

    @staticmethod
    def _barrier_progress(resp: m.SyncStatusResponse | None) -> str:
        if resp is None:
            return "no status polled"
        return f"{resp.workers_received}/{resp.total_workers} received"

    def run(self, iterations: int | None = None) -> None:
        """Full training run (reference: src/worker_main.cpp:40-43).
        A drain request (SIGTERM / ``pst-ctl drain``) stops the loop
        BETWEEN iterations: the in-flight iteration completes — its
        barrier contribution is never abandoned half-streamed — and the
        caller's shutdown() deregisters so the barrier narrows."""
        total = iterations if iterations is not None else self.config.iterations
        for i in range(total):
            if self._drain.is_set():
                log.warning("worker %d: draining — stopping after "
                            "iteration %d", self.config.worker_id,
                            self.iteration)
                break
            # async fast-forwards may skip numbers; never re-push a completed
            # iteration
            it = max(i, self.iteration + 1)
            loss = self.run_iteration(it)
            log.info("worker %d iteration %d loss %.4f",
                     self.config.worker_id, it, loss)

    # ------------------------------------------------------------ checkpoint
    def load_checkpoint_from_server(self, path: str) -> bool:
        """Ask the PS to load a checkpoint into itself
        (reference: src/worker.cpp:289-314 — the worker does not keep the
        returned parameter copy)."""
        self.status = m.WorkerStatus.CHECKPOINTING
        try:
            resp = self.query_with_retry(
                lambda: self._ps.call("LoadCheckpoint",
                                      m.LoadCheckpointRequest(path=path),
                                      timeout=60.0))
            if resp.success:
                # cached params predate the restore; force a real pull
                self._next_params = None
                log.info("worker %d: PS restored checkpoint %s (epoch %d)",
                         self.config.worker_id, path, resp.epoch)
            else:
                log.warning("worker %d: checkpoint restore failed: %s",
                            self.config.worker_id, resp.message)
            return resp.success
        finally:
            self.status = m.WorkerStatus.IDLE
