"""Worker-local gradient computation — jitted, with local data parallelism.

Replaces two reference components at once:

- the gradient stub (`compute_gradients` fills 0.01 —
  reference: src/worker.cpp:316-329) becomes a real jitted
  value_and_grad of the worker's model;
- the intra-node NCCL all-reduce (`NCCLManager` +
  `aggregate_gradients_multi_gpu` — reference: src/nccl_manager.cpp:102-121,
  src/worker.cpp:409-448) becomes *sharding the batch across local devices
  inside one jitted step*: the loss is a mean over the global batch, so XLA
  inserts the cross-device reduction itself.  No manager class, no explicit
  collective, no H2D round-trips per tensor.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import TensorStore


class Trainer:
    """Jitted gradient computation for one worker process.

    ``local_devices``: devices for intra-worker data parallelism (defaults
    to all visible devices).  The batch's leading axis is sharded across
    them; parameters are replicated.
    """

    def __init__(self, model, local_devices: list | None = None):
        self.model = model
        devices = local_devices or jax.local_devices()
        self._mesh = jax.sharding.Mesh(np.array(devices), ("local",))
        self._replicated = jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec())
        self._batch_sharded = jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec("local"))

        def loss_and_grads(params, batch):
            loss, grads = jax.value_and_grad(model.loss)(params, batch)
            return loss, grads

        self._step = jax.jit(
            loss_and_grads,
            out_shardings=(self._replicated,
                           jax.tree.map(lambda _: self._replicated,
                                        {k: 0 for k in model.param_shapes()})),
        )

    @property
    def num_local_devices(self) -> int:
        return self._mesh.devices.size

    def init_params(self, seed: int = 0) -> TensorStore:
        """Deterministic init — every worker derives the identical store for
        PS bootstrap (cf. the reference's fabricated dummy 10x10 'weight'
        when the pull comes back empty — src/worker.cpp:346-353)."""
        params = self.model.init_params(seed)
        return {k: np.asarray(v, np.float32) for k, v in params.items()}

    def _shard_batch(self, batch):
        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, self._batch_sharded)
        return jax.tree.map(put, batch)

    def compute_gradients(self, params: Mapping[str, np.ndarray],
                          batch) -> tuple[TensorStore, float]:
        """params (host store) + batch -> (gradient store, loss)."""
        device_params = {
            k: jax.device_put(jnp.asarray(v), self._replicated)
            for k, v in params.items()}
        loss, grads = self._step(device_params, self._shard_batch(batch))
        host_grads = {k: np.asarray(v, np.float32) for k, v in grads.items()}
        return host_grads, float(loss)
