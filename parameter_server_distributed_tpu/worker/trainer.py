"""Worker-local gradient computation — jitted, packed, with local data
parallelism.

Replaces two reference components at once:

- the gradient stub (`compute_gradients` fills 0.01 —
  reference: src/worker.cpp:316-329) becomes a real jitted
  value_and_grad of the worker's model;
- the intra-node NCCL all-reduce (`NCCLManager` +
  `aggregate_gradients_multi_gpu` — reference: src/nccl_manager.cpp:102-121,
  src/worker.cpp:409-448) becomes *sharding the batch across local devices
  inside one jitted step*: the loss is a mean over the global batch, so XLA
  inserts the cross-device reduction itself.  No manager class, no explicit
  collective, no H2D round-trips per tensor.

Transfer discipline: the reference pays per-tensor cudaMalloc/H2D/D2H on
every iteration (src/worker.cpp:409-448).  Here the whole parameter store
crosses the host<->device boundary as ONE flat f32 buffer each way per
iteration — the jitted step unpacks it, differentiates, and repacks the
gradients with the loss piggybacked at offset 0, so a 60-tensor ResNet
costs the same two transfers as a 1-tensor MLP.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import TensorStore


class Trainer:
    """Jitted gradient computation for one worker process.

    ``local_devices``: devices for intra-worker data parallelism (defaults
    to all visible devices).  The batch's leading axis is sharded across
    them; parameters are replicated.

    ``mesh_config`` + ``rule_fn``: intra-worker MODEL parallelism — the
    worker's local chips form a full mesh (data/fsdp/tensor/...) and the
    unpacked params are sharding-constrained by ``rule_fn(mesh)`` inside
    the jitted step, so XLA partitions the forward/backward across the
    worker's chips (Megatron TP, ZeRO fsdp) while the PS protocol still
    sees one packed host store per push/pull.  The packed flat buffers at
    the host<->device boundary are themselves element-sharded over ALL
    mesh axes (padded to divisibility), so no chip ever materializes a
    full replica of the params or grads — the point of a model-parallel
    worker.  The reference's workers are strictly single-GPU-per-rank
    (src/worker.cpp); this is the TPU-native upgrade: a worker whose
    model does not fit one chip still speaks plain PS.
    """

    def __init__(self, model, local_devices: list | None = None,
                 mesh_config=None, rule_fn=None):
        self.model = model
        devices = local_devices or jax.local_devices()
        self._rule = None
        if mesh_config is not None:
            from ..parallel.mesh import (AXIS_NAMES, batch_sharding,
                                         build_mesh)

            need = mesh_config.num_devices
            if len(devices) < need:
                raise ValueError(
                    f"worker mesh {mesh_config.axis_sizes} needs {need} "
                    f"local devices, have {len(devices)}")
            self._mesh = build_mesh(mesh_config, devices=devices[:need])
            if rule_fn is not None:
                self._rule = rule_fn(self._mesh)
            # flat param/grad buffers are element-sharded across every
            # chip: 1/N of the store per chip at the boundary
            self._flat_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec(AXIS_NAMES))
            self._n_shard = need
            self._batch_sharded = batch_sharding(self._mesh)
        else:
            self._mesh = jax.sharding.Mesh(np.array(devices), ("local",))
            self._flat_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec())
            self._n_shard = 1
            self._batch_sharded = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec("local"))

        # fixed packing layout: (name, offset, size, shape, dtype), by name
        init = model.init_params(0)
        self._layout = []
        offset = 0
        for name in sorted(init):
            shape = tuple(np.shape(init[name]))
            size = math.prod(shape) if shape else 1
            self._layout.append((name, offset, size, shape,
                                 jnp.asarray(init[name]).dtype))
            offset += size
        self._packed_size = offset
        del init

        # padded so the element-sharded flat buffers divide over the mesh
        self._padded_in = -(-self._packed_size // self._n_shard) * self._n_shard
        out_size = 1 + self._packed_size  # loss at offset 0
        self._padded_out = -(-out_size // self._n_shard) * self._n_shard

        layout = self._layout
        mesh = self._mesh
        param_rule = self._rule
        pad_out = self._padded_out - out_size

        def packed_step(flat_params, batch):
            params = {name: flat_params[off:off + size]
                      .reshape(shape).astype(dtype)
                      for name, off, size, shape, dtype in layout}
            if param_rule is not None:
                # model parallelism: constrain each unpacked param to its
                # rule sharding — XLA partitions the whole step around it
                params = {
                    name: jax.lax.with_sharding_constraint(
                        value, jax.sharding.NamedSharding(
                            mesh, param_rule(name, tuple(value.shape))))
                    for name, value in params.items()}
            loss, grads = jax.value_and_grad(model.loss)(params, batch)
            flat = jnp.concatenate(
                [jnp.reshape(loss, (1,)).astype(jnp.float32)]
                + [grads[name].astype(jnp.float32).ravel()
                   for name, *_ in layout]
                + ([jnp.zeros((pad_out,), jnp.float32)] if pad_out else []))
            return flat

        self._step = jax.jit(packed_step,
                             out_shardings=self._flat_sharding)

    @property
    def num_local_devices(self) -> int:
        return self._mesh.devices.size

    def init_params(self, seed: int = 0) -> TensorStore:
        """Deterministic init — every worker derives the identical store for
        PS bootstrap (cf. the reference's fabricated dummy 10x10 'weight'
        when the pull comes back empty — src/worker.cpp:346-353)."""
        params = self.model.init_params(seed)
        return {k: np.asarray(v, np.float32) for k, v in params.items()}

    def _shard_batch(self, batch):
        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, self._batch_sharded)
        return jax.tree.map(put, batch)

    def _pack(self, params: Mapping[str, np.ndarray]) -> np.ndarray:
        flat = np.zeros(self._padded_in, np.float32)
        for name, off, size, _shape, _dtype in self._layout:
            flat[off:off + size] = np.asarray(
                params[name], np.float32).ravel()
        return flat

    def compute_gradients(self, params: Mapping[str, np.ndarray],
                          batch) -> tuple[TensorStore, float]:
        """params (host store) + batch -> (gradient store, loss).

        One H2D upload (packed params), one D2H fetch (loss + packed
        grads), regardless of tensor count."""
        flat = jax.device_put(self._pack(params), self._flat_sharding)
        packed = np.asarray(self._step(flat, self._shard_batch(batch)))
        loss = float(packed[0])
        grads = {name: packed[1 + off:1 + off + size].reshape(shape)
                 for name, off, size, shape, _dtype in self._layout}
        return grads, loss
