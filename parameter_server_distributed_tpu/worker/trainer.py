"""Worker-local gradient computation — jitted, packed, with local data
parallelism.

Replaces two reference components at once:

- the gradient stub (`compute_gradients` fills 0.01 —
  reference: src/worker.cpp:316-329) becomes a real jitted
  value_and_grad of the worker's model;
- the intra-node NCCL all-reduce (`NCCLManager` +
  `aggregate_gradients_multi_gpu` — reference: src/nccl_manager.cpp:102-121,
  src/worker.cpp:409-448) becomes *sharding the batch across local devices
  inside one jitted step*: the loss is a mean over the global batch, so XLA
  inserts the cross-device reduction itself.  No manager class, no explicit
  collective, no H2D round-trips per tensor.

Transfer discipline: the reference pays per-tensor cudaMalloc/H2D/D2H on
every iteration (src/worker.cpp:409-448).  Here the whole parameter store
crosses the host<->device boundary as ONE flat f32 buffer each way per
iteration — the jitted step unpacks it, differentiates, and repacks the
gradients with the loss piggybacked at offset 0, so a 60-tensor ResNet
costs the same two transfers as a 1-tensor MLP.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.lock_order import checked_lock
from ..core.tensor import TensorStore

# One dispatch at a time per process: trainer-originated XLA work (step
# launch, bucket slice fetches) may run from several threads at once —
# the worker's train thread plus the RPC sender draining GradientBuckets,
# times N in-process workers under tests.  The XLA CPU client has
# deadlocked under that concurrency (both dispatches parked forever);
# serializing OUR dispatch entry points costs nothing in production (one
# worker per process, dispatch is microseconds) and removes the overlap
# the client cannot handle.  D2H/compute overlap is unaffected: the lock
# covers launching work, and async copies still complete in parallel.
_DISPATCH_LOCK = checked_lock("trainer._DISPATCH_LOCK")


class GradientBuckets:
    """Lazily-fetched packed gradients: the D2H leg of the pipelined data
    plane.

    ``compute_gradient_buckets`` returns one of these instead of a
    materialized gradient dict: the jitted step's flat output stays on
    device, and iterating yields ``(name, f32 array)`` per tensor while
    fetching the flat buffer host-side in bucket-sized slices on demand.
    Fed to a lazy wire-tensor iterator (worker/worker.py) under the
    chunk-stream/fused RPCs, bucket N+1's D2H copy (kicked off
    asynchronously) overlaps bucket N's compress/encode/transport — the
    whole-store fetch stall of the serial path disappears.

    Bucket 0 additionally carries the loss scalar (flat offset 0);
    reading :attr:`loss` fetches it, blocking until the step's compute is
    done.  Fetched buckets are cached, so re-iteration (the unary
    fallback replays the tensors) costs no second device round-trip.
    ``on_fetch(bucket_index, n_buckets)`` fires on each REAL device
    fetch — tests and the data-plane microbench use it to observe
    pipelining."""

    def __init__(self, layout, device_flat, bucket_bytes: int,
                 on_fetch: Callable[[int, int], None] | None = None):
        self._device = device_flat
        self.on_fetch = on_fetch
        # greedy plan over the fixed layout: consecutive tensors grouped
        # into ~bucket_bytes f32 slices of the flat output (loss scalar
        # rides bucket 0); a tensor larger than the budget rides alone —
        # same grouping rule as rpc/data_plane.split_tensors
        plan: list[tuple[int, int, list]] = []
        group: list = []
        start = 0
        for entry in layout:
            _name, off, size, _shape, _dtype = entry
            end = 1 + off + size
            if group and bucket_bytes > 0 and \
                    4 * (end - start) > bucket_bytes:
                plan.append((start, 1 + off, group))
                group, start = [], 1 + off
            group.append(entry)
        if group or not plan:
            end = (1 + group[-1][1] + group[-1][2]) if group else 1
            plan.append((start, end, group))
        self._plan = plan
        self._slices: list = [None] * len(plan)
        self._host: dict[int, np.ndarray] = {}
        self._lock = threading.Lock()

    @property
    def num_buckets(self) -> int:
        return len(self._plan)

    @property
    def loss(self) -> float:
        return float(self._fetch(0)[0])

    def _dev_slice(self, i: int):
        s = self._slices[i]
        if s is None:
            a, b, _ = self._plan[i]
            with _DISPATCH_LOCK:
                s = self._slices[i] = self._device[a:b]
        return s

    def _fetch(self, i: int) -> np.ndarray:
        with self._lock:
            buf = self._host.get(i)
            if buf is None:
                if self.on_fetch is not None:
                    self.on_fetch(i, len(self._plan))
                buf = self._host[i] = np.asarray(self._dev_slice(i))
        return buf

    def _prefetch(self, i: int) -> None:
        """Kick bucket i's device→host copy without blocking, so it runs
        under the previous bucket's encode/transport."""
        if i >= len(self._plan) or i in self._host:
            return
        start_copy = getattr(self._dev_slice(i), "copy_to_host_async", None)
        if start_copy is not None:
            with _DISPATCH_LOCK:
                start_copy()

    def __iter__(self) -> Iterator[tuple[str, np.ndarray]]:
        for i, (start, _end, entries) in enumerate(self._plan):
            self._prefetch(i + 1)
            buf = self._fetch(i)
            for name, off, size, shape, _dtype in entries:
                a = 1 + off - start
                yield name, buf[a:a + size].reshape(shape)


class Trainer:
    """Jitted gradient computation for one worker process.

    ``local_devices``: devices for intra-worker data parallelism (defaults
    to all visible devices).  The batch's leading axis is sharded across
    them; parameters are replicated.

    ``mesh_config`` + ``rule_fn``: intra-worker MODEL parallelism — the
    worker's local chips form a full mesh (data/fsdp/tensor/...) and the
    unpacked params are sharding-constrained by ``rule_fn(mesh)`` inside
    the jitted step, so XLA partitions the forward/backward across the
    worker's chips (Megatron TP, ZeRO fsdp) while the PS protocol still
    sees one packed host store per push/pull.  The packed flat buffers at
    the host<->device boundary are themselves element-sharded over ALL
    mesh axes (padded to divisibility), so no chip ever materializes a
    full replica of the params or grads — the point of a model-parallel
    worker.  The reference's workers are strictly single-GPU-per-rank
    (src/worker.cpp); this is the TPU-native upgrade: a worker whose
    model does not fit one chip still speaks plain PS.
    """

    def __init__(self, model, local_devices: list | None = None,
                 mesh_config=None, rule_fn=None):
        self.model = model
        devices = local_devices or jax.local_devices()
        self._rule = None
        if mesh_config is not None:
            from ..parallel.mesh import (AXIS_NAMES, batch_sharding,
                                         build_mesh)

            need = mesh_config.num_devices
            if len(devices) < need:
                raise ValueError(
                    f"worker mesh {mesh_config.axis_sizes} needs {need} "
                    f"local devices, have {len(devices)}")
            self._mesh = build_mesh(mesh_config, devices=devices[:need])
            if rule_fn is not None:
                self._rule = rule_fn(self._mesh)
            # flat param/grad buffers are element-sharded across every
            # chip: 1/N of the store per chip at the boundary
            self._flat_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec(AXIS_NAMES))
            self._n_shard = need
            self._batch_sharded = batch_sharding(self._mesh)
        else:
            self._mesh = jax.sharding.Mesh(np.array(devices), ("local",))
            self._flat_sharding = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec())
            self._n_shard = 1
            self._batch_sharded = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec("local"))

        # fixed packing layout: (name, offset, size, shape, dtype), by name
        init = model.init_params(0)
        self._layout = []
        offset = 0
        for name in sorted(init):
            shape = tuple(np.shape(init[name]))
            size = math.prod(shape) if shape else 1
            self._layout.append((name, offset, size, shape,
                                 jnp.asarray(init[name]).dtype))
            offset += size
        self._packed_size = offset
        del init

        # padded so the element-sharded flat buffers divide over the mesh
        self._padded_in = -(-self._packed_size // self._n_shard) * self._n_shard
        out_size = 1 + self._packed_size  # loss at offset 0
        self._padded_out = -(-out_size // self._n_shard) * self._n_shard

        layout = self._layout
        mesh = self._mesh
        param_rule = self._rule
        pad_out = self._padded_out - out_size

        def packed_step(flat_params, batch):
            params = {name: flat_params[off:off + size]
                      .reshape(shape).astype(dtype)
                      for name, off, size, shape, dtype in layout}
            if param_rule is not None:
                # model parallelism: constrain each unpacked param to its
                # rule sharding — XLA partitions the whole step around it
                params = {
                    name: jax.lax.with_sharding_constraint(
                        value, jax.sharding.NamedSharding(
                            mesh, param_rule(name, tuple(value.shape))))
                    for name, value in params.items()}
            loss, grads = jax.value_and_grad(model.loss)(params, batch)
            flat = jnp.concatenate(
                [jnp.reshape(loss, (1,)).astype(jnp.float32)]
                + [grads[name].astype(jnp.float32).ravel()
                   for name, *_ in layout]
                + ([jnp.zeros((pad_out,), jnp.float32)] if pad_out else []))
            return flat

        self._step = jax.jit(packed_step,
                             out_shardings=self._flat_sharding)

    @property
    def num_local_devices(self) -> int:
        return self._mesh.devices.size

    def init_params(self, seed: int = 0) -> TensorStore:
        """Deterministic init — every worker derives the identical store for
        PS bootstrap (cf. the reference's fabricated dummy 10x10 'weight'
        when the pull comes back empty — src/worker.cpp:346-353)."""
        params = self.model.init_params(seed)
        return {k: np.asarray(v, np.float32) for k, v in params.items()}

    def _shard_batch(self, batch):
        def put(x):
            x = np.asarray(x)
            return jax.device_put(x, self._batch_sharded)
        return jax.tree.map(put, batch)

    _pack_bufs: list[np.ndarray] | None = None
    _pack_turn = 0

    def _pack(self, params: Mapping[str, np.ndarray]) -> np.ndarray:
        # Persistent DOUBLE buffer instead of a fresh np.zeros every
        # iteration: the padded tail stays zero from allocation and every
        # layout slot is overwritten per call, so reuse is exact.  Two
        # buffers alternate because the CPU PJRT client may ZERO-COPY a
        # device_put numpy array (the device buffer aliases it): the
        # buffer written this iteration must not be the one the previous
        # iteration's upload may still alias.
        if self._pack_bufs is None:
            self._pack_bufs = [np.zeros(self._padded_in, np.float32)
                               for _ in range(2)]
        flat = self._pack_bufs[self._pack_turn]
        self._pack_turn ^= 1
        for name, off, size, _shape, _dtype in self._layout:
            flat[off:off + size] = np.asarray(
                params[name], np.float32).ravel()
        return flat

    def _dispatch_step(self, params: Mapping[str, np.ndarray], batch):
        """Pack + upload + launch the jitted step; returns the (async)
        flat device output without fetching it."""
        packed = self._pack(params)
        with _DISPATCH_LOCK:
            flat = jax.device_put(packed, self._flat_sharding)
            return self._step(flat, self._shard_batch(batch))

    def compute_gradients(self, params: Mapping[str, np.ndarray],
                          batch) -> tuple[TensorStore, float]:
        """params (host store) + batch -> (gradient store, loss).

        One H2D upload (packed params), one D2H fetch (loss + packed
        grads), regardless of tensor count."""
        packed = np.asarray(self._dispatch_step(params, batch))
        loss = float(packed[0])
        grads = {name: packed[1 + off:1 + off + size].reshape(shape)
                 for name, off, size, shape, _dtype in self._layout}
        return grads, loss

    def compute_gradient_buckets(self, params: Mapping[str, np.ndarray],
                                 batch, bucket_bytes: int | None = None,
                                 on_fetch=None) -> GradientBuckets:
        """Incremental-D2H variant of :meth:`compute_gradients`: same jitted
        step, but the packed gradient buffer stays on device and comes back
        host-side in ~``bucket_bytes`` slices fetched lazily as the
        returned :class:`GradientBuckets` is iterated — the producer side
        of the pipelined push (worker/worker.py).  Default bucket budget:
        rpc/data_plane.bucket_bytes()."""
        if bucket_bytes is None:
            from ..rpc.data_plane import bucket_bytes as _bb
            bucket_bytes = _bb()
        return GradientBuckets(self._layout,
                               self._dispatch_step(params, batch),
                               bucket_bytes, on_fetch=on_fetch)
