"""Sharded parameter-server client: the classic multi-PS topology.

BASELINE config 3 ("4 PS shards / 8 workers, sharded push/pull") has two
realizations in this framework: inside one SPMD program the fsdp mesh axis
IS the shard table (parallel/train_step.py), and across processes the
store is name-partitioned over several ordinary PS servers — this module.
Each tensor has one owner shard (stable CRC32 hash of its name, identical
on every worker with no coordination); pushes and pulls fan out per owner
and responses merge back into one logical store.

`ShardedPSClient` mirrors `rpc.service.RpcClient`'s ``call(method,
request)`` surface, so `worker.Worker` uses either interchangeably — the
coordinator's discovery response (GetPSAddressResponse extension field 3)
decides which gets built.  With one address it degrades to exactly the
single-PS behavior.

Per-shard semantics stay those of `ParameterServerCore`: every worker
pushes to EVERY shard each iteration (a shard owning no tensors of the
current push still receives an empty gradient list), so each shard's
barrier sees the same contributor set and iteration numbering as the
unsharded topology.
"""

from __future__ import annotations

import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from ..obs import trace as obs_trace
from ..rpc import messages as m
from ..rpc.data_plane import PSClient


def shard_owner(name: str, n_shards: int) -> int:
    """Stable tensor-name -> shard index (CRC32; identical across
    processes and runs, unlike Python's randomized hash())."""
    return zlib.crc32(name.encode("utf-8")) % n_shards


class ShardedPSClient:
    """Fan-out/merge client over N parameter-server shards.  Each shard
    connection is a :class:`rpc.data_plane.PSClient`, so pushes and pulls
    ride the chunk-stream data plane per shard (with per-connection unary
    fallback against reference servers)."""

    def __init__(self, addresses: Sequence[str],
                 service: str = m.PARAMETER_SERVER_SERVICE,
                 methods=None):
        if not addresses:
            raise ValueError("need at least one PS shard address")
        self.addresses = list(addresses)
        self._clients = [PSClient(addr, service, methods)
                         for addr in addresses]
        # shard RPCs are independent — issue them concurrently so the
        # fan-out latency is max(shard latencies), not their sum
        self._pool = (ThreadPoolExecutor(
            max_workers=len(self._clients),
            thread_name_prefix="ps-shard") if len(self._clients) > 1
            else None)

    @property
    def num_shards(self) -> int:
        return len(self._clients)

    @property
    def shm_active(self) -> bool:
        """True once ANY shard connection rides the same-host shared-
        memory transport (each PSClient negotiates per connection, so a
        mixed local/remote shard map uses shm exactly where it can)."""
        return any(getattr(c, "shm_active", False) for c in self._clients)

    def close(self) -> None:
        for client in self._clients:
            client.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    # ------------------------------------------------------------------ call
    def call(self, method: str, request, timeout: float | None = None):
        if self.num_shards == 1:
            return self._clients[0].call(method, request, timeout=timeout)
        handler = getattr(self, f"_call_{method}", None)
        if handler is None:
            raise ValueError(f"unsupported sharded method {method!r}")
        return handler(request, timeout)

    def _submit(self, fn, *fn_args, **fn_kwargs):
        """Pool submit that carries the calling thread's span context into
        the fan-out thread: shard RPC spans nest under the worker's
        push/pull span instead of rooting disconnected traces."""
        ctx = obs_trace.current()

        def run():
            with obs_trace.attach(ctx):
                return fn(*fn_args, **fn_kwargs)

        return self._pool.submit(run)

    def _fan_out(self, method: str, requests, timeout):
        futures = [self._submit(client.call, method, request,
                                timeout=timeout)
                   for client, request in zip(self._clients, requests)]
        return [f.result() for f in futures]

    # ------------------------------------------------------------- push path
    def push_gradients(self, update: m.GradientUpdate,
                       timeout: float | None = None) -> m.PushResponse:
        """Streaming-data-plane push (chunk streams per shard, concurrent
        fan-out).  Same merge/stale-retry semantics as the unary path."""
        if self.num_shards == 1:
            return self._clients[0].push_gradients(update, timeout=timeout)
        return self._push_sharded(update, timeout, stream=True)

    def _call_ReceiveGradients(self, request: m.GradientUpdate, timeout):
        return self._push_sharded(request, timeout, stream=False)

    def _partition(self, tensors) -> list[list]:
        """Name-partition a tensor iterable over the shards (one list per
        shard; non-owners get an empty list, which still counts as a
        barrier contribution when pushed)."""
        per_shard: list[list] = [[] for _ in range(self.num_shards)]
        for tensor in (tensors() if callable(tensors) else tensors):
            per_shard[shard_owner(tensor.name, self.num_shards)].append(
                tensor)
        return per_shard

    @staticmethod
    def _merge_pushes(responses) -> m.PushResponse:
        return m.PushResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            iteration=max(r.iteration for r in responses),
            aggregation_complete=all(r.aggregation_complete
                                     for r in responses),
            workers_received=min(r.workers_received for r in responses),
            total_workers=max(r.total_workers for r in responses))

    def _push_sharded(self, request: m.GradientUpdate, timeout,
                      stream: bool) -> m.PushResponse:
        def push(client, update):
            if stream:
                return client.push_gradients(update, timeout=timeout)
            return client.call("ReceiveGradients", update, timeout=timeout)

        per_shard = self._partition(request.gradients)
        updates = [m.GradientUpdate(worker_id=request.worker_id,
                                    iteration=request.iteration,
                                    gradients=tensors)
                   for tensors in per_shard]
        futures = [self._submit(push, client, update)
                   for client, update in zip(self._clients, updates)]
        responses = [f.result() for f in futures]
        # Async (bounded-staleness) partial failure: shards that accepted
        # applied the update ON ARRIVAL, so a blanket worker-level retry
        # would double-apply their partitions.  Re-push ONLY the rejected
        # shards, with the SAME payload at the shard's current iteration —
        # bounded-staleness semantics allow applying the gradient at a
        # later logical time, and this keeps every shard at exactly one
        # update per batch.  (Sync mode never produces 'stale' rejections
        # and its re-pushes overwrite idempotently.)
        for _ in range(3):
            stale = [i for i, r in enumerate(responses)
                     if not r.success and "stale" in r.message]
            if not stale:
                break
            for i in stale:
                responses[i] = push(
                    self._clients[i],
                    m.GradientUpdate(worker_id=request.worker_id,
                                     iteration=responses[i].iteration,
                                     gradients=per_shard[i]))
        return self._merge_pushes(responses)

    # ------------------------------------------------------------ fused path
    def push_pull(self, worker_id: int, iteration: int, tensors,
                  pull_wire_dtype: int = 0, timeout: float | None = None,
                  on_chunk=None) -> tuple[m.PushResponse,
                                          m.ParameterUpdate | None]:
        """Fused push→barrier→pull fanned out per shard (one
        PushPullStream round per shard, concurrent).  Every shard sees a
        push — owners get their partition, the rest an empty chunk — so
        each shard's barrier counts the same contributor set as the unary
        topology; stale rejections re-push only the rejected shards with
        the same payload (the `_push_sharded` semantics).  The merged
        parameter update is ``None`` — caller falls back to barrier-poll +
        pull — unless EVERY shard delivered fresh parameters."""
        if self.num_shards == 1:
            return self._clients[0].push_pull(
                worker_id, iteration, tensors,
                pull_wire_dtype=pull_wire_dtype, timeout=timeout,
                on_chunk=on_chunk)
        # name-partitioning needs the full tensor list up front, so the
        # sharded topology materializes the (possibly lazy) producer; the
        # per-bucket D2H overlap is a single-PS refinement
        per_shard = self._partition(tensors)

        def fused(client, shard_tensors, it):
            return client.push_pull(worker_id, it, shard_tensors,
                                    pull_wire_dtype=pull_wire_dtype,
                                    timeout=timeout, on_chunk=on_chunk)

        futures = [self._submit(fused, client, shard_tensors, iteration)
                   for client, shard_tensors in zip(self._clients, per_shard)]
        results = [f.result() for f in futures]
        for _ in range(3):
            stale = [i for i, (push, _) in enumerate(results)
                     if not push.success and "stale" in push.message]
            if not stale:
                break
            for i in stale:
                results[i] = fused(self._clients[i], per_shard[i],
                                   results[i][0].iteration)
        merged_push = self._merge_pushes([push for push, _ in results])
        stores = [params for _, params in results]
        if not merged_push.success or any(s is None for s in stores):
            return merged_push, None
        return merged_push, self._merge_pulls(stores)

    # ------------------------------------------------------------- pull path
    def pull_parameters(self, request: m.PullRequest,
                        timeout: float | None = None,
                        on_chunk=None) -> m.ParameterUpdate:
        """Streaming-data-plane pull (chunk streams per shard, concurrent
        fan-out), merged exactly like the unary path.  ``on_chunk`` is
        invoked from the fan-out threads CONCURRENTLY (shards stream
        independently) — consumers must be thread-safe per call; the
        worker's per-tensor dict insert is (tensor names are disjoint
        across shards)."""
        if self.num_shards == 1:
            return self._clients[0].pull_parameters(request, timeout=timeout,
                                                    on_chunk=on_chunk)
        futures = [self._submit(client.pull_parameters, request,
                                timeout=timeout, on_chunk=on_chunk)
                   for client in self._clients]
        return self._merge_pulls([f.result() for f in futures])

    def _call_ServeParameters(self, request: m.PullRequest, timeout):
        return self._merge_pulls(
            self._fan_out("ServeParameters",
                          [request] * self.num_shards, timeout))

    @staticmethod
    def _merge_pulls(responses) -> m.ParameterUpdate:
        merged: list = []
        for response in responses:
            merged.extend(response.parameters)
        return m.ParameterUpdate(
            iteration=max(r.iteration for r in responses),
            parameters=merged,
            ready=all(r.ready for r in responses))

    # ------------------------------------------------------------------ sync
    def _call_CheckSyncStatus(self, request: m.SyncStatusRequest, timeout):
        responses = self._fan_out("CheckSyncStatus",
                                  [request] * self.num_shards, timeout)
        return m.SyncStatusResponse(
            iteration=request.iteration,
            ready=all(r.ready for r in responses),
            workers_received=min(r.workers_received for r in responses),
            total_workers=max(r.total_workers for r in responses))

    # ------------------------------------------------------------ checkpoint
    def _shard_path(self, path: str, index: int) -> str:
        """Distinct per-shard checkpoint path: shards may share a
        filesystem, so an explicit path gets a .shard<N> suffix (shard 0
        keeps the bare path for reference-tool compatibility)."""
        if not path or index == 0:
            return path
        return f"{path}.shard{index}"

    def _call_SaveCheckpoint(self, request: m.SaveCheckpointRequest, timeout):
        responses = self._fan_out(
            "SaveCheckpoint",
            [m.SaveCheckpointRequest(epoch=request.epoch,
                                     path=self._shard_path(request.path, i))
             for i in range(self.num_shards)], timeout)
        return m.SaveCheckpointResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            checkpoint_path=responses[0].checkpoint_path)

    def _call_LoadCheckpoint(self, request: m.LoadCheckpointRequest, timeout):
        responses = self._fan_out(
            "LoadCheckpoint",
            [m.LoadCheckpointRequest(path=self._shard_path(request.path, i))
             for i in range(self.num_shards)], timeout)
        merged: list = []
        for response in responses:
            merged.extend(response.parameters)
        return m.LoadCheckpointResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            epoch=max(r.epoch for r in responses),
            parameters=merged)
