"""Sharded parameter-server client: the classic multi-PS topology.

BASELINE config 3 ("4 PS shards / 8 workers, sharded push/pull") has two
realizations in this framework: inside one SPMD program the fsdp mesh axis
IS the shard table (parallel/train_step.py), and across processes the
store is name-partitioned over several ordinary PS servers — this module.
Each tensor has one owner shard (stable CRC32 hash of its name, identical
on every worker with no coordination); pushes and pulls fan out per owner
and responses merge back into one logical store.

`ShardedPSClient` mirrors `rpc.service.RpcClient`'s ``call(method,
request)`` surface, so `worker.Worker` uses either interchangeably — the
coordinator's discovery response (GetPSAddressResponse extension field 3)
decides which gets built.  With one address it degrades to exactly the
single-PS behavior.

Per-shard semantics stay those of `ParameterServerCore`: every worker
pushes to EVERY shard each iteration (a shard owning no tensors of the
current push still receives an empty gradient list), so each shard's
barrier sees the same contributor set and iteration numbering as the
unsharded topology.

Replication extensions (ISSUE 7, replication/):

- **hot failover** — built with a :class:`~..replication.failover
  .ShardMapClient`, a shard RPC that dies with a transport error (never
  UNIMPLEMENTED — that is the reference-peer downgrade) reports the dead
  primary to the coordinator, which promotes the shard's backup; the
  SAME iteration retries against the replica.  The dead address is never
  revisited (permanent downgrade, PR-2 discipline lifted to addresses),
  and the replica's aggregated watermark makes the retry idempotent.
- **live resharding** — a push rejected with the ``stale shard map``
  marker means a reshard moved tensors this client still routes by the
  old partition: the client waits for the coordinator's map epoch to
  advance, rebuilds its shard connections, repartitions, and replays the
  round (per-(worker, tensor) dedup on unchanged shards absorbs the
  replay) — zero failed steps across a 2→4 split under load.
"""

from __future__ import annotations

import logging
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

import grpc

from ..obs import flight
from ..obs import trace as obs_trace
from ..replication.failover import ShardMapClient, _status_code
from ..replication.messages import STALE_SHARD_MAP
from ..rpc import messages as m
from ..rpc.data_plane import PSClient

log = logging.getLogger("pst.shards")


def shard_owner(name: str, n_shards: int) -> int:
    """Stable tensor-name -> shard index (CRC32; identical across
    processes and runs, unlike Python's randomized hash())."""
    return zlib.crc32(name.encode("utf-8")) % n_shards


def _is_stale_map(response) -> bool:
    message = getattr(response, "message", "") or ""
    return (getattr(response, "success", True) is False
            and STALE_SHARD_MAP in message)


class ShardedPSClient:
    """Fan-out/merge client over N parameter-server shards.  Each shard
    connection is a :class:`rpc.data_plane.PSClient`, so pushes and pulls
    ride the chunk-stream data plane per shard (with per-connection unary
    fallback against reference servers).  ``shard_map`` (optional) turns
    on hot failover and live-reshard repartitioning — see the module
    docstring."""

    # bounded replays: one reshard repartition or failover retry per
    # round is the common case; two covers a promotion racing a reshard
    _MAX_ROUND_REPLAYS = 3

    # The hierarchical-aggregation tier (tiers/group_client.py) does not
    # interpose on the sharded topology: the leaf would have to sit
    # between the per-tensor partitioner and N shard barriers, and every
    # shard's contributor accounting would need the group cover — the
    # flat fan-out already overlaps shards, so the tier's win is the
    # single-PS ingress bottleneck it was built for (ISSUE 9).
    supports_tiers = False

    def __init__(self, addresses: Sequence[str],
                 service: str = m.PARAMETER_SERVER_SERVICE,
                 methods=None,
                 shard_map: ShardMapClient | None = None):
        if not addresses:
            raise ValueError("need at least one PS shard address")
        self._service = service
        self._methods = methods
        self._shard_map = shard_map
        # guards the address/client/pool triple during a failover swap or
        # a reshard rebuild (fan-out threads read them concurrently)
        self._topology_lock = threading.Lock()
        self.addresses: list[str] = []
        self._clients: list[PSClient] = []
        self._pool: ThreadPoolExecutor | None = None
        # (worker, iteration) of the round in flight, stamped by the
        # push/pull entry points purely for flight-recorder attribution:
        # a failover retry deep in _with_failover can then name the
        # retried iteration in the postmortem.  One worker runs one round
        # at a time, so a plain pair is race-benign.
        self._round: tuple[int, int] = (-1, -1)
        self._build(list(addresses))

    def _build(self, addresses: list[str]) -> None:
        self.addresses = list(addresses)
        self._clients = [PSClient(addr, self._service, self._methods)
                         for addr in addresses]
        # shard RPCs are independent — issue them concurrently so the
        # fan-out latency is max(shard latencies), not their sum
        self._pool = (ThreadPoolExecutor(
            max_workers=len(self._clients),
            thread_name_prefix="ps-shard") if len(self._clients) > 1
            else None)

    def _rebuild(self, addresses: list[str]) -> None:
        """Replace the whole shard topology (reshard repartition)."""
        with self._topology_lock:
            old_clients, old_pool = self._clients, self._pool
            self._build(addresses)
        for client in old_clients:
            client.close()
        if old_pool is not None:
            old_pool.shutdown(wait=False)
        log.info("shard topology rebuilt: %d shards %s",
                 len(addresses), addresses)

    @property
    def num_shards(self) -> int:
        return len(self._clients)

    @property
    def shm_active(self) -> bool:
        """True once ANY shard connection rides the same-host shared-
        memory transport (each PSClient negotiates per connection, so a
        mixed local/remote shard map uses shm exactly where it can)."""
        return any(getattr(c, "shm_active", False) for c in self._clients)

    def close(self) -> None:
        for client in self._clients:
            client.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self._shard_map is not None:
            self._shard_map.close()

    # -------------------------------------------------- failover / resharding
    def _with_failover(self, index: int, fn):
        """Run ``fn(client)`` against shard ``index``; on a transport
        error (anything but UNIMPLEMENTED, which the PSClient fallback
        machinery owns), report the dead primary, let the coordinator
        promote its backup, swap the connection to the replica, and
        retry the SAME call once.  The dead address is never revisited."""
        with self._topology_lock:
            client = self._clients[index]
            address = self.addresses[index]
        try:
            return fn(client)
        except grpc.RpcError as exc:
            if (self._shard_map is None
                    or _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED):
                raise
            log.warning("shard %d (%s) failed mid-call (%s); requesting "
                        "backup promotion", index, address,
                        _status_code(exc))
            replacement = self._shard_map.report_failure(index, address)
            if not replacement or replacement == address:
                raise  # no backup to promote: surface the real error
            with self._topology_lock:
                if self.addresses[index] == address:
                    self._clients[index].close()
                    self._clients[index] = PSClient(
                        replacement, self._service, self._methods)
                    self.addresses[index] = replacement
                client = self._clients[index]
            log.warning("shard %d failed over %s -> %s; retrying the "
                        "same round against the replica", index, address,
                        replacement)
            worker, iteration = self._round
            flight.record("failover.retry", iteration=iteration,
                          worker=worker, a=index, note=replacement)
            return fn(client)

    def refresh_topology(self, wait_for_epoch_above: int | None = None,
                         timeout: float = 15.0) -> bool:
        """Re-fetch the shard map (optionally waiting for its epoch to
        pass ``wait_for_epoch_above`` — the reshard-publication park) and
        rebuild the connections if the primaries changed.  True when the
        topology actually changed."""
        if self._shard_map is None:
            return False
        if wait_for_epoch_above is not None:
            self._shard_map.wait_for_epoch_above(wait_for_epoch_above,
                                                 timeout=timeout)
        elif not self._shard_map.refresh():
            return False
        new = self._shard_map.primaries()
        if new and new != self.addresses:
            self._rebuild(new)
            return True
        return False

    def _repartition_after_stale_map(self) -> bool:
        """A shard rejected a push with the stale-shard-map marker: park
        until the coordinator publishes the newer map, rebuild, and tell
        the caller whether a replay is worth it."""
        if self._shard_map is None:
            return False
        known = self._shard_map.epoch
        changed = self.refresh_topology(wait_for_epoch_above=known)
        if changed:
            return True
        # epoch advanced without an address change (e.g. promotion won a
        # race) — still worth one replay
        return self._shard_map.epoch > known

    # ------------------------------------------------------------------ call
    def call(self, method: str, request, timeout: float | None = None):
        for _ in range(self._MAX_ROUND_REPLAYS):
            if self.num_shards == 1:
                resp = self._with_failover(
                    0, lambda c: c.call(method, request, timeout=timeout))
            else:
                handler = getattr(self, f"_call_{method}", None)
                if handler is None:
                    raise ValueError(f"unsupported sharded method {method!r}")
                resp = handler(request, timeout)
            if not _is_stale_map(resp):
                return resp
            if not self._repartition_after_stale_map():
                return resp
        return resp

    def _submit(self, fn, *fn_args, **fn_kwargs):
        """Pool submit that carries the calling thread's span context into
        the fan-out thread: shard RPC spans nest under the worker's
        push/pull span instead of rooting disconnected traces."""
        ctx = obs_trace.current()

        def run():
            with obs_trace.attach(ctx):
                return fn(*fn_args, **fn_kwargs)

        return self._pool.submit(run)

    def _fan_out(self, method: str, requests, timeout):
        futures = [
            self._submit(self._with_failover, i,
                         lambda c, req=request: c.call(method, req,
                                                       timeout=timeout))
            for i, request in enumerate(requests)]
        return [f.result() for f in futures]

    # ------------------------------------------------------------- push path
    def push_gradients(self, update: m.GradientUpdate,
                       timeout: float | None = None) -> m.PushResponse:
        """Streaming-data-plane push (chunk streams per shard, concurrent
        fan-out).  Same merge/stale-retry semantics as the unary path."""
        self._round = (update.worker_id, update.iteration)
        for _ in range(self._MAX_ROUND_REPLAYS):
            if self.num_shards == 1:
                resp = self._with_failover(
                    0, lambda c: c.push_gradients(update, timeout=timeout))
            else:
                resp = self._push_sharded(update, timeout, stream=True)
            if not _is_stale_map(resp):
                return resp
            if not self._repartition_after_stale_map():
                return resp
        return resp

    def _call_ReceiveGradients(self, request: m.GradientUpdate, timeout):
        return self._push_sharded(request, timeout, stream=False)

    def _partition(self, tensors) -> list[list]:
        """Name-partition a tensor iterable over the shards (one list per
        shard; non-owners get an empty list, which still counts as a
        barrier contribution when pushed)."""
        per_shard: list[list] = [[] for _ in range(self.num_shards)]
        for tensor in (tensors() if callable(tensors) else tensors):
            per_shard[shard_owner(tensor.name, self.num_shards)].append(
                tensor)
        return per_shard

    @staticmethod
    def _merge_pushes(responses) -> m.PushResponse:
        return m.PushResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            iteration=max(r.iteration for r in responses),
            aggregation_complete=all(r.aggregation_complete
                                     for r in responses),
            workers_received=min(r.workers_received for r in responses),
            total_workers=max(r.total_workers for r in responses))

    @staticmethod
    def _bounded_stale(response) -> bool:
        """A bounded-staleness (async-mode) rejection — NOT the reshard
        stale-shard-map marker, which the round-replay loop owns."""
        return (not response.success and "stale" in response.message
                and STALE_SHARD_MAP not in response.message)

    def _push_sharded(self, request: m.GradientUpdate, timeout,
                      stream: bool) -> m.PushResponse:
        def push(client, update):
            if stream:
                return client.push_gradients(update, timeout=timeout)
            return client.call("ReceiveGradients", update, timeout=timeout)

        per_shard = self._partition(request.gradients)
        updates = [m.GradientUpdate(worker_id=request.worker_id,
                                    iteration=request.iteration,
                                    gradients=tensors)
                   for tensors in per_shard]
        futures = [self._submit(self._with_failover, i,
                                lambda c, u=update: push(c, u))
                   for i, update in enumerate(updates)]
        responses = [f.result() for f in futures]
        # Async (bounded-staleness) partial failure: shards that accepted
        # applied the update ON ARRIVAL, so a blanket worker-level retry
        # would double-apply their partitions.  Re-push ONLY the rejected
        # shards, with the SAME payload at the shard's current iteration —
        # bounded-staleness semantics allow applying the gradient at a
        # later logical time, and this keeps every shard at exactly one
        # update per batch.  (Sync mode never produces 'stale' rejections
        # and its re-pushes overwrite idempotently.)
        for _ in range(3):
            stale = [i for i, r in enumerate(responses)
                     if self._bounded_stale(r)]
            if not stale:
                break
            for i in stale:
                responses[i] = self._with_failover(
                    i, lambda c, i=i: push(c, m.GradientUpdate(
                        worker_id=request.worker_id,
                        iteration=responses[i].iteration,
                        gradients=per_shard[i])))
        return self._merge_pushes(responses)

    # ------------------------------------------------------------ fused path
    def push_pull(self, worker_id: int, iteration: int, tensors,
                  pull_wire_dtype: int = 0, timeout: float | None = None,
                  on_chunk=None) -> tuple[m.PushResponse,
                                          m.ParameterUpdate | None]:
        """Fused push→barrier→pull fanned out per shard (one
        PushPullStream round per shard, concurrent).  Every shard sees a
        push — owners get their partition, the rest an empty chunk — so
        each shard's barrier counts the same contributor set as the unary
        topology; stale rejections re-push only the rejected shards with
        the same payload (the `_push_sharded` semantics).  The merged
        parameter update is ``None`` — caller falls back to barrier-poll +
        pull — unless EVERY shard delivered fresh parameters.

        With a shard map, a stale-shard-map rejection (live reshard)
        parks for the new epoch, rebuilds the topology, and replays the
        WHOLE round against the new partition; a dead shard fails over to
        its promoted backup and replays that shard's round.  Both replays
        are idempotent (server-side per-(worker, tensor) dedup + the
        replica's aggregated watermark), so the worker observes a normal
        — if slower — round: zero failed steps."""
        self._round = (worker_id, iteration)
        if self._shard_map is None and self.num_shards == 1:
            # exact pre-replication behavior, lazy producer included
            return self._clients[0].push_pull(
                worker_id, iteration, tensors,
                pull_wire_dtype=pull_wire_dtype, timeout=timeout,
                on_chunk=on_chunk)
        # replays (failover, repartition) must re-read the tensors, so
        # materialize the (possibly lazy) producer once up front; the
        # per-bucket D2H overlap is a single-PS refinement
        all_tensors = list(tensors() if callable(tensors) else tensors)
        for _ in range(self._MAX_ROUND_REPLAYS):
            result = self._push_pull_once(worker_id, iteration, all_tensors,
                                          pull_wire_dtype, timeout, on_chunk)
            if not _is_stale_map(result[0]):
                return result
            log.warning("worker %d: push rejected stale-shard-map at "
                        "iteration %d; refreshing topology", worker_id,
                        iteration)
            if not self._repartition_after_stale_map():
                return result
        return result

    def _push_pull_once(self, worker_id: int, iteration: int, all_tensors,
                        pull_wire_dtype, timeout, on_chunk):
        if self.num_shards == 1:
            return self._with_failover(0, lambda c: c.push_pull(
                worker_id, iteration, all_tensors,
                pull_wire_dtype=pull_wire_dtype, timeout=timeout,
                on_chunk=on_chunk))
        per_shard = self._partition(all_tensors)

        def fused(client, shard_tensors, it):
            return client.push_pull(worker_id, it, shard_tensors,
                                    pull_wire_dtype=pull_wire_dtype,
                                    timeout=timeout, on_chunk=on_chunk)

        futures = [
            self._submit(self._with_failover, i,
                         lambda c, t=shard_tensors: fused(c, t, iteration))
            for i, shard_tensors in enumerate(per_shard)]
        results = [f.result() for f in futures]
        for _ in range(3):
            stale = [i for i, (push, _) in enumerate(results)
                     if self._bounded_stale(push)]
            if not stale:
                break
            for i in stale:
                results[i] = self._with_failover(
                    i, lambda c, i=i: fused(c, per_shard[i],
                                            results[i][0].iteration))
        merged_push = self._merge_pushes([push for push, _ in results])
        stores = [params for _, params in results]
        if not merged_push.success or any(s is None for s in stores):
            return merged_push, None
        return merged_push, self._merge_pulls(stores)

    # ------------------------------------------------------------- pull path
    def pull_parameters(self, request: m.PullRequest,
                        timeout: float | None = None,
                        on_chunk=None) -> m.ParameterUpdate:
        """Streaming-data-plane pull (chunk streams per shard, concurrent
        fan-out), merged exactly like the unary path.  ``on_chunk`` is
        invoked from the fan-out threads CONCURRENTLY (shards stream
        independently) — consumers must be thread-safe per call; the
        worker's per-tensor dict insert is (tensor names are disjoint
        across shards)."""
        self._round = (request.worker_id, request.iteration)
        if self.num_shards == 1:
            return self._with_failover(0, lambda c: c.pull_parameters(
                request, timeout=timeout, on_chunk=on_chunk))
        futures = [
            self._submit(self._with_failover, i,
                         lambda c: c.pull_parameters(request,
                                                     timeout=timeout,
                                                     on_chunk=on_chunk))
            for i in range(self.num_shards)]
        return self._merge_pulls([f.result() for f in futures])

    def _call_ServeParameters(self, request: m.PullRequest, timeout):
        return self._merge_pulls(
            self._fan_out("ServeParameters",
                          [request] * self.num_shards, timeout))

    @staticmethod
    def _merge_pulls(responses) -> m.ParameterUpdate:
        merged: list = []
        for response in responses:
            merged.extend(response.parameters)
        return m.ParameterUpdate(
            iteration=max(r.iteration for r in responses),
            parameters=merged,
            ready=all(r.ready for r in responses))

    # ------------------------------------------------------------------ sync
    def _call_CheckSyncStatus(self, request: m.SyncStatusRequest, timeout):
        responses = self._fan_out("CheckSyncStatus",
                                  [request] * self.num_shards, timeout)
        return m.SyncStatusResponse(
            iteration=request.iteration,
            ready=all(r.ready for r in responses),
            workers_received=min(r.workers_received for r in responses),
            total_workers=max(r.total_workers for r in responses))

    # ------------------------------------------------------------ checkpoint
    def _shard_path(self, path: str, index: int) -> str:
        """Distinct per-shard checkpoint path: shards may share a
        filesystem, so an explicit path gets a .shard<N> suffix (shard 0
        keeps the bare path for reference-tool compatibility)."""
        if not path or index == 0:
            return path
        return f"{path}.shard{index}"

    def _call_SaveCheckpoint(self, request: m.SaveCheckpointRequest, timeout):
        responses = self._fan_out(
            "SaveCheckpoint",
            [m.SaveCheckpointRequest(epoch=request.epoch,
                                     path=self._shard_path(request.path, i))
             for i in range(self.num_shards)], timeout)
        return m.SaveCheckpointResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            checkpoint_path=responses[0].checkpoint_path)

    def _call_LoadCheckpoint(self, request: m.LoadCheckpointRequest, timeout):
        responses = self._fan_out(
            "LoadCheckpoint",
            [m.LoadCheckpointRequest(path=self._shard_path(request.path, i))
             for i in range(self.num_shards)], timeout)
        merged: list = []
        for response in responses:
            merged.extend(response.parameters)
        return m.LoadCheckpointResponse(
            success=all(r.success for r in responses),
            message="; ".join(sorted({r.message for r in responses})),
            epoch=max(r.epoch for r in responses),
            parameters=merged)
