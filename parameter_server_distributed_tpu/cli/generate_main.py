"""Text generation CLI for the LM flagship (KV-cached decode).

    python -m parameter_server_distributed_tpu.cli.generate_main \
        --model=small_lm --prompt="the quick brown" --max-new=64 \
        [--ckpt=path.ckpt | --ckpt-dir=orbax_dir [--avg-last=K] \
         | --hf-gpt2=<local transformers checkout or hub name>] \
        [--temperature=0.8] [--top-k=40] [--top-p=0.9] \
        [--beam=4 [--length-penalty=0.6]] \
        [--seed=0] \
        [--dtype=bf16] [--tokens=1,2,3]

Parameters come from (in priority order) ``--ckpt`` (the host binary
checkpoint format — same files the PS writes), ``--ckpt-dir`` (latest
orbax sharded TrainState from pst-train), or fresh ``--seed`` init (demo
mode).  Either layer layout decodes: stores from ``--scan-layers``
training (stacked ``blocks/*``) and unrolled stores are converted to
whatever layout this process's model uses (``--scan-layers`` /
``--no-scan-layers`` / model default).  Prompts are byte-tokenized (data/text.ByteTokenizer, vocab 258 —
works for any registry LM whose vocab covers it); ``--tokens`` supplies
raw comma-separated token ids instead.  Output is the decoded
continuation (or raw ids with ``--tokens``).

The reference has no inference path at all (its gradient computation is a
0.01-constant stub — reference src/worker.cpp:316-329); this CLI completes
the train -> checkpoint -> generate loop.
"""

from __future__ import annotations

import logging
import sys

from ..config import parse_argv, require_flag_value


def draft_cost_ratio(flags: dict, draft, model) -> float:
    """--draft-cost-ratio if given, else the parameter-count proxy the
    adaptive depth controller's cost model defaults to (per-token decode
    cost tracks params, FLOPs- or bytes-bound alike).  Shared by
    pst-generate and pst-serve so the default cannot drift."""
    if "draft-cost-ratio" in flags:
        return float(flags["draft-cost-ratio"])
    return max(0.05, draft.num_params() / model.num_params())


def draft_ckpt_flags(path: str, lora_alpha: str = "") -> dict:
    """--draft-ckpt accepts either checkpoint form: a single-file host
    checkpoint (reference binary codec) or a sharded checkpoint DIRECTORY
    (what --ckpt-dir training runs write) — dispatch by what the path is,
    into the flag load_params reads for that form.  ``lora_alpha``
    (--draft-lora-alpha: the draft may be LoRA-trained with a DIFFERENT
    alpha than the target) forwards to the merge-on-load."""
    import os

    out = {"ckpt-dir": path} if os.path.isdir(path) else {"ckpt": path}
    if lora_alpha:
        out["lora-alpha"] = lora_alpha
    return out


def _merge_if_lora(params, flags: dict, what: str,
                   flag_name: str = "--lora-alpha"):
    """A checkpoint written by a --lora run carries adapter entries; fold
    them into dense weights before serving.  alpha must MATCH training
    (it scales the adapters), so it is demanded explicitly rather than
    silently defaulted.  ``flag_name`` is the user-facing flag that
    feeds this dict — --draft-lora-alpha for a DRAFT checkpoint."""
    from ..models.lora import lora_names, merge_lora

    if not lora_names(params):
        return params, what
    if not flags.get("lora-alpha"):
        raise SystemExit(
            f"{what} contains LoRA adapters; pass {flag_name}=A (the "
            f"ALPHA the run trained with, e.g. --lora=8:16 -> 16) to "
            f"merge them for serving")
    alpha = float(flags["lora-alpha"])
    return (merge_lora(params, alpha=alpha),
            f"{what} (LoRA merged, alpha {alpha:g})")


def load_params(flags: dict, model, seed: int,
                lora_flag: str = "--lora-alpha"):
    """Resolve the parameter source; returns (params, description).
    ``lora_flag`` names the user-facing alpha flag in merge errors
    (draft call sites pass --draft-lora-alpha)."""
    if flags.get("ckpt"):
        from ..checkpoint import codec
        epoch, iteration, params = codec.load(flags["ckpt"])
        return _merge_if_lora(
            params, flags,
            f"host checkpoint {flags['ckpt']} (iter {iteration})",
            lora_flag)
    if flags.get("ckpt-dir"):
        from ..checkpoint import sharded as sc
        avg_k = int(flags.get("avg-last", 0))
        if avg_k > 1:
            have = min(avg_k, len(sc._committed_steps(flags["ckpt-dir"])))
            step, state = sc.average_checkpoints(flags["ckpt-dir"], avg_k)
            what = f"average of last {have} checkpoints (newest step {step})"
        else:
            step, state = sc.restore_latest(flags["ckpt-dir"])
            what = f"sharded checkpoint step {step}"
        if step is None:
            raise FileNotFoundError(
                f"no step_N checkpoints under {flags['ckpt-dir']!r}")
        params = state["params"] if isinstance(state, dict) else state.params
        if avg_k > 1:
            from ..models.lora import lora_names
            if lora_names(params):
                # averaging A and B independently then merging computes
                # W + s*mean(A)@mean(B), which equals NONE of the
                # averaged models (the product is nonlinear in (A, B))
                raise SystemExit(
                    "--avg-last cannot average LoRA checkpoints (A@B is "
                    "nonlinear in the factors); merge each checkpoint "
                    "first (models.lora.merge_lora) or drop --avg-last")
        return _merge_if_lora(params, flags, what, lora_flag)
    return model.init_params(seed), f"fresh init (seed {seed})"


def match_layout(model, params):
    """Checkpoints port across layer layouts: convert a store to whatever
    layout this model instance uses (stacked blocks/* for scan_layers,
    unrolled layer<i>/* otherwise)."""
    from ..models.transformer import stack_layers, unstack_layers

    stacked_store = any(n.startswith("blocks/") for n in params)
    if model.config.scan_layers and not stacked_store:
        return stack_layers(params, model.config.n_layers)
    if not model.config.scan_layers and stacked_store:
        return unstack_layers(params)
    return params


KNOWN_FLAGS = frozenset({
    "model", "dtype", "scan-layers", "no-scan-layers", "seed", "ckpt",
    "ckpt-dir", "avg-last", "tokens", "prompt", "top-k", "top-p", "beam",
    "temperature", "max-new", "lora-alpha", "draft-lora-alpha",
    "draft-model", "draft-ckpt", "draft-seed",
    "draft-len", "adaptive-draft", "draft-cost-ratio",
    "length-penalty", "hf-gpt2",
})


def load_hf(flags: dict):
    """--hf-gpt2=<local dir or hub name>: convert a transformers GPT-2
    checkpoint (models/hf.py) and use its own tokenizer.  Returns
    (model, params, tokenizer_or_None)."""
    import jax.numpy as jnp
    import transformers

    from ..models.hf import from_hf_gpt2
    from ..models.registry import resolve_dtype

    src = flags["hf-gpt2"]
    hf_model = transformers.GPT2LMHeadModel.from_pretrained(src)
    dtype_flag = flags.get("dtype", "")
    dtype = resolve_dtype(dtype_flag) if dtype_flag else jnp.float32
    model, params = from_hf_gpt2(
        hf_model, dtype=dtype, scan_layers=("scan-layers" in flags))
    try:
        tok = transformers.AutoTokenizer.from_pretrained(src)
    except Exception:  # noqa: BLE001 — tokenizer files may be absent
        tok = None
    return model, params, tok


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    _, flags = parse_argv(argv)
    if "help" in flags:
        print(__doc__)
        return 0
    # bare --lora-alpha would merge with alpha 1 instead of the trained
    # value, silently mis-scaling every adapter
    require_flag_value(argv, "--lora-alpha", "--draft-lora-alpha",
                       "--draft-cost-ratio",
                       hint="the ALPHA the run trained with")
    unknown = set(flags) - KNOWN_FLAGS
    if unknown:
        # same contract as pst-train: a typo'd flag silently falling back
        # to its default corrupts results invisibly — fail loudly
        raise SystemExit(f"unknown flag(s): {', '.join(sorted(unknown))}; "
                         f"--help lists the accepted flags")

    import numpy as np

    from ..data.text import ByteTokenizer
    from ..models.generation import generate
    from ..models.registry import get_model_and_batches
    from ..models.transformer import Transformer

    seed = int(flags.get("seed", 0))
    hf_tok = None
    if flags.get("hf-gpt2"):
        if flags.get("ckpt") or flags.get("ckpt-dir"):
            raise ValueError("--hf-gpt2 provides its own weights; it does "
                             "not combine with --ckpt/--ckpt-dir")
        model, params, hf_tok = load_hf(flags)
        print(f"params: HF GPT-2 checkpoint {flags['hf-gpt2']} "
              f"({model.num_params() / 1e6:.1f}M params)", file=sys.stderr)
    else:
        model, _ = get_model_and_batches(
            flags.get("model", "small_lm"), 1, dtype=flags.get("dtype", ""),
            scan=(False if "no-scan-layers" in flags
                  else True if "scan-layers" in flags else None))
        if not isinstance(model, Transformer):
            raise ValueError(f"--model={flags.get('model')!r} is not an LM")
        params, source = load_params(flags, model, seed)
        print(f"params: {source}", file=sys.stderr)
        params = match_layout(model, params)

    tokenizer = ByteTokenizer()
    if flags.get("tokens"):
        ids = [int(t) for t in flags["tokens"].split(",")]
        decode_text = False
    elif hf_tok is not None:
        prompt_text = flags.get("prompt", "hello")
        ids = hf_tok.encode(prompt_text)
        decode_text = True
    elif flags.get("hf-gpt2"):
        raise ValueError("--hf-gpt2 checkpoint has no tokenizer files; "
                         "pass raw ids via --tokens=1,2,3")
    else:
        from ..data.text import require_vocab
        prompt_text = flags.get("prompt", "hello")
        require_vocab(model.config.vocab, tokenizer)
        ids = tokenizer.encode(prompt_text) or [tokenizer.BOS]
        decode_text = True
    if any(not 0 <= t < model.config.vocab for t in ids):
        raise ValueError(f"token id out of range for vocab "
                         f"{model.config.vocab}")

    top_k = int(flags.get("top-k", 0))
    top_p = float(flags.get("top-p", 0.0))
    beam = int(flags.get("beam", 0))
    # sampling flags imply sampling: temperature 0 (greedy) would silently
    # ignore top-k/top-p, so they default the temperature to 1.0
    default_temp = "1.0" if (top_k or top_p) else "0.0"
    temperature = float(flags.get("temperature", default_temp))
    prompt = np.asarray([ids], np.int32)
    max_new = int(flags.get("max-new", 64))
    draft_name = flags.get("draft-model", "")
    if beam <= 1 and "length-penalty" in flags:
        raise ValueError("--length-penalty applies to beam search; "
                         "pass --beam=W > 1")
    if draft_name:
        if beam > 1 or top_k or top_p:
            raise ValueError("--draft-model (speculative decoding) "
                             "supports greedy (default) or plain "
                             "--temperature sampling; it does not combine "
                             "with --beam/--top-k/--top-p")
        from ..models.generation import speculative_generate_batched
        draft, _ = get_model_and_batches(draft_name, 1,
                                         dtype=flags.get("dtype", ""))
        if not isinstance(draft, Transformer):
            raise ValueError(f"--draft-model={draft_name!r} is not an LM")
        dparams, dsource = load_params(
            draft_ckpt_flags(flags.get("draft-ckpt", ""),
                             flags.get("draft-lora-alpha", "")), draft,
            int(flags.get("draft-seed", seed + 1)),
            lora_flag="--draft-lora-alpha")
        dparams = match_layout(draft, dparams)
        print(f"draft params: {dsource}", file=sys.stderr)
        # whole-loop-on-device batched decoder (accept/resample jitted,
        # per-row ragged caches) — the serving path; the host-loop
        # speculative_generate stays as the tested reference
        # --adaptive-draft: --draft-len becomes the CAP; the first call
        # runs measured spec-vs-greedy probes and memoizes the winning
        # depth (one-shot CLI calls pay the calibration, so fixed depth
        # stays the default here — servers and repeated callers benefit)
        adaptive = "adaptive-draft" in flags
        rho = draft_cost_ratio(flags, draft, model)
        out, stats = speculative_generate_batched(
            model, params, draft, dparams, prompt, max_new,
            draft_len=int(flags.get("draft-len", 4)),
            temperature=temperature, seed=seed, adaptive=adaptive,
            draft_cost_ratio=rho)
        depth_note = (f", settled depth {stats['draft_depth']}"
                      if adaptive else "")
        print(f"speculative: {stats['tokens_per_target_forward']:.2f} "
              f"tokens/target-forward (incl. prefill), accept rate "
              f"{stats['draft_accept_rate']:.2f}{depth_note}",
              file=sys.stderr)
    elif beam > 1:
        if top_k or top_p or "temperature" in flags:
            raise ValueError("--beam is deterministic; it does not combine "
                             "with --temperature/--top-k/--top-p")
        from ..models.generation import beam_search
        # text mode: the tokenizer's EOS finishes beams early
        # (require_vocab above guaranteed the byte vocab is covered);
        # raw-token mode has no reserved stop id, HF or not
        if not decode_text:
            eos = None
        elif hf_tok is not None:
            eos = hf_tok.eos_token_id
        else:
            eos = tokenizer.EOS
        out, score = beam_search(
            model, params, prompt, max_new, beam_width=beam, eos_id=eos,
            length_penalty=float(flags.get("length-penalty", 0.0)))
        print(f"beam: width {beam}, joint logprob "
              f"{float(np.asarray(score)[0]):.3f}", file=sys.stderr)
    else:
        out = generate(model, params, prompt, max_new,
                       temperature=temperature, top_k=top_k, top_p=top_p,
                       rng=seed)
    tokens = np.asarray(out)[0]
    if decode_text:
        eos_id = (hf_tok.eos_token_id if hf_tok is not None
                  else tokenizer.EOS)
        stop = np.nonzero(tokens == eos_id)[0]
        if stop.size:  # trim at the first EOS (beam padding or natural)
            tokens = tokens[:int(stop[0])]
        text = (hf_tok.decode(tokens) if hf_tok is not None
                else tokenizer.decode(tokens))
        print(text, flush=True)
    else:
        print(",".join(str(int(t)) for t in tokens), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
