"""Worker process entry point.

Argv contract mirrors the reference (reference: src/worker_main.cpp:6-18):

    python -m parameter_server_distributed_tpu.cli.worker_main \
        [coordinator_addr] [worker_id] [iterations] [worker_addr]
        [worker_port] [checkpoint_path] [flags...]

A non-empty checkpoint_path triggers a restore request at startup, tolerant
of failure (reference: src/worker_main.cpp:28-38).

Extension flags:
    --model=NAME     model from the registry (default mnist_mlp)
    --batch=N        per-worker batch size (default 32)
    --seed=N         data seed (defaults to worker_id so shards differ)
    --data=PATH      file-backed dataset (token .bin for LMs, npz x/y
                     otherwise); default synthetic
    --wire=ENC       tensor payload encoding: f32 (reference-compatible,
                     default), raw, bf16 (half the push/pull bytes),
                     int8 (quarter-size error-feedback gradient pushes,
                     bf16 pulls; requires a framework PS), or topk
                     (top-k sparsified pushes at --topk-density, unsent
                     mass carried by error feedback; bf16 pulls)
    --topk-density=F fraction of entries a topk push keeps (default 0.01)
    --dtype=bf16     model compute dtype (factories that take one)
    --remat / --no-remat / --scan-layers / --no-scan-layers
                     transformer LM layer-loop knobs (same semantics as
                     pst-train; absent = model default)
    --mesh=SPEC      intra-worker MODEL parallelism over the worker's
                     local chips (e.g. fsdp:2,data:2 or tensor:4): params
                     are sharding-constrained inside the jitted step, so
                     a model too big for one chip still speaks plain PS.
                     Default: pure local data parallelism over all chips
    --no-fused       disable the fused PushPullStream data plane (one RPC
                     round per step, docs/training.md) and run the
                     reference-shaped serial push/poll/pull protocol
    --tiers / --no-tiers
                     join (or refuse) the coordinator's two-tier
                     hierarchical-aggregation topology (tiers/): same-host
                     workers fold locally at an elected leaf aggregator,
                     one quantized contribution per group goes upstream.
                     Absent = PSDT_TIERS env (default off)
    --freerun        free-running barrier-free loop (freerun/,
                     docs/training.md "Free-running async training"):
                     push, pull whatever version the PS has published,
                     step again — never polls a barrier.  Pair with a
                     --freerun PS.  Absent = PSDT_FREERUN env
"""

from __future__ import annotations

import logging
import signal
import sys

from .. import freerun as freerun_mod
from ..config import WorkerConfig, parse_argv
from ..models.registry import get_model_and_batches
from ..worker.trainer import Trainer
from ..worker.worker import Worker


def build_worker(config: WorkerConfig, seed: int | None = None) -> Worker:
    data_seed = config.worker_id if seed is None else seed
    model, batches = get_model_and_batches(config.model, config.batch_size,
                                           seed=data_seed,
                                           data_path=config.data_path,
                                           dtype=config.model_dtype,
                                           remat=config.remat,
                                           scan=config.scan_layers)
    mesh_config = rule_fn = None
    if config.mesh:
        from .train_main import parse_mesh
        from ..parallel.train_loop import _pick_rule

        mesh_config = parse_mesh(config.mesh)
        if mesh_config.pipeline > 1 or mesh_config.sequence > 1:
            # pipe needs the schedule machinery (pst-train); seq has no
            # param rule here — accepting it would leave chips silently
            # doing replicated work
            raise ValueError(
                "worker --mesh supports data/fsdp/tensor/expert axes; "
                "use pst-train for pipeline or sequence parallelism")
        rule_fn = lambda mesh: _pick_rule(config.model, mesh)  # noqa: E731
    return Worker(config, Trainer(model, mesh_config=mesh_config,
                                  rule_fn=rule_fn), batches)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    positional, flags = parse_argv(argv)
    config = WorkerConfig(
        coordinator_address=positional[0] if len(positional) > 0 else "127.0.0.1:50052",
        worker_id=int(positional[1]) if len(positional) > 1 else 0,
        iterations=int(positional[2]) if len(positional) > 2 else 10,
        address=positional[3] if len(positional) > 3 else "127.0.0.1",
        port=int(positional[4]) if len(positional) > 4 else 50060,
        checkpoint_path=positional[5] if len(positional) > 5 else "",
        model=flags.get("model", "mnist_mlp"),
        batch_size=int(flags.get("batch", 32)),
        model_dtype=flags.get("dtype", ""),
        remat=(False if "no-remat" in flags
               else True if "remat" in flags else None),
        scan_layers=(False if "no-scan-layers" in flags
                     else True if "scan-layers" in flags else None),
        data_path=flags.get("data", ""),
        wire_dtype=flags.get("wire", "f32"),
        # omit when unset so WorkerConfig's default governs (one owner)
        **({"topk_density": float(flags["topk-density"])}
           if "topk-density" in flags else {}),
        mesh=flags.get("mesh", ""),
        fused_step="no-fused" not in flags,
        tiers=(False if "no-tiers" in flags
               else True if "tiers" in flags else None),
        freerun="freerun" in flags or freerun_mod.enabled(),
    )
    worker = build_worker(config, seed=int(flags["seed"]) if "seed" in flags else None)
    worker.initialize()

    if config.checkpoint_path:
        # tolerant of failure, like the reference (src/worker_main.cpp:28-38)
        try:
            worker.load_checkpoint_from_server(config.checkpoint_path)
        except Exception as exc:  # noqa: BLE001
            logging.warning("checkpoint restore failed (continuing): %s", exc)

    # Graceful preemption (elastic/, ISSUE 13): the FIRST SIGTERM
    # latches a drain instead of killing the process mid-stream — the
    # in-flight iteration completes, the loop below stops, and
    # shutdown() deregisters so the barrier narrows at the next width
    # refresh.  A SECOND SIGTERM escalates: a worker wedged
    # mid-iteration (unreachable PS, barrier timeout) must still be
    # killable without resorting to kill -9.  (Replaces — does not
    # chain — any earlier handler: both exits run through the normal
    # path/atexit, which stamps the flight ring clean.)
    def _on_sigterm(_signum, _frame):
        if worker.drain_requested:
            logging.warning("worker %d: second SIGTERM — exiting now",
                            config.worker_id)
            raise SystemExit(143)
        logging.warning("worker %d: SIGTERM — draining after the "
                        "in-flight iteration", config.worker_id)
        worker.request_drain()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded use)

    try:
        for i in range(config.iterations):
            if worker.drain_requested:
                print(f"Worker {config.worker_id} draining: deregistering "
                      f"after iteration {worker.iteration}", flush=True)
                break
            it = max(i, worker.iteration + 1)
            loss = worker.run_iteration(it)
            desc = "bootstrap: seeded PS init" if worker.last_bootstrap \
                else f"loss {loss:.4f}"
            print(f"Worker {config.worker_id} completed iteration {it} "
                  f"({desc})", flush=True)
    finally:
        worker.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
