"""SPMD training CLI (pure-collectives mode, no PS process).

    python -m parameter_server_distributed_tpu.cli.train_main \
        --model=mnist_mlp --steps=100 --batch=64 --optimizer=adam --lr=1e-3 \
        --schedule=cosine --warmup=10 --clip-norm=1.0 --accum=2 \
        --data=/data/train.npz \
        --mesh=data:2,fsdp:2,tensor:2 --ckpt-dir=/tmp/ckpt --ckpt-every=50 \
        --ckpt-keep=3 --resume --metrics=/tmp/metrics.jsonl

``--attention=dense|flash|xla_flash|ring|ulysses|ulysses_flash|
ulysses_xla_flash`` selects the attention implementation for transformer
models: flash = pallas kernels (shard_mapped over batch/head shards when
the mesh is >1 device), xla_flash = the same blockwise recurrence as a
compiled lax.scan (any backend), ring/ulysses = sequence parallelism
over the mesh's seq axis (pair with --mesh=seq:N); ulysses_flash /
ulysses_xla_flash run the pallas kernel / the lax.scan recurrence on
each device's gathered full sequence.

``--dtype=bf16`` trains in bfloat16 (f32 MXU accumulation) for models
whose factory takes a dtype; ``--remat`` recomputes layer activations in
the backward pass (jax.checkpoint, transformer LMs) — the long-context
memory/FLOPs trade.  ``--no-remat`` forces it off for models that default
it on (lm_350m); neither flag keeps the model's default.
``--scan-layers`` / ``--no-scan-layers`` likewise force lax.scan over
stacked layer weights (depth-independent compile time) or the unrolled
loop (cross-layer XLA fusion) for transformer LMs.
``--remat-policy=full|dots`` picks what remat may keep (flagship LMs):
full recomputes the whole layer, dots saves the projection/MLP matmul
outputs and recomputes only the attention einsums (~5% extra FLOPs
instead of ~33%, for O(L·S·d) saved activations).  ``--lora=R[:ALPHA]``
switches to LoRA fine-tuning: rank-R adapters on the attention q/v
projections are the ONLY trainable parameters (base weights frozen, no
optimizer state allocated for them — models/lora.py; merge with
``models.lora.merge_lora`` for serving).  ``--seq=N``
overrides the LM sequence length (long-context runs; synthetic token
streams follow the model).

``--hf-gpt2=<checkout>`` / ``--hf-llama=<checkout>`` train the
CONVERTED transformers checkpoint instead of a registry preset
(models/hf.from_hf_gpt2 / from_hf_llama): the converted weights are the
initializer, ``--data`` feeds it (synthetic crops otherwise), and both
compose with ``--lora``, ``--ema``, and a ``pipe`` mesh axis — the
fine-tune flow for models the reference ecosystem ships (llama
conversions are the native arch, so every schedule applies).

``--mesh=pipe:P`` trains transformer models with pipeline parallelism
(parallel/pipeline.py): layer blocks live on their pipe rank,
microbatches stream through; ``--microbatches=M`` sets the schedule depth
(default P).  ``--pipeline-schedule=gpipe|1f1b`` picks the schedule:
gpipe (all forwards then all backwards via autodiff) or 1f1b (interleaved
one-forward-one-backward — O(P) instead of O(M) in-flight activations).
``--virtual-stages=V`` (with 1f1b) runs the Megatron INTERLEAVED
schedule: each rank holds V round-robin layer chunks, shrinking the
pipeline bubble ~V-fold at V x the ppermute count.  Requires n_layers
divisible by P*V; combine with data:N.  ``--attention`` may be dense or
flash inside pipeline stages.

``--ema=0.999`` tracks a Polyak/EMA shadow of the parameters at that
decay inside the optimizer state (checkpointed and sharded like any
slot); with ``--eval-every`` the final summary reports
``ema_eval_loss`` next to the raw ``eval_loss``.

``--data`` switches from synthetic loaders to file-backed data
(data/files.py): a token shard (.bin/.u32 memmap) for LM models, an npz
with x/y arrays otherwise.  ``--eval-every=N`` runs a held-out
evaluation (mean loss over ``--eval-steps`` batches, no updates) every N
steps and at the end; ``--eval-data`` points it at a held-out file,
otherwise a shifted-seed synthetic stream is used.

The mesh spec names axes explicitly; unnamed axes default to 1.  For
multi-host runs set --coordinator=HOST:PORT --num-processes=N
--process-id=I (or run on a TPU pod where jax.distributed auto-configures).
``--per-process-data`` switches multi-host runs to per-process loading:
each host draws only batch/N rows at an independent seed and JAX stitches
the global batch from the local shards — no host materializes the full
batch (the scalable data path; default keeps every host loading the same
deterministic global batch).
"""

from __future__ import annotations

import json
import logging
import sys

from ..config import MeshConfig, parse_argv, require_flag_value


def parse_mesh(spec: str) -> MeshConfig:
    if not spec:
        return MeshConfig()
    names = {"data", "fsdp", "tensor", "sequence", "pipeline", "expert",
             "seq", "pipe"}
    alias = {"seq": "sequence", "pipe": "pipeline"}
    kwargs = {}
    for part in spec.split(","):
        name, _, size = part.partition(":")
        name = name.strip()
        if name not in names:
            raise ValueError(f"unknown mesh axis {name!r}")
        if not size.strip().isdigit():
            raise ValueError(
                f"mesh axis {name!r} needs an integer size, e.g. "
                f"'{name}:2' (got {part!r})")
        kwargs[alias.get(name, name)] = int(size)
    return MeshConfig(**kwargs)


KNOWN_FLAGS = frozenset({
    "model", "hf-gpt2", "hf-llama", "batch", "data", "seq", "eval-every",
    "eval-steps", "eval-data",
    "per-process-data", "prefetch", "attention", "microbatches",
    "pipeline-schedule", "virtual-stages", "dtype", "remat", "no-remat",
    "scan-layers", "remat-policy", "lora", "init-ckpt-dir", "ema",
    "no-scan-layers", "steps", "optimizer", "lr", "schedule", "warmup",
    "clip-norm", "accum", "mesh", "ckpt-dir", "ckpt-every", "ckpt-keep",
    "log-every", "seed", "resume", "metrics", "coordinator",
    "num-processes", "process-id",
})


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    _, flags = parse_argv(argv)
    if "help" in flags:
        print(__doc__)
        return 0
    unknown = set(flags) - KNOWN_FLAGS
    if unknown:
        # a typo'd flag silently falling back to its default is how a 64x
        # batch lands in a benchmark unnoticed — fail loudly instead
        raise SystemExit(f"unknown flag(s): {', '.join(sorted(unknown))}; "
                         f"--help lists the accepted flags")

    if "model" in flags and ("hf-gpt2" in flags or "hf-llama" in flags):
        raise SystemExit("--model and --hf-gpt2/--hf-llama both pick the "
                         "model; pass one (the converted checkpoint "
                         "defines its own architecture)")
    # a bare --lora would silently run a near-useless rank-1 adapter
    # (parse_argv's "1" sentinel); --lora=1 stays a deliberate choice
    require_flag_value(argv, "--lora",
                       hint="the R[:ALPHA] spec, e.g. --lora=8 or "
                            "--lora=8:16")
    if "coordinator" in flags or int(flags.get("num-processes", 1)) > 1:
        from ..parallel.distributed import initialize_multihost
        initialize_multihost(
            coordinator_address=flags.get("coordinator"),
            num_processes=int(flags.get("num-processes", 1)),
            process_id=int(flags.get("process-id", 0)))

    from ..parallel.train_loop import TrainLoopConfig, run_training

    config = TrainLoopConfig(
        model=flags.get("model", "mnist_mlp"),
        hf_gpt2=flags.get("hf-gpt2", ""),
        hf_llama=flags.get("hf-llama", ""),
        batch_size=int(flags.get("batch", 64)),
        data_path=flags.get("data", ""),
        seq_len=int(flags.get("seq", 0)),
        eval_every=int(flags.get("eval-every", 0)),
        eval_steps=int(flags.get("eval-steps", 4)),
        eval_data_path=flags.get("eval-data", ""),
        per_process_data="per-process-data" in flags,
        prefetch=int(flags.get("prefetch", 2)),
        attention=flags.get("attention", "dense"),
        microbatches=int(flags.get("microbatches", 0)),
        pipeline_schedule=flags.get("pipeline-schedule", "gpipe"),
        virtual_stages=int(flags.get("virtual-stages", 1)),
        model_dtype=flags.get("dtype", ""),
        remat=(False if "no-remat" in flags
               else True if "remat" in flags else None),
        scan_layers=(False if "no-scan-layers" in flags
                     else True if "scan-layers" in flags else None),
        remat_policy=flags.get("remat-policy", ""),
        lora=flags.get("lora", ""),
        init_ckpt_dir=flags.get("init-ckpt-dir", ""),
        ema=float(flags.get("ema", 0.0)),
        steps=int(flags.get("steps", 100)),
        optimizer=flags.get("optimizer", "adam"),
        learning_rate=float(flags.get("lr", 1e-3)),
        schedule=flags.get("schedule", "constant"),
        warmup_steps=int(flags.get("warmup", 0)),
        clip_norm=float(flags.get("clip-norm", 0.0)),
        accum_steps=int(flags.get("accum", 1)),
        mesh=parse_mesh(flags.get("mesh", "")),
        checkpoint_dir=flags.get("ckpt-dir", ""),
        checkpoint_every=int(flags.get("ckpt-every", 0)),
        checkpoint_keep=int(flags.get("ckpt-keep", 0)),
        log_every=int(flags.get("log-every", 10)),
        seed=int(flags.get("seed", 0)),
        resume="resume" in flags,
        metrics_path=flags.get("metrics", ""),
    )
    summary = run_training(config)
    print(json.dumps(summary, default=float), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
