"""`pst-analyze`: the project's concurrency & wire-protocol analyzer.

    python -m parameter_server_distributed_tpu.cli.analyze_main \
        [root_dir] [--json] [--baseline=PATH] [--manifest=PATH] \
        [--no-wire] [--no-ext] [--no-knobs] [--no-events] \
        [--no-interproc] [--write-wire-manifest] \
        [--write-ext-manifests] [--write-knob-registry]

Runs the static passes (lock discipline — including the interprocedural
held-set propagation, exception hygiene, thread hygiene, extension
protocol, knob registry, flight events) over the package source and
diffs the live wire contract against the golden manifest
(analysis/wire_manifest.json).  Exit 0 when every finding is covered by
the reviewed baseline (analysis/baseline.json), 1 otherwise — wire this
into CI next to the tier-1 tests (scripts/analyze.sh).  See
docs/analysis.md for the pass catalogue, the declared lock-order table,
and the baseline / manifest / registry workflows.

``--write-wire-manifest`` regenerates the golden wire manifest from the
current schemas and exits; ``--write-ext-manifests`` does the same for
the per-extension protocol manifests (analysis/ext_manifests.json) and
``--write-knob-registry`` for the PSDT_* knob registry
(analysis/knob_registry.json) — run the matching writer (and commit the
result) as part of any deliberate protocol / knob change.
"""

from __future__ import annotations

import sys

from ..config import parse_argv


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # tooling runs must not deposit flight rings into a cluster's
    # PSDT_FLIGHT_DIR evidence directory (obs/flight.py)
    from ..obs import flight
    flight.suppress_for_tool()
    positional, flags = parse_argv(argv)

    from ..analysis import extcheck, knobcheck, runner, wirecheck

    manifest_path = flags.get("manifest") or None
    if "write-wire-manifest" in flags:
        path = wirecheck.write_manifest(manifest_path)
        print(f"wire manifest written: {path}")
        return 0
    if "write-ext-manifests" in flags:
        path = extcheck.write_manifests(
            flags.get("ext-manifest") or None,
            root=positional[0] if positional else None)
        print(f"extension manifests written: {path}")
        return 0
    if "write-knob-registry" in flags:
        path = knobcheck.write_registry(
            flags.get("knob-registry") or None,
            root=positional[0] if positional else None)
        print(f"knob registry written: {path}")
        return 0

    report = runner.run(
        root=positional[0] if positional else None,
        baseline_path=flags.get("baseline") or None,
        manifest_path=manifest_path,
        wire="no-wire" not in flags,
        ext="no-ext" not in flags,
        knobs="no-knobs" not in flags,
        events="no-events" not in flags,
        interproc="no-interproc" not in flags,
        ext_manifest_path=flags.get("ext-manifest") or None,
        knob_registry_path=flags.get("knob-registry") or None,
    )
    if "json" in flags:
        print(runner.to_json_str(report))
    else:
        print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
