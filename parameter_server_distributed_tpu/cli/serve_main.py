"""Continuous-batching serving process (`pst-serve`) — the operational
face of models/serving.DecodeServer.

    pst-serve --model=small_lm [--ckpt=... | --ckpt-dir=... |
              --hf-gpt2=<checkout>] \\
              [--slots=8] [--max-len=2048] [--temperature=0.8 --top-k=40] \\
              [--quant=int8] [--kv-cache=int8] [--eos=ID] \\
              [--prompt-cache=N]   # repeated prompts skip prefill (LRU)
              [--draft-model=tiny_lm --draft-ckpt=... --draft-len=4]
              [--no-adaptive-draft] [--draft-cost-ratio=R]
              [--fused-rounds=N]  # amortize N decode rounds per device
                                  # dispatch when no requests are waiting
                                  # (token-exact; higher throughput,
                                  # blockier streaming)
              # speculative serving: --draft-len is the depth CAP; the
              # server adapts per-round depth from the measured accept
              # rate (disabling speculation when it cannot pay) unless
              # --no-adaptive-draft pins it

Line protocol (JSONL on stdin/stdout — composable behind any transport):

    -> {"id": 1, "prompt": "hello"}             # or "tokens": [1,2,3]
    -> {"id": 2, "tokens": [5,6], "max_new": 32}
    -> {"id": 4, "prompt": "hi", "temperature": 0.7, "stop": [13]}
    <- {"id": 1, "token": 42}                   # streamed as decoded
    <- {"id": 1, "done": true, "text": "..."}   # or "tokens": [...]
    <- {"id": 3, "error": "..."}                # bad request

Per-request "temperature" overrides the server default for that request
only (temperatures are a traced per-slot input — mixed batches share one
compiled step; rejected in speculative mode, where the accept rule is
compiled for the server temperature).  "stop": [ids...] finishes that
request at any of the listed tokens, alongside the global --eos.

Requests are admitted the moment a slot frees (continuous batching — one
compiled ragged decode step serves every in-flight request); stdin close
drains the in-flight work and exits.  Reference has no serving runtime at
all (no model, no inference — reference src/worker.cpp:316-329); this
completes the train -> checkpoint -> serve loop as a process main in the
reference's CLI style (component #10, SURVEY.md §2).
"""

from __future__ import annotations

import json
import logging
import queue
import sys
import threading
import time

from ..config import parse_argv, require_flag_value
from ..obs import flight

KNOWN_FLAGS = frozenset({
    "model", "dtype", "scan-layers", "no-scan-layers", "seed", "ckpt",
    "ckpt-dir", "avg-last", "hf-gpt2", "slots", "max-len", "temperature",
    "top-k", "top-p", "eos", "quant", "kv-cache", "default-max-new",
    "lora-alpha", "draft-lora-alpha", "prompt-cache",
    "draft-model", "draft-ckpt", "draft-seed", "draft-len",
    "no-adaptive-draft", "draft-cost-ratio", "fused-rounds",
    "follow", "subscriber-id",
    # decode fleet mode (fleet/, ISSUE 14): serve the psdt_fleet.Decode
    # gRPC service instead of the stdin/stdout line protocol, and
    # (optionally) register with a coordinator for routing/autoscaling
    "serve-port", "coordinator", "server-id",
})


def _reader(out_q: "queue.Queue[tuple | None]") -> None:
    """stdin -> request queue as TYPED items — ("req", dict) or
    ("err", message) — with None marking end of input.  The out-of-band
    tag means no request payload can alias the error channel (an in-band
    magic key could), a valid-JSON scalar/array becomes a per-line error
    instead of crashing the loop, and a `null` line can never be confused
    with the EOF sentinel."""
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as exc:
            out_q.put(("err", str(exc)))
            continue
        if not isinstance(obj, dict):
            out_q.put(("err",
                       f"request must be a JSON object, got {line[:80]!r}"))
            continue
        out_q.put(("req", obj))
    out_q.put(None)


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s "
                               "%(message)s")
    _, flags = parse_argv(argv)
    if "help" in flags:
        print(__doc__)
        return 0
    # bare --lora-alpha would merge with alpha 1 instead of the trained
    # value, silently mis-scaling every adapter
    require_flag_value(argv, "--lora-alpha", "--draft-lora-alpha",
                       hint="the ALPHA the run trained with")
    require_flag_value(argv, "--draft-cost-ratio",
                       hint="draft/target per-token cost for the "
                            "adaptive depth controller")
    # bare --fused-rounds would parse as 1 and silently disable the
    # feature the user asked for
    require_flag_value(argv, "--fused-rounds",
                       hint="decode rounds per device dispatch, e.g. "
                            "--fused-rounds=8")
    # bare --follow would silently serve boot weights forever
    require_flag_value(argv, "--follow",
                       hint="the training PS address to track, e.g. "
                            "--follow=10.0.0.5:50051")
    # bare --serve-port parses as 1 and binds an arbitrary low port;
    # bare --coordinator would register against localhost silently
    require_flag_value(argv, "--serve-port", "--coordinator",
                       "--server-id",
                       hint="fleet mode, e.g. --serve-port=50070 "
                            "--coordinator=10.0.0.5:50052 --server-id=0")
    unknown = set(flags) - KNOWN_FLAGS
    if unknown:
        raise SystemExit(f"unknown flag(s): {', '.join(sorted(unknown))}; "
                         f"--help lists the accepted flags")

    from ..models.serving import DecodeServer
    from .generate_main import load_hf, load_params, match_layout

    hf_tok = None
    if flags.get("hf-gpt2"):
        model, params, hf_tok = load_hf(flags)
        source = f"HF GPT-2 checkpoint {flags['hf-gpt2']}"
    else:
        from ..models.registry import get_model_and_batches
        from ..models.transformer import Transformer
        model, _ = get_model_and_batches(
            flags.get("model", "small_lm"), 1, dtype=flags.get("dtype", ""),
            scan=(False if "no-scan-layers" in flags
                  else True if "scan-layers" in flags else None))
        if not isinstance(model, Transformer):
            raise ValueError(f"--model={flags.get('model')!r} is not an LM")
        params, source = load_params(flags, model,
                                     int(flags.get("seed", 0)))
        params = match_layout(model, params)
    # one binding for both weight paths — the boot params here and every
    # follower hot swap below quantize identically or not at all
    quantize = None
    if flags.get("quant", "") == "int8":
        from ..models.quant import quantize_params as quantize
        params = quantize(params)
        source += " (int8 weights)"
    print(f"serving: {source}", file=sys.stderr)

    from ..data.text import ByteTokenizer
    tokenizer = ByteTokenizer()
    eos = int(flags["eos"]) if flags.get("eos") else (
        hf_tok.eos_token_id if hf_tok is not None else None)
    spec_kwargs: dict = {}
    if flags.get("draft-model"):
        # speculative continuous batching — greedy or plain --temperature
        # sampling (DecodeServer rejects top-k/top-p); same flag family
        # as pst-generate
        from ..models.registry import get_model_and_batches as _get
        from ..models.transformer import Transformer as _T
        draft, _ = _get(flags["draft-model"], 1,
                        dtype=flags.get("dtype", ""))
        if not isinstance(draft, _T):
            raise ValueError(f"--draft-model={flags['draft-model']!r} "
                             "is not an LM")
        from .generate_main import draft_ckpt_flags, draft_cost_ratio
        dparams, dsource = load_params(
            draft_ckpt_flags(flags.get("draft-ckpt", ""),
                             flags.get("draft-lora-alpha", "")), draft,
            int(flags.get("draft-seed", int(flags.get("seed", 0)) + 1)),
            lora_flag="--draft-lora-alpha")
        dparams = match_layout(draft, dparams)
        print(f"draft: {dsource}", file=sys.stderr)
        spec_kwargs = dict(
            draft=draft, draft_params=dparams,
            draft_len=int(flags.get("draft-len", "4")),
            # adaptive depth on by default (--draft-len is the cap);
            # --no-adaptive-draft pins it.  --draft-cost-ratio overrides
            # the param-count proxy for the controller's cost model
            adaptive_draft="no-adaptive-draft" not in flags,
            draft_cost_ratio=draft_cost_ratio(flags, draft, model))
    follower = None
    if flags.get("follow"):
        # live weight publication (delta/, ISSUE 10): subscribe to a
        # training PS and hot-swap fresh weight versions between
        # admissions.  Every failure mode degrades to serving the
        # last-good weights — the decode process never crashes or stalls
        # on the training side's health (delta/subscriber.py).
        import os as _os

        from ..delta.subscriber import WeightFollower
        follower = WeightFollower(
            flags["follow"],
            subscriber_id=int(flags.get("subscriber-id",
                                        str(_os.getpid() & 0x7FFF))))
        follower.start()
        print(f"following weights from {flags['follow']}",
              file=sys.stderr)

    srv = DecodeServer(
        model, params,
        slots=int(flags.get("slots", "8")),
        max_len=int(flags.get("max-len", "2048")),
        temperature=float(flags.get("temperature", "0.0")),
        top_k=int(flags.get("top-k", "0")),
        top_p=float(flags.get("top-p", "0.0")),
        eos_id=eos,
        cache_dtype=("int8" if flags.get("kv-cache", "") == "int8"
                     else "native"),
        # --prompt-cache=N: repeated prompts skip the prefill forward
        # (LRU of N prompts' logits + K/V rows; 0 = off)
        prompt_cache=int(flags.get("prompt-cache", "0")),
        seed=int(flags.get("seed", 0)), **spec_kwargs)
    default_max_new = int(flags.get("default-max-new", "64"))

    if flags.get("serve-port") is not None or flags.get("coordinator"):
        # ---- decode fleet mode (fleet/, ISSUE 14): gRPC service +
        # coordinator registration instead of the line protocol.  The
        # line-protocol path below is byte-unchanged without these flags
        # (the downgrade matrix: no router => single-server pst-serve).
        import signal

        from ..fleet.decode import FleetDecodeServer
        fds = FleetDecodeServer(
            srv,
            server_id=int(flags.get("server-id", "0")),
            port=int(flags.get("serve-port", "0")),
            coordinator=flags.get("coordinator") or None,
            follower=follower, transform=quantize)
        port = fds.start()
        print(f"decode fleet server {fds.server_id} on port {port}"
              + (f", registered with {flags['coordinator']}"
                 if flags.get("coordinator") else " (standalone)"),
              file=sys.stderr)
        # graceful preemption: SIGTERM drains (in-flight streams finish,
        # then the server leaves the fleet) — the scale-in path
        signal.signal(signal.SIGTERM, lambda *_: fds.drain())
        try:
            while not fds.wait_drained(0.5):
                pass
        except KeyboardInterrupt:
            fds.drain()
            fds.wait_drained(10.0)
        fds.stop()
        print(f"serving stats: {json.dumps(srv.stats)}", file=sys.stderr)
        return 0

    in_q: "queue.Queue[dict | None]" = queue.Queue()
    threading.Thread(target=_reader, args=(in_q,), daemon=True,
                     name="pst-serve-stdin").start()

    pending: list[dict] = []          # parsed, awaiting a free slot
    fused_rounds = int(flags.get("fused-rounds", "1"))
    live: dict[int, dict] = {}        # request_id -> request (slot-held)
    text_mode: dict[int, bool] = {}
    eof = False

    def finish(req: dict, tokens: list[int], is_text: bool) -> None:
        done: dict = {"id": req.get("id"), "done": True}
        if is_text:
            # the terminator — global eos or a per-request stop token —
            # is metadata, not content: trim it from the decoded text
            # (admit() already rejected non-list "stop" fields)
            enders = {int(t) for t in req.get("stop") or ()}
            if eos is not None:
                enders.add(eos)
            cut = [i for i, t in enumerate(tokens) if t in enders]
            trim = tokens[:cut[0]] if cut else tokens
            done["text"] = (hf_tok.decode(trim) if hf_tok is not None
                            else tokenizer.decode(trim))
        else:
            done["tokens"] = tokens
        _emit(done)

    def finish_run() -> int:
        if follower is not None:
            follower.stop()
            if follower.degraded:
                print(f"weight follower degraded: "
                      f"{follower.degrade_reason} (kept serving version "
                      f"{follower.version})", file=sys.stderr)
        print(f"serving stats: {json.dumps(srv.stats)}", file=sys.stderr)
        return 0

    def maybe_swap() -> None:
        """Hot-swap the newest complete weight version (if any) between
        admissions.  A bad publication (shape/name drift after a model
        change upstream) must never kill serving — the server keeps the
        last-good weights and says so."""
        if follower is None:
            return
        fresh = follower.poll()
        if fresh is None:
            return
        store, version = fresh
        t0 = time.perf_counter()
        try:
            srv.swap_params(quantize(store) if quantize else store)
        except Exception as exc:  # noqa: BLE001 — serving boundary: keep
            # decoding on the last-good weights whatever the feed sends
            print(f"weight swap to version {version} failed ({exc}); "
                  f"keeping last-good weights", file=sys.stderr)
            return
        flight.record("publish.swap", a=version,
                      b=int(1e6 * (time.perf_counter() - t0)))
        print(f"weights: swapped to version {version}", file=sys.stderr)

    def admit() -> None:
        while pending and srv.has_free_slot:
            req = pending.pop(0)
            rid_key = req.get("id")
            try:
                if "tokens" in req:
                    ids = [int(t) for t in req["tokens"]]
                    is_text = False
                elif "prompt" in req:
                    if hf_tok is not None:
                        ids = hf_tok.encode(req["prompt"])
                    else:
                        from ..data.text import require_vocab
                        require_vocab(model.config.vocab, tokenizer)
                        ids = (tokenizer.encode(req["prompt"])
                               or [tokenizer.BOS])
                    is_text = True
                else:
                    raise ValueError("request needs 'prompt' or 'tokens'")
                temp = req.get("temperature")
                stop_field = req.get("stop", [])
                if not isinstance(stop_field, list):
                    # a JSON string would silently iterate per character
                    raise ValueError("'stop' must be an array of token ids")
                rid = srv.submit(
                    ids, int(req.get("max_new", default_max_new)),
                    temperature=None if temp is None else float(temp),
                    stop=[int(t) for t in stop_field])
            except Exception as exc:  # noqa: BLE001 — server boundary: a
                # malformed request (wrong types included) must become a
                # per-request error, never kill the other in-flight work
                _emit({"id": rid_key, "error": str(exc)})
                continue
            if rid in srv.finished():
                # max_new=1 (or instant EOS): the prefill token already
                # completed the request inside submit()
                tokens = srv.result(rid)
                for t in tokens:
                    _emit({"id": rid_key, "token": int(t)})
                finish(req, tokens, is_text)
                continue
            # the prefill forward already produced the first token —
            # stream it now (step() only emits subsequent ones)
            _emit({"id": rid_key, "token": int(srv.peek(rid)[0])})
            live[rid] = req
            text_mode[rid] = is_text

    while True:
        # drain whatever arrived on stdin without blocking the decode loop
        try:
            while True:
                item = in_q.get_nowait()
                if item is None:
                    eof = True
                    break
                tag, payload = item
                if tag == "err":
                    _emit({"error": payload})
                else:
                    pending.append(payload)
        except queue.Empty:
            pass
        # between admissions is the swap point: no decode round is in
        # flight, so the next round reads the fresh weights whole
        maybe_swap()
        admit()
        if srv.idle:
            if eof and not pending:
                return finish_run()
            if not pending:
                # nothing in flight: block for the next request (or EOF).
                # A following server wakes periodically so weight
                # versions keep swapping in while the queue is empty —
                # the first request after a quiet stretch must not be
                # served stale weights.
                try:
                    item = in_q.get(
                        timeout=0.5 if follower is not None else None)
                except queue.Empty:
                    maybe_swap()
                    continue
                if item is None:
                    return finish_run()
                tag, payload = item
                if tag == "err":
                    _emit({"error": payload})
                else:
                    pending.append(payload)
                continue
        # fuse rounds only when nothing is waiting for a slot — a
        # pending request must get the next admission opportunity
        emitted = (srv.step_many(fused_rounds)
                   if fused_rounds > 1 and not pending else srv.step())
        done_now = set(srv.finished())
        # stream every token BEFORE retiring finished requests: a
        # speculative round can emit several tokens for one rid, and the
        # finishing token may not be its last emitted pair
        for rid, token in emitted:
            _emit({"id": live[rid].get("id"), "token": int(token)})
        for rid in done_now & set(live):
            finish(live[rid], srv.result(rid), text_mode[rid])
            del live[rid], text_mode[rid]


if __name__ == "__main__":
    sys.exit(main())
