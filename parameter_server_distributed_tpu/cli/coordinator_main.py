"""Coordinator process entry point.

Argv contract mirrors the reference (reference: src/coordinator_main.cpp:6-20):

    python -m parameter_server_distributed_tpu.cli.coordinator_main \
        [bind_addr] [ps_addr] [--ps-shards=host:port,host:port,...]

    bind_addr  default 0.0.0.0:50052
    ps_addr    default 127.0.0.1:50051 (host:port split like the reference)

Extensions: ``--ps-shards`` lists ADDITIONAL parameter-server shard
addresses beyond ps_addr — the store is then name-partitioned across all
of them and framework workers fan pushes/pulls out per tensor owner
(reference peers only see ps_addr).  ``--ps-backups`` lists backup
replica addresses aligned by shard index with [ps_addr, *ps-shards]
(replication/): a shard with a backup can be hot-failed-over — workers
report the dead primary and the coordinator promotes the backup in the
epoch-numbered shard map.
"""

from __future__ import annotations

import logging
import sys

from ..config import (DEFAULT_COORDINATOR_PORT, DEFAULT_PS_PORT,
                      CoordinatorConfig, parse_argv, parse_host_port)
from ..server.coordinator_service import Coordinator


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    positional, flags = parse_argv(argv)
    bind = positional[0] if len(positional) > 0 \
        else f"0.0.0.0:{DEFAULT_COORDINATOR_PORT}"
    ps = positional[1] if len(positional) > 1 \
        else f"127.0.0.1:{DEFAULT_PS_PORT}"
    bind_host, bind_port = parse_host_port(bind, DEFAULT_COORDINATOR_PORT)
    ps_host, ps_port = parse_host_port(ps, DEFAULT_PS_PORT)
    shards = tuple(s for s in flags.get("ps-shards", "").split(",") if s)
    backups = tuple(s for s in flags.get("ps-backups", "").split(",") if s)
    coordinator = Coordinator(CoordinatorConfig(
        bind_address=bind_host, port=bind_port,
        ps_address=ps_host, ps_port=ps_port, ps_shards=shards,
        ps_backups=backups))
    coordinator.start()
    print(f"Coordinator server listening on {bind}", flush=True)
    try:
        coordinator.wait()
    except KeyboardInterrupt:
        coordinator.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
