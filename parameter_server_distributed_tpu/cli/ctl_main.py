"""``pst-ctl``: cluster membership control (elastic/, ISSUE 13).

    pst-ctl drain <worker_id> [coordinator_addr]
    pst-ctl members [coordinator_addr]

``drain`` asks the coordinator to mark the worker DRAINING: the worker
sees its own state on its next heartbeat-cadence membership poll,
finishes the in-flight iteration, deregisters, and the elastic barrier
narrows at the next width refresh — graceful preemption with zero
failed steps, no SSH to the worker host needed.

``members`` prints the epoch-numbered membership table
(joining/active/draining/gone per worker).

Degrades gracefully against a reference coordinator, which does not
implement the ``UpdateMembership`` extension RPC.
"""

from __future__ import annotations

import sys

from ..config import parse_argv
from ..elastic import messages as emsg
from ..elastic.membership import MembershipClient

USAGE = ("usage: pst-ctl drain <worker_id> [coordinator_addr]\n"
         "       pst-ctl members [coordinator_addr]")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # a control tool run with PSDT_FLIGHT_DIR exported must not deposit
    # its own flight ring into the cluster's evidence directory
    from ..obs import flight
    flight.suppress_for_tool()
    positional, _flags = parse_argv(argv)
    if not positional:
        print(USAGE, file=sys.stderr)
        return 2
    command = positional[0]

    if command == "drain":
        if len(positional) < 2:
            print(USAGE, file=sys.stderr)
            return 2
        target = int(positional[1])
        coordinator = positional[2] if len(positional) > 2 \
            else "127.0.0.1:50052"
        client = MembershipClient(coordinator)
        try:
            resp = client.drain(target)
        finally:
            client.close()
        if resp is None:
            print("drain unavailable: coordinator does not implement "
                  "UpdateMembership (reference build?)", file=sys.stderr)
            return 1
        print(f"{resp.message} (membership epoch {resp.epoch})")
        return 0 if resp.success else 1

    if command == "members":
        coordinator = positional[1] if len(positional) > 1 \
            else "127.0.0.1:50052"
        client = MembershipClient(coordinator)
        try:
            resp = client.query()
        finally:
            client.close()
        if resp is None:
            print("membership unavailable: coordinator does not implement "
                  "UpdateMembership (reference build?)", file=sys.stderr)
            return 1
        print(f"membership epoch {resp.epoch} ({len(resp.entries)} entries)")
        for entry in resp.entries:
            state = emsg.STATE_NAMES.get(int(entry.state),
                                         f"state{entry.state}")
            print(f"  worker {entry.worker_id}: {state} "
                  f"(since epoch {entry.epoch})")
        return 0

    print(USAGE, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
