"""``pst-ctl``: cluster membership + decode fleet control (elastic/,
ISSUE 13; fleet/, ISSUE 14).

    pst-ctl drain <worker_id> [coordinator_addr]
    pst-ctl members [coordinator_addr]
    pst-ctl fleet [coordinator_addr]
    pst-ctl fleet-drain <server_id> [coordinator_addr]
    pst-ctl scale <n> [coordinator_addr]

``drain`` asks the coordinator to mark the worker DRAINING: the worker
sees its own state on its next heartbeat-cadence membership poll,
finishes the in-flight iteration, deregisters, and the elastic barrier
narrows at the next width refresh — graceful preemption with zero
failed steps, no SSH to the worker host needed.

``members`` prints the epoch-numbered membership table
(joining/active/draining/gone per worker).

``fleet`` prints the decode fleet table (state, slots free/total, queue
depth, serving weight version per server — the rows the router scores
on); ``fleet-drain`` is the serving twin of ``drain`` (the server stops
admitting, finishes its in-flight streams, and leaves — scale-in's
drain-before-stop step); ``scale <n>`` sets the manual fleet-size
target (0 hands control back to the autoscaler's watermarks).

Degrades gracefully against a reference coordinator, which implements
neither extension RPC.
"""

from __future__ import annotations

import sys

import grpc

from ..config import parse_argv
from ..elastic import messages as emsg
from ..elastic.membership import MembershipClient
from ..fleet import messages as fmsg
from ..rpc import messages as m
from ..rpc.service import RpcClient
from ..rpc.service import status_code as _status_code

USAGE = ("usage: pst-ctl drain <worker_id> [coordinator_addr]\n"
         "       pst-ctl members [coordinator_addr]\n"
         "       pst-ctl fleet [coordinator_addr]\n"
         "       pst-ctl fleet-drain <server_id> [coordinator_addr]\n"
         "       pst-ctl scale <n> [coordinator_addr]")


def _fleet_call(coordinator: str,
                request: fmsg.FleetRequest) -> fmsg.FleetResponse | None:
    """One UpdateFleet round trip; None (after printing the downgrade
    message every fleet subcommand shares) when the coordinator lacks
    the extension (reference build)."""
    client = RpcClient(coordinator, m.COORDINATOR_SERVICE,
                       fmsg.FLEET_COORD_METHODS)
    try:
        return client.call("UpdateFleet", request, timeout=5.0)
    except grpc.RpcError as exc:
        if _status_code(exc) == grpc.StatusCode.UNIMPLEMENTED:
            print("fleet unavailable: coordinator does not implement "
                  "UpdateFleet (reference build?)", file=sys.stderr)
            return None
        raise
    finally:
        client.close()


def _print_fleet(resp: fmsg.FleetResponse) -> None:
    target = (f", target {resp.scale_target}" if resp.scale_target
              else ", autoscale")
    print(f"fleet epoch {resp.epoch} ({len(resp.entries)} servers"
          f"{target})")
    for entry in resp.entries:
        state = fmsg.STATE_NAMES.get(int(entry.state),
                                     f"state{entry.state}")
        print(f"  server {entry.server_id} [{entry.address}]: {state}, "
              f"{entry.free_slots}/{entry.slots} slots free, "
              f"queue {entry.queue_depth}, "
              f"version {entry.weight_version}, "
              f"{entry.active_streams} streams")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # a control tool run with PSDT_FLIGHT_DIR exported must not deposit
    # its own flight ring into the cluster's evidence directory
    from ..obs import flight
    flight.suppress_for_tool()
    positional, _flags = parse_argv(argv)
    if not positional:
        print(USAGE, file=sys.stderr)
        return 2
    command = positional[0]

    if command == "drain":
        if len(positional) < 2:
            print(USAGE, file=sys.stderr)
            return 2
        target = int(positional[1])
        coordinator = positional[2] if len(positional) > 2 \
            else "127.0.0.1:50052"
        client = MembershipClient(coordinator)
        try:
            resp = client.drain(target)
        finally:
            client.close()
        if resp is None:
            print("drain unavailable: coordinator does not implement "
                  "UpdateMembership (reference build?)", file=sys.stderr)
            return 1
        print(f"{resp.message} (membership epoch {resp.epoch})")
        return 0 if resp.success else 1

    if command == "members":
        coordinator = positional[1] if len(positional) > 1 \
            else "127.0.0.1:50052"
        client = MembershipClient(coordinator)
        try:
            resp = client.query()
        finally:
            client.close()
        if resp is None:
            print("membership unavailable: coordinator does not implement "
                  "UpdateMembership (reference build?)", file=sys.stderr)
            return 1
        print(f"membership epoch {resp.epoch} ({len(resp.entries)} entries)")
        for entry in resp.entries:
            state = emsg.STATE_NAMES.get(int(entry.state),
                                         f"state{entry.state}")
            print(f"  worker {entry.worker_id}: {state} "
                  f"(since epoch {entry.epoch})")
        return 0

    if command == "fleet":
        coordinator = positional[1] if len(positional) > 1 \
            else "127.0.0.1:50052"
        resp = _fleet_call(coordinator, fmsg.FleetRequest(
            server_id=-1, action=fmsg.FLEET_QUERY))
        if resp is None:
            return 1
        _print_fleet(resp)
        return 0

    if command == "fleet-drain":
        if len(positional) < 2:
            print(USAGE, file=sys.stderr)
            return 2
        target = int(positional[1])
        coordinator = positional[2] if len(positional) > 2 \
            else "127.0.0.1:50052"
        resp = _fleet_call(coordinator, fmsg.FleetRequest(
            server_id=-1, action=fmsg.FLEET_DRAIN,
            target_server_id=target))
        if resp is None:
            return 1
        print(f"{resp.message} (fleet epoch {resp.epoch})")
        return 0 if resp.success else 1

    if command == "scale":
        if len(positional) < 2:
            print(USAGE, file=sys.stderr)
            return 2
        target = int(positional[1])
        coordinator = positional[2] if len(positional) > 2 \
            else "127.0.0.1:50052"
        resp = _fleet_call(coordinator, fmsg.FleetRequest(
            server_id=-1, action=fmsg.FLEET_SCALE, scale_target=target))
        if resp is None:
            return 1
        print(f"{resp.message} (fleet epoch {resp.epoch})")
        return 0 if resp.success else 1

    print(USAGE, file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
