"""Parameter-server process entry point.

Argv contract mirrors the reference (reference: src/parameter_main.cpp:6-18):

    python -m parameter_server_distributed_tpu.cli.ps_main \
        [bind_addr] [total_workers] [checkpoint_interval] [flags...]

    bind_addr            default 0.0.0.0:50051
    total_workers        default 2
    checkpoint_interval  default 10 (iterations per checkpoint epoch)

Extension flags beyond the reference:
    --lr=F          learning rate (default 1.0, the reference's implicit lr)
    --optimizer=S   sgd | momentum | adam (host numpy/native-C++), or
                    device_{sgd,momentum,adam} (optax under jit) /
                    pallas_{sgd,momentum,adam} (fused pallas kernels) for a
                    device-resident store
    --staleness=N   bounded-staleness async mode (0 = synchronous)
    --aggregation=S streaming (default: fold-on-arrival accumulator,
                    O(model) barrier close) | buffered (classic
                    buffer-all-then-mean; also PSDT_AGGREGATION env)
    --elastic       barrier width follows live registrations (needs
                    --coordinator=ADDR to poll the registry)
    --ckpt-dir=D    checkpoint directory (default .)
    --keep=N        checkpoint retention
    --backup=ADDR   backup replica PS (replication/): the post-apply
                    store streams there after every barrier close so the
                    coordinator can promote it on this shard's death
    --replication=M async (default) | sync (close blocks on the backup
                    ack) | off — also the PSDT_REPLICATION env
    --standby=ADDR  address this PS re-arms replication toward AFTER a
                    promotion from backup to primary (otherwise the
                    promoted primary runs un-backed-up — surfaced as the
                    ps.replica.unarmed gauge in pst-status --metrics)
    --quorum=F      K-of-N barrier close (elastic/, docs/training.md
                    "Elastic membership & quorum barriers"): seal once
                    ceil(F * live width) contributors committed and the
                    grace window elapsed; stragglers fold forward
                    lr-damped.  Also the PSDT_QUORUM env; default off
                    (all-of-N, byte-identical)
    --quorum-grace-ms=N
                    grace window past the K-th commit (default 250;
                    also PSDT_QUORUM_GRACE_MS)
    --freerun       free-running barrier-free training (freerun/,
                    docs/training.md "Free-running async training"):
                    every push applies on arrival damped by
                    PSDT_STALENESS_BETA^staleness; no barrier, no seal.
                    Also the PSDT_FREERUN env; default off

With --coordinator=ADDR and PSDT_TIERS=1 the PS also polls the
coordinator's reduction topology (tiers/), so a leaf aggregator's ONE
quantized upstream push counts as its whole same-host group on the
barrier (docs/training.md "Hierarchical aggregation").
"""

from __future__ import annotations

import logging
import sys

from ..config import (DEFAULT_PS_PORT, ParameterServerConfig, parse_argv,
                      parse_host_port)
from ..server.ps_service import ParameterServer


def build_config(argv: list[str]) -> tuple[ParameterServerConfig, str | None]:
    positional, flags = parse_argv(argv)
    bind = positional[0] if len(positional) > 0 else f"0.0.0.0:{DEFAULT_PS_PORT}"
    host, port = parse_host_port(bind, DEFAULT_PS_PORT)
    config = ParameterServerConfig(
        bind_address=host, port=port,
        total_workers=int(positional[1]) if len(positional) > 1 else 2,
        checkpoint_interval=int(positional[2]) if len(positional) > 2 else 10,
        learning_rate=float(flags.get("lr", 1.0)),
        optimizer=flags.get("optimizer", "sgd"),
        staleness_bound=int(flags.get("staleness", 0)),
        aggregation=flags.get("aggregation", ""),
        elastic="elastic" in flags,
        checkpoint_dir=flags.get("ckpt-dir", "."),
        checkpoint_keep=int(flags.get("keep", 0)),
        backup_address=flags.get("backup", ""),
        replication=flags.get("replication", ""),
        standby_address=flags.get("standby", ""),
        quorum=float(flags.get("quorum", 0.0)),
        quorum_grace_ms=float(flags.get("quorum-grace-ms", -1.0)),
        freerun="freerun" in flags,
    )
    return config, flags.get("coordinator")


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    config, coordinator_addr = build_config(argv)

    live_fn = None
    if config.elastic and coordinator_addr:
        # Membership-backed width provider (elastic/, ISSUE 13): counts
        # every non-GONE member and carries the membership epoch as its
        # generation, so a drain/leave/reap narrows the barrier at the
        # next width read.  Degrades internally to the classic
        # ListWorkers count against a reference coordinator.
        from ..elastic.membership import MembershipWidthProvider
        live_fn = MembershipWidthProvider(coordinator_addr)

    # Tier contribution weights ride the coordinator connection whenever
    # one is configured: the ENABLE decision lives at the coordinator
    # (the provider answers {} when tiers are off there, and latches
    # flat on UNIMPLEMENTED), so a PS host missing the PSDT_TIERS env
    # cannot silently mis-attribute group pushes under env skew.
    contributions_fn = None
    if coordinator_addr:
        from ..tiers.topology import TierContributionProvider
        contributions_fn = TierContributionProvider(coordinator_addr)

    ps = ParameterServer(config, live_workers_fn=live_fn,
                         contributions_fn=contributions_fn)
    ps.start()
    print(f"Parameter server listening on {config.bind_address}:{config.port}",
          flush=True)
    try:
        ps.wait()
    except KeyboardInterrupt:
        ps.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
