"""Cluster status CLI: the observability surface the reference only exposed
as raw RPCs (Coordinator.ListWorkers — proto/coordinator.proto:8; PS
CheckSyncStatus — proto/parameter_server.proto:7).

    python -m parameter_server_distributed_tpu.cli.status_main \
        [coordinator_addr] [--iteration=N] [--metrics] [--metrics-json] \
        [--watch[=SECONDS]] [--watch-count=N]

Prints the worker registry (id/address/hostname) and the PS sync state for
the given iteration (default: 0).  ``--metrics`` adds the cluster metric
rollup the coordinator aggregates from heartbeat-piggybacked worker
snapshots (obs/export.py): per-worker RPC p50/p95 latency, wire-byte
totals (with the f32-payload compression ratio), step-phase breakdown,
and the straggler spread.  ``--metrics-json`` emits the raw rollup JSON
instead (for dashboards/scripts).  Degrades gracefully against a
reference coordinator, which does not implement the extension RPC.

``--watch`` (ISSUE 8) keeps polling the rollup and prints RATES between
consecutive snapshots — steps/s and wire MB/s per worker — off a bounded
time-series ring (obs/stats.TimeSeriesRing): the live view of cluster
throughput the one-shot percentile rollup cannot give.  Interval defaults
to 1 s (``--watch=5`` overrides); ``--watch-count=N`` bounds the ticks
(scripts/tests), default unbounded (Ctrl-C exits).
"""

from __future__ import annotations

import sys
import time

import grpc

from ..config import parse_argv
from ..obs.export import render_fleet, render_membership, render_rollup
from ..obs.stats import TimeSeriesRing
from ..rpc import messages as m
from ..rpc.service import RpcClient


def rollup_to_snapshot(rollup: dict, t: float | None = None) -> dict:
    """Flatten a cluster rollup into the registry-snapshot shape
    ``obs.stats.snapshot_rates`` diffs: monotone per-worker totals become
    counters (step counts, wire bytes), so consecutive rollups yield
    steps/s and MB/s."""
    counters: dict[str, float] = {}
    for wid, w in rollup.get("per_worker", {}).items():
        step = w.get("step")
        if step:
            counters[f"worker.{wid}.steps"] = step["count"]
        counters[f"worker.{wid}.bytes_sent"] = w.get("bytes_sent", 0)
        counters[f"worker.{wid}.bytes_received"] = w.get(
            "bytes_received", 0)
    return {"t": t if t is not None else time.time(),
            "counters": counters, "gauges": {}, "histograms": {}}


def render_watch_line(rates: dict | None, workers: int,
                      rollup: dict | None = None) -> str:
    """One ``--watch`` tick: per-worker step rate + cluster wire MB/s,
    plus — when the coordinator serves the elastic membership rollup
    (ISSUE 13) — a live/draining/stale-folded membership line."""
    if rates is None:
        line = f"watch: {workers} workers reporting (collecting baseline)"
    else:
        counters = rates.get("counters", {})
        steps = {name.split(".")[1]: rate for name, rate in counters.items()
                 if name.startswith("worker.") and name.endswith(".steps")}
        sent = sum(rate for name, rate in counters.items()
                   if name.endswith(".bytes_sent"))
        received = sum(rate for name, rate in counters.items()
                       if name.endswith(".bytes_received"))
        step_part = (" ".join(f"w{wid}={rate:.2f}/s"
                              for wid, rate in sorted(steps.items()))
                     or "no steps")
        line = (f"watch dt={rates['dt_s']:.1f}s steps: {step_part} | wire: "
                f"{sent / 1e6:.2f} MB/s out, "
                f"{received / 1e6:.2f} MB/s in")
    membership = (rollup or {}).get("membership")
    if membership:
        stale_folds = sum(
            w.get("ps", {}).get("stale_folds", 0)
            for w in (rollup or {}).get("per_worker", {}).values())
        extra = f"; {stale_folds} stale folds" if stale_folds else ""
        line += f"\n  membership: {render_membership(membership)}{extra}"
    fleet = (rollup or {}).get("fleet")
    if fleet:
        line += f"\n  fleet: {render_fleet(fleet)}"
    # free-running mode (freerun/, ISSUE 16): the colocated-PS snapshot
    # carries the staleness distribution and the per-unit damp the
    # schedule currently applies — the live health view of a barrier-free
    # run (a growing p95 means the damp is about to bite harder)
    for w in (rollup or {}).get("per_worker", {}).values():
        fr = w.get("ps", {}).get("freerun")
        if not fr:
            continue
        part = f"\n  freerun: {fr.get('applies', 0)} applies"
        if fr.get("duplicates"):
            part += f", {fr['duplicates']} dups"
        if fr.get("floor_drops"):
            part += f", {fr['floor_drops']} floor drops"
        stl = fr.get("staleness")
        if stl:
            part += (f" | staleness p50={stl['p50']:.1f} "
                     f"p95={stl['p95']:.1f}")
        if fr.get("effective_beta") is not None:
            part += f" | eff beta {fr['effective_beta']:.4f}"
        line += part
        break  # one PS rollup is the whole free-run story
    return line


def _watch_loop(coordinator_addr: str, interval_s: float,
                max_ticks: int | None) -> int:
    ring = TimeSeriesRing(capacity=64)
    last_counters: dict | None = None
    ticks = 0
    with RpcClient(coordinator_addr, m.COORDINATOR_SERVICE,
                   {**m.COORDINATOR_METHODS,
                    **m.COORDINATOR_EXT_METHODS}) as coord:
        while max_ticks is None or ticks < max_ticks:
            if ticks:
                time.sleep(interval_s)
            ticks += 1
            try:
                rollup_json = coord.call(
                    "GetClusterMetrics", m.ClusterMetricsRequest(),
                    timeout=5.0).rollup_json
            except grpc.RpcError as exc:
                code = getattr(exc, "code", lambda: None)()
                if code == grpc.StatusCode.UNIMPLEMENTED:
                    print("watch unavailable: coordinator does not "
                          "implement GetClusterMetrics (reference build?)")
                    return 1
                print(f"watch: coordinator unreachable ({code})")
                continue
            import json

            rollup = json.loads(rollup_json) if rollup_json else {}
            snap = rollup_to_snapshot(rollup)
            # rates only across CHANGED snapshots: the rollup serves
            # CACHED heartbeat snapshots (5 s cadence by default), so a
            # faster poll would read byte-identical rollups as 0.00/s —
            # indistinguishable from a real stall — and then cram the
            # whole heartbeat interval's delta into one poll period.
            # Skipping unchanged snapshots keeps dt the true spacing of
            # fresh data; a genuinely stalled worker still shows 0.00/s
            # because OTHER counters (heartbeats ride wire-byte totals)
            # advance its snapshot.
            if snap["counters"] != last_counters:
                last_counters = snap["counters"]
                ring.push(snap)
            print(render_watch_line(ring.rates(),
                                    len(rollup.get("per_worker", {})),
                                    rollup=rollup),
                  flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # a status tool run with PSDT_FLIGHT_DIR exported must not deposit
    # its own flight ring into the cluster's evidence directory
    from ..obs import flight
    flight.suppress_for_tool()
    positional, flags = parse_argv(argv)
    coordinator_addr = positional[0] if positional else "127.0.0.1:50052"

    if "watch" in flags:
        # bare --watch parses as "1" (parse_argv): a 1 s default cadence
        interval = float(flags["watch"])
        max_ticks = (int(flags["watch-count"])
                     if "watch-count" in flags else None)
        try:
            return _watch_loop(coordinator_addr, interval, max_ticks)
        except KeyboardInterrupt:
            return 0

    want_metrics = "metrics" in flags or "metrics-json" in flags
    metrics_json = None
    with RpcClient(coordinator_addr, m.COORDINATOR_SERVICE,
                   {**m.COORDINATOR_METHODS,
                    **m.COORDINATOR_EXT_METHODS}) as coord:
        workers = coord.call("ListWorkers", m.ListWorkersRequest(), timeout=5.0)
        ps_addr = coord.call("GetParameterServerAddress",
                             m.GetPSAddressRequest(), timeout=5.0)
        if want_metrics:
            try:
                metrics_json = coord.call(
                    "GetClusterMetrics", m.ClusterMetricsRequest(),
                    timeout=5.0).rollup_json
            except grpc.RpcError as exc:
                code = getattr(exc, "code", lambda: None)()
                if code != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                # reference coordinator: the metrics extension RPC does
                # not exist there; report instead of erroring out
                metrics_json = ""

    print(f"coordinator: {coordinator_addr}")
    print(f"parameter server: {ps_addr.address}:{ps_addr.port}")
    shards = list(ps_addr.shards)
    if len(shards) > 1:
        print(f"ps shards: {len(shards)}")
        for i, shard in enumerate(shards):
            print(f"  shard {i}: {shard}")
    print(f"registered workers: {workers.total_workers}")
    for w in workers.workers:
        print(f"  worker {w.worker_id}: {w.address}:{w.port} ({w.hostname})")

    iteration = int(flags.get("iteration", 0))
    targets = shards if len(shards) > 1 \
        else [f"{ps_addr.address}:{ps_addr.port}"]
    for i, target in enumerate(targets):
        label = f"shard {i} " if len(targets) > 1 else ""
        try:
            with RpcClient(target, m.PARAMETER_SERVER_SERVICE,
                           m.PARAMETER_SERVER_METHODS) as ps:
                sync = ps.call("CheckSyncStatus",
                               m.SyncStatusRequest(iteration=iteration),
                               timeout=5.0)
            print(f"{label}sync status @ iteration {sync.iteration}: "
                  f"ready={sync.ready} received={sync.workers_received}/"
                  f"{sync.total_workers}")
        except Exception as exc:  # noqa: BLE001
            print(f"{label}parameter server unreachable: {exc}")

    if want_metrics:
        if not metrics_json:
            print("cluster metrics unavailable (coordinator does not "
                  "implement GetClusterMetrics — reference build?)")
        elif "metrics-json" in flags:
            print(metrics_json)
        else:
            import json

            print(render_rollup(json.loads(metrics_json)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
