"""Cluster status CLI: the observability surface the reference only exposed
as raw RPCs (Coordinator.ListWorkers — proto/coordinator.proto:8; PS
CheckSyncStatus — proto/parameter_server.proto:7).

    python -m parameter_server_distributed_tpu.cli.status_main \
        [coordinator_addr] [--iteration=N] [--metrics] [--metrics-json]

Prints the worker registry (id/address/hostname) and the PS sync state for
the given iteration (default: 0).  ``--metrics`` adds the cluster metric
rollup the coordinator aggregates from heartbeat-piggybacked worker
snapshots (obs/export.py): per-worker RPC p50/p95 latency, wire-byte
totals (with the f32-payload compression ratio), step-phase breakdown,
and the straggler spread.  ``--metrics-json`` emits the raw rollup JSON
instead (for dashboards/scripts).  Degrades gracefully against a
reference coordinator, which does not implement the extension RPC.
"""

from __future__ import annotations

import sys

import grpc

from ..config import parse_argv
from ..obs.export import render_rollup
from ..rpc import messages as m
from ..rpc.service import RpcClient


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    positional, flags = parse_argv(argv)
    coordinator_addr = positional[0] if positional else "127.0.0.1:50052"

    want_metrics = "metrics" in flags or "metrics-json" in flags
    metrics_json = None
    with RpcClient(coordinator_addr, m.COORDINATOR_SERVICE,
                   {**m.COORDINATOR_METHODS,
                    **m.COORDINATOR_EXT_METHODS}) as coord:
        workers = coord.call("ListWorkers", m.ListWorkersRequest(), timeout=5.0)
        ps_addr = coord.call("GetParameterServerAddress",
                             m.GetPSAddressRequest(), timeout=5.0)
        if want_metrics:
            try:
                metrics_json = coord.call(
                    "GetClusterMetrics", m.ClusterMetricsRequest(),
                    timeout=5.0).rollup_json
            except grpc.RpcError as exc:
                code = getattr(exc, "code", lambda: None)()
                if code != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                # reference coordinator: the metrics extension RPC does
                # not exist there; report instead of erroring out
                metrics_json = ""

    print(f"coordinator: {coordinator_addr}")
    print(f"parameter server: {ps_addr.address}:{ps_addr.port}")
    shards = list(ps_addr.shards)
    if len(shards) > 1:
        print(f"ps shards: {len(shards)}")
        for i, shard in enumerate(shards):
            print(f"  shard {i}: {shard}")
    print(f"registered workers: {workers.total_workers}")
    for w in workers.workers:
        print(f"  worker {w.worker_id}: {w.address}:{w.port} ({w.hostname})")

    iteration = int(flags.get("iteration", 0))
    targets = shards if len(shards) > 1 \
        else [f"{ps_addr.address}:{ps_addr.port}"]
    for i, target in enumerate(targets):
        label = f"shard {i} " if len(targets) > 1 else ""
        try:
            with RpcClient(target, m.PARAMETER_SERVER_SERVICE,
                           m.PARAMETER_SERVER_METHODS) as ps:
                sync = ps.call("CheckSyncStatus",
                               m.SyncStatusRequest(iteration=iteration),
                               timeout=5.0)
            print(f"{label}sync status @ iteration {sync.iteration}: "
                  f"ready={sync.ready} received={sync.workers_received}/"
                  f"{sync.total_workers}")
        except Exception as exc:  # noqa: BLE001
            print(f"{label}parameter server unreachable: {exc}")

    if want_metrics:
        if not metrics_json:
            print("cluster metrics unavailable (coordinator does not "
                  "implement GetClusterMetrics — reference build?)")
        elif "metrics-json" in flags:
            print(metrics_json)
        else:
            import json

            print(render_rollup(json.loads(metrics_json)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
