"""Standalone evaluation CLI (`pst-eval`): loss/perplexity (LMs) or
loss/accuracy (classifiers) of a checkpoint over a dataset — no training
step, no server.

    pst-eval --model=small_lm [--ckpt=... | --ckpt-dir=... [--avg-last=K]
             [--lora-alpha=A]] \\
             [--data=corpus.txt|shard.bin|data.npz] [--batch=32]
             [--steps=16] [--seq=N] [--seed=0] [--dtype=bf16]
             [--scan-layers | --no-scan-layers]
    pst-eval --hf-gpt2=<checkout> [--data=...]   # converted checkpoint

Output is ONE strict-JSON line: ``{"model": ..., "loss": mean,
"perplexity": exp(loss)}`` for token models (perplexity is per-token —
dense LM loss is the mean next-token NLL; for MoE models the loss
includes the load-balance aux term, so perplexity is OMITTED rather
than reported skewed), or ``{"model": ..., "loss": ...,
"accuracy": top1}`` for (x, y) models.  A non-finite loss (diverged
checkpoint) reports ``null``, never a bare NaN token.  ``--data`` takes the same
sources the trainer does (raw .txt byte-tokenized, .bin token shard,
npz x/y); without it the registry's synthetic stream evaluates —
useful only as a smoke check.

The reference has no evaluation path (no model at all — reference
src/worker.cpp:316-329); this completes the CLI suite: train,
generate, serve, status, eval.
"""

from __future__ import annotations

import json
import logging
import sys

from ..config import parse_argv, require_flag_value

KNOWN_FLAGS = frozenset({
    "model", "hf-gpt2", "dtype", "scan-layers", "no-scan-layers", "seed",
    "ckpt", "ckpt-dir", "avg-last", "lora-alpha", "data", "batch", "steps",
    "seq",
})


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s "
                               "%(message)s")
    _, flags = parse_argv(argv)
    if "help" in flags:
        print(__doc__)
        return 0
    require_flag_value(argv, "--lora-alpha",
                       hint="the ALPHA the run trained with")
    unknown = set(flags) - KNOWN_FLAGS
    if unknown:
        raise SystemExit(f"unknown flag(s): {', '.join(sorted(unknown))}; "
                         f"--help lists the accepted flags")

    import jax
    import numpy as np

    from ..models.registry import get_model_and_batches
    from ..models.transformer import Transformer
    from .generate_main import load_params, match_layout

    name = flags.get("model", "small_lm")
    batch = int(flags.get("batch", 32))
    steps = int(flags.get("steps", 16))
    seed = int(flags.get("seed", 0))
    if flags.get("hf-gpt2"):
        # evaluate a converted transformers checkpoint directly (same
        # loader pst-generate/pst-serve use; --seq fixed by n_positions)
        conflicts = {"model", "ckpt", "ckpt-dir", "avg-last",
                     "lora-alpha"} & set(flags)
        if conflicts:
            # avg-last/lora-alpha act during checkpoint LOADING, which
            # the hf branch never does — ignoring them would silently
            # score the raw converted weights
            raise SystemExit(
                "--hf-gpt2 defines model AND weights; drop "
                + "/".join(sorted("--" + c for c in conflicts)))
        if flags.get("seq"):
            raise SystemExit("--hf-gpt2 fixes seq (n_positions); "
                             "drop --seq")
        from ..models.registry import lm_batches
        from .generate_main import load_hf
        model, params, _ = load_hf(flags)
        name = f"hf-gpt2:{flags['hf-gpt2']}"
        source = name
        batches = lm_batches(model, batch, seed=seed + 100_003,
                             data_path=flags.get("data", ""))
    else:
        model, batches = get_model_and_batches(
            name, batch, seed=seed + 100_003,  # held-out stream shift
            data_path=flags.get("data", ""), dtype=flags.get("dtype", ""),
            scan=(False if "no-scan-layers" in flags
                  else True if "scan-layers" in flags else None),
            seq_len=int(flags.get("seq", 0)))
        params, source = load_params(flags, model, seed)
    is_lm = isinstance(model, Transformer)
    if is_lm:
        params = match_layout(model, params)
    print(f"evaluating: {source}", file=sys.stderr)

    if not is_lm and hasattr(model, "apply"):
        # ONE forward serves both metrics: the models' xy losses (MLP /
        # ResNet / ViT) are all plain softmax cross-entropy over apply()
        # logits, so deriving loss from the same logits is exact
        import jax.numpy as jnp

        @jax.jit
        def eval_batch(params, x, y):
            logits = model.apply(params, x)
            logp = jax.nn.log_softmax(logits, axis=-1)
            loss = -jnp.mean(jnp.take_along_axis(
                logp, y[:, None].astype(jnp.int32), axis=-1))
            return loss, jnp.argmax(logits, axis=-1)
    else:
        eval_batch = None
        loss_fn = jax.jit(model.loss)
    total_loss, correct, count = 0.0, 0, 0
    for _ in range(max(1, steps)):
        data = next(batches)
        if eval_batch is not None:
            x, y = data
            loss, pred = eval_batch(params, x, y)
            total_loss += float(loss)
            correct += int((np.asarray(pred) == np.asarray(y)).sum())
            count += len(np.asarray(y))
        else:
            total_loss += float(loss_fn(params, data))
    mean_loss = total_loss / max(1, steps)
    finite = bool(np.isfinite(mean_loss))
    out = {"model": name,
           "loss": round(mean_loss, 6) if finite else None,
           "batches": max(1, steps)}
    if is_lm and finite and model.config.moe_every == 0:
        # cap like train_loop's eval summary: strict-JSON safe
        out["perplexity"] = round(float(np.exp(min(mean_loss, 700.0))), 4)
    elif is_lm and finite:
        out["note"] = ("loss includes the MoE load-balance aux term; "
                       "perplexity omitted")
    if count:
        out["accuracy"] = round(correct / count, 4)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
