"""``pst-route``: the decode fleet's front-door stream router (fleet/,
ISSUE 14).

    pst-route --coordinator=HOST:PORT [--port=50060] [--poll-s=0.5]

Speaks the same ``psdt_fleet.Decode`` gRPC service the decode servers
speak, so clients cannot tell a router from a single server: each
incoming ``SubmitStream`` is admitted to the best ACTIVE backend by
free-slot/queue-depth score (fleet table polled from the coordinator's
``UpdateFleet`` extension) and PINNED there for its lifetime — a
mid-stream rolling weight update swaps versions under the stream
(PR 10 semantics) and never re-routes a live continuation.

Downgrade matrix: no router deployed => point clients at the single
``pst-serve --serve-port`` process directly, byte-identical service.
"""

from __future__ import annotations

import logging
import sys

from ..config import parse_argv, require_flag_value

KNOWN_FLAGS = frozenset({"coordinator", "port", "poll-s"})


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s "
                               "%(message)s")
    _, flags = parse_argv(argv)
    if "help" in flags:
        print(__doc__)
        return 0
    require_flag_value(argv, "--coordinator", "--port", "--poll-s",
                       hint="e.g. --coordinator=10.0.0.5:50052 "
                            "--port=50060")
    unknown = set(flags) - KNOWN_FLAGS
    if unknown:
        raise SystemExit(f"unknown flag(s): {', '.join(sorted(unknown))}; "
                         f"--help lists the accepted flags")
    if not flags.get("coordinator"):
        raise SystemExit("pst-route needs --coordinator=HOST:PORT "
                         "(the fleet table lives there)")

    from ..fleet.router import FleetRouter
    router = FleetRouter(flags["coordinator"],
                         port=int(flags.get("port", "0")),
                         bind_address="0.0.0.0",
                         poll_s=float(flags.get("poll-s", "0.5")))
    port = router.start()
    print(f"fleet router on port {port} "
          f"(coordinator {flags['coordinator']})", file=sys.stderr)
    try:
        router.wait()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
