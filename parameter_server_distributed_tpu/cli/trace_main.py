"""``pst-trace``: cross-process iteration postmortems from flight rings.

    pst-trace <flight_dir> [--iteration=N] [--json] [--chrome=out.json]
                           [--list] [--stalled=SECONDS]

Run every cluster process with ``PSDT_FLIGHT_DIR=<dir>`` (the flight
recorder, obs/flight.py — always on, crash-surviving), then point this
tool at the directory after the fact — the rings of processes that died
by ``kill -9``/SIGSEGV decode like any other:

- default: process listing (who shut down clean, who DIED), the failure
  narrative (promotions, same-iteration failover retries, permanent
  downgrades), and the last published iteration's end-to-end timeline
  with its critical path and per-worker straggler attribution.
- ``--iteration=N``: postmortem that iteration instead.
- ``--json``: the same report as machine-readable JSON.
- ``--chrome=out.json``: write a merged Chrome trace (flight events as
  slices/instants, plus any PSDT_TRACE_FILE span dumps in the directory)
  for Perfetto.
- ``--list``: just the process/iteration inventory.
- ``--stalled=SECONDS``: audit every iteration for a stalled barrier
  (never published, or the seal waited longer than SECONDS past the
  last commit) — the elastic-quorum acceptance check (exit 1 when any
  iteration stalled; see docs/training.md "Elastic membership & quorum
  barriers").

See docs/observability.md ("Flight recorder", "pst-trace postmortems").
"""

from __future__ import annotations

import json
import sys

from ..config import parse_argv, require_flag_value
from ..obs import flight, postmortem


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # PSDT_FLIGHT_DIR may still be exported from the shell that drove
    # the cluster: this tool's own auto-enabled ring must not pollute
    # the directory it is about to analyze
    flight.suppress_for_tool()
    require_flag_value(argv, "--chrome", "--iteration", "--stalled",
                       hint="e.g. --chrome=merged.json")
    positional, flags = parse_argv(argv)
    if not positional:
        print("usage: pst-trace <flight_dir> [--iteration=N] [--json] "
              "[--chrome=out.json] [--list]", file=sys.stderr)
        return 2
    directory = positional[0]
    iteration = int(flags["iteration"]) if "iteration" in flags else None

    chrome_out = flags.get("chrome")
    if chrome_out:
        path = postmortem.export_chrome_trace(directory, str(chrome_out))
        print(f"chrome trace written: {path}")
        if "json" not in flags and "list" not in flags and iteration is None:
            return 0

    if "stalled" in flags:
        stall_s = float(flags["stalled"])
        rings = postmortem.load_rings(directory)
        if not rings:
            print(f"no flight rings under {directory}", file=sys.stderr)
            return 1
        stalled = postmortem.stalled_iterations(
            postmortem.merge_events(rings), stall_s)
        if "json" in flags:
            print(json.dumps({"stall_s": stall_s, "stalled": stalled},
                             default=float))
        elif stalled:
            for s in stalled:
                print(f"STALLED iteration {s['iteration']}: {s['reason']}")
        else:
            print(f"zero stalled iterations (threshold {stall_s:g}s)")
        return 1 if stalled else 0

    rep = postmortem.report(directory, iteration=iteration)
    if not rep["processes"]:
        print(f"no flight rings under {directory} (run the cluster with "
              f"PSDT_FLIGHT_DIR={directory})", file=sys.stderr)
        return 1
    if "list" in flags:
        rep.pop("timeline", None)
        rep.pop("critical_path", None)
    if "json" in flags:
        print(json.dumps(rep, default=float))
    else:
        print(postmortem.render_report(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
