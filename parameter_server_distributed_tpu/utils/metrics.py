"""Metrics, step timing, and profiling.

The reference's observability is bare stdout prints (SURVEY.md §5: server
start lines, checkpoint saves, per-iteration worker status).  Here:

- `StepTimer`: wall-clock per-step timing with p50/p95 summaries;
- `MetricsLogger`: structured JSONL metrics (step, loss, samples/sec,
  collective/step time) — machine-readable where the reference had log
  greps;
- `profile_trace`: context manager around `jax.profiler.trace` for TPU
  timeline captures (set PSDT_TRACE_DIR to enable).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Iterator


class StepTimer:
    def __init__(self, capacity: int = 1024):
        self._durations: list[float] = []
        self._capacity = capacity
        self._t0: float | None = None

    def __enter__(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._t0 is not None
        self.record(time.perf_counter() - self._t0)

    def record(self, duration_s: float) -> None:
        self._durations.append(duration_s)
        if len(self._durations) > self._capacity:
            del self._durations[:-self._capacity]

    @property
    def count(self) -> int:
        return len(self._durations)

    def percentile(self, q: float) -> float:
        if not self._durations:
            return float("nan")
        ordered = sorted(self._durations)
        idx = min(len(ordered) - 1, int(q / 100.0 * len(ordered)))
        return ordered[idx]

    def summary(self) -> dict[str, float]:
        if not self._durations:
            return {"count": 0}
        return {
            "count": len(self._durations),
            "mean_s": sum(self._durations) / len(self._durations),
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "last_s": self._durations[-1],
        }


class MetricsLogger:
    """Append-only JSONL metrics stream (path=None: in-memory only)."""

    def __init__(self, path: str | None = None):
        self._path = path
        self._records: list[dict] = []
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def log(self, **fields: Any) -> dict:
        record = {"t": time.time(), **fields}
        self._records.append(record)
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps(record, default=float) + "\n")
        return record

    @property
    def records(self) -> list[dict]:
        return list(self._records)

    def latest(self, metric: str) -> Any:
        for record in reversed(self._records):
            if metric in record:
                return record[metric]
        return None


@contextlib.contextmanager
def profile_trace(name: str = "train",
                  trace_dir: str | None = None) -> Iterator[None]:
    """TPU timeline capture via jax.profiler; no-op unless a directory is
    given or PSDT_TRACE_DIR is set."""
    trace_dir = trace_dir or os.environ.get("PSDT_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield


def samples_per_sec(batch_size: int, step_time_s: float,
                    num_chips: int = 1) -> float:
    if step_time_s <= 0:
        return float("nan")
    return batch_size / step_time_s / max(1, num_chips)
