"""Backward-compat shim: the metrics/timing utilities moved into the
observability subsystem (obs/stats.py) when cluster-wide tracing and the
coordinator-aggregated rollup landed.  Import from
``parameter_server_distributed_tpu.obs`` in new code."""

from __future__ import annotations

from ..obs.stats import (MetricsLogger, StepTimer, profile_trace,  # noqa: F401
                         samples_per_sec)

__all__ = ["StepTimer", "MetricsLogger", "profile_trace", "samples_per_sec"]
