"""Userspace network-condition injection for loopback benchmarks.

The top-k/bf16 wire encodings exist to win on a REAL network boundary
(DCN between PS hosts and workers), where bytes cost wall-clock; on
localhost the kernel moves 10+ GB/s and the byte advantage vanishes
(BASELINE.md: top-k at 1B was a null result on loopback).  The honest
way to measure the wire win without two hosts is to inject latency and
a bandwidth cap into the path.  Kernel tools (tc netem / tbf) need
modules this environment's kernel doesn't ship, so this is a portable
userspace equivalent: a TCP relay that forwards byte-for-byte while

- delaying each chunk by ``delay_ms`` (one-way; applied in both
  directions, so round-trips see ~2x), WITHOUT serializing the stream —
  chunks are timestamped at read and released at read-time + delay,
  preserving pipelining exactly like a long link does, and
- pacing writes to ``mbps`` megabits/second per direction (token-bucket
  style: the writer owes ``bytes/rate`` seconds after each chunk).

gRPC/HTTP-2 traffic relays transparently (it is plain TCP).  One relay
fronts one backend port; `bench.py pushpull` starts one per PS shard
when PSDT_BENCH_NET="rtt_ms:mbps" is set and points the client at the
relay ports (reference wire comparison: the reference's repeated-float
proto has no compression at all — reference proto/parameter_server.proto:19-24).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from queue import Queue

_CHUNK = 65536


class ThrottledRelay:
    """TCP relay 127.0.0.1:<listen_port> -> 127.0.0.1:<target_port> with
    one-way delay and a per-direction bandwidth cap.

    >>> relay = ThrottledRelay(target_port, delay_ms=10, mbps=500)
    >>> port = relay.start()     # connect clients here
    >>> relay.stop()
    """

    def __init__(self, target_port: int, delay_ms: float = 0.0,
                 mbps: float = 0.0, host: str = "127.0.0.1"):
        self.target = (host, int(target_port))
        self.delay_s = float(delay_ms) / 1e3
        # bytes/second; 0 = uncapped
        self.rate = float(mbps) * 1e6 / 8.0
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # on-the-wire byte totals per direction, across all connections —
        # lets a test/bench assert what a transport change (e.g. packed
        # wire dtypes) actually put on the link, independent of what the
        # application THINKS it sent (counted at relay read, before
        # delay/pacing)
        self._byte_lock = threading.Lock()
        self.bytes_to_target = 0     # client -> backend (requests)
        self.bytes_from_target = 0   # backend -> client (responses)
        # chaos state (replication failover tests): live relayed sockets,
        # so drop_connections() can hard-close them all, and the refusal
        # latch that makes subsequent connects die too — a process
        # kill/partition without an OS-level kill in-tree
        self._conn_lock = threading.Lock()
        self._conns: list[socket.socket] = []
        self._refuse = False

    def byte_counts(self) -> tuple[int, int]:
        """(bytes_to_target, bytes_from_target) so far."""
        with self._byte_lock:
            return self.bytes_to_target, self.bytes_from_target

    def reset_byte_counts(self) -> None:
        with self._byte_lock:
            self.bytes_to_target = 0
            self.bytes_from_target = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.target[0], 0))
        listener.listen(64)
        self._listener = listener
        accept = threading.Thread(target=self._accept_loop, daemon=True,
                                  name="netsim-accept")
        accept.start()
        self._threads.append(accept)
        return listener.getsockname()[1]

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.drop_connections(refuse_new=True)

    # --------------------------------------------------------------- chaos
    def drop_connections(self, refuse_new: bool = True) -> int:
        """Process-kill/partition chaos: hard-close every relayed
        connection (both endpoints observe an abrupt stream death, like a
        ``kill -9`` of the backend) and, with ``refuse_new`` (default),
        make later connects die immediately too — the shard stays "dead"
        until :meth:`restore_connections`.  Returns how many sockets were
        severed.  The failover tests use this to sever one PS shard
        without an OS-level kill in-tree."""
        with self._conn_lock:
            self._refuse = refuse_new
            conns, self._conns = self._conns, []
        for sock in conns:
            try:
                # RST, not FIN: linger-0 abort so the peer's in-flight
                # RPC fails NOW instead of waiting out a half-open drain
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        return len(conns)

    def restore_connections(self) -> None:
        """Lift the refusal latch set by :meth:`drop_connections`: NEW
        connections relay normally again (severed ones stay dead)."""
        with self._conn_lock:
            self._refuse = False

    def _register_conn(self, *socks: socket.socket) -> bool:
        """Track sockets for the chaos teardown; False when the relay is
        currently refusing (the caller must close them)."""
        with self._conn_lock:
            if self._refuse:
                return False
            self._conns.extend(socks)
            return True

    # ------------------------------------------------------------- internals
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._conn_lock:
                refusing = self._refuse
            if refusing:
                # "dead host": accept then abort, so the client observes
                # an immediate connection failure, not a hang
                try:
                    conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                    struct.pack("ii", 1, 0))
                except OSError:
                    pass
                conn.close()
                continue
            try:
                upstream = socket.create_connection(self.target)
            except OSError:
                conn.close()
                continue
            if not self._register_conn(conn, upstream):
                # drop_connections raced the accept: sever both ends
                for sock in (conn, upstream):
                    try:
                        sock.close()
                    except OSError:
                        pass
                continue
            for src, dst, attr in ((conn, upstream, "bytes_to_target"),
                                   (upstream, conn, "bytes_from_target")):
                self._pump(src, dst, attr)

    def _pump(self, src: socket.socket, dst: socket.socket,
              count_attr: str) -> None:
        """One direction: a reader timestamps chunks into a queue, a
        writer releases each at read-time + delay and paces to the rate —
        the pipelined long-link model (latency does not serialize
        throughput, bandwidth is capped independently)."""
        q: Queue = Queue(maxsize=256)

        def reader():
            try:
                while not self._stop.is_set():
                    data = src.recv(_CHUNK)
                    if not data:
                        break
                    with self._byte_lock:
                        setattr(self, count_attr,
                                getattr(self, count_attr) + len(data))
                    q.put((time.monotonic(), data))
            except OSError:
                pass
            q.put((0.0, b""))          # EOF sentinel

        def writer():
            pace = time.monotonic()
            try:
                while True:
                    ts, data = q.get()
                    if not data:
                        break
                    release = ts + self.delay_s
                    if self.rate > 0:
                        pace = max(pace, time.monotonic())
                        release = max(release, pace)
                        pace = release + len(data) / self.rate
                    wait = release - time.monotonic()
                    if wait > 0:
                        time.sleep(wait)
                    dst.sendall(data)
            except OSError:
                pass
            # half-close so gRPC sees clean stream shutdown
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

        for fn in (reader, writer):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"netsim-{fn.__name__}")
            t.start()
            self._threads.append(t)
