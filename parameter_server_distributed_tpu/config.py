"""Typed configuration for the whole framework.

The reference spreads configuration across three untyped layers: positional
argv on each binary (reference: src/parameter_main.cpp:10-18,
src/coordinator_main.cpp:10-20, src/worker_main.cpp:13-18), env vars in the
start scripts (reference: scripts/README.md:13-36), and Terraform variables
(reference: terraform/variables.tf).  Here a single set of dataclasses covers
all of it plus the TPU-side knobs (mesh shape, staleness bound, dtype), with
defaults matching the reference's observable behavior.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

# Defaults mirroring the reference
DEFAULT_PS_PORT = 50051          # reference: src/parameter_main.cpp:7
DEFAULT_COORDINATOR_PORT = 50052  # reference: scripts/start_coordinator.sh
DEFAULT_TOTAL_WORKERS = 2        # reference: src/parameter_main.cpp:14
DEFAULT_CHECKPOINT_INTERVAL = 10  # iterations/epoch — src/parameter_main.cpp:8
HEARTBEAT_PERIOD_S = 5.0         # reference: src/worker.cpp:233
STALE_TIMEOUT_S = 30.0           # reference: src/coordinator.cpp:52
REAP_PERIOD_S = 10.0             # reference: src/coordinator_service.cpp:104-105
AUTOSAVE_CHECK_PERIOD_S = 5.0    # reference: src/parameter_server_service.cpp:152
SYNC_POLL_PERIOD_S = 0.05        # reference: src/worker.cpp:372
SYNC_POLL_MAX = 200              # reference: src/worker.cpp:373
SYNC_OUTER_RETRIES = 3           # reference: src/worker.cpp:334
RETRY_MAX_ATTEMPTS = 5           # reference: src/worker.cpp:130
RETRY_BASE_DELAY_S = 0.1         # reference: src/worker.cpp:135 (100ms * 2^n)


@dataclasses.dataclass(frozen=True)
class CoordinatorConfig:
    bind_address: str = "0.0.0.0"
    port: int = DEFAULT_COORDINATOR_PORT
    ps_address: str = "127.0.0.1"
    ps_port: int = DEFAULT_PS_PORT
    stale_timeout_s: float = STALE_TIMEOUT_S
    reap_period_s: float = REAP_PERIOD_S
    # Extension: additional PS shard addresses ("host:port") beyond the
    # primary above — the store is then name-partitioned across all of
    # them (classic sharded parameter server; workers fan pushes/pulls
    # out per tensor owner).  Reference topology is the empty default.
    ps_shards: tuple[str, ...] = ()
    # Replication (replication/): backup replica addresses aligned by
    # shard index with [ps_address:ps_port, *ps_shards].  A shard with a
    # backup listed here can be hot-failed-over: workers report the dead
    # primary, the coordinator promotes the backup (epoch-numbered shard
    # map), and the same iteration retries against the replica.
    ps_backups: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ParameterServerConfig:
    bind_address: str = "0.0.0.0"
    port: int = DEFAULT_PS_PORT
    total_workers: int = DEFAULT_TOTAL_WORKERS
    checkpoint_interval: int = DEFAULT_CHECKPOINT_INTERVAL
    checkpoint_dir: str = "."
    autosave_period_s: float = AUTOSAVE_CHECK_PERIOD_S
    learning_rate: float = 1.0   # reference applies param -= mean_grad (lr=1.0)
    # extensions beyond the reference:
    optimizer: str = "sgd"       # sgd | momentum | adam | adamw (host,
                                 # native C++ fused kernels) | device_sgd |
                                 # device_momentum | device_adam |
                                 # device_adamw | device_adamw_bf16 (bf16
                                 # moment slots: half the state HBM) |
                                 # pallas_sgd | pallas_momentum | pallas_adam
    momentum: float = 0.9
    weight_decay: float = 1e-4   # adamw variants only (matrices-only decay)
    staleness_bound: int = 0     # 0 = strictly synchronous (reference behavior)
    # Sync-barrier aggregation data path (core/ps_core.py): "streaming"
    # folds every push into a running accumulator on arrival (O(model)
    # barrier close, ~1x model peak gradient memory, duplicate pushes
    # first-push-wins); "buffered" is the classic buffer-all-then-mean
    # escape hatch (last-push-wins).  Empty = PSDT_AGGREGATION env or the
    # streaming default.
    aggregation: str = ""
    elastic: bool = False        # True: barrier width tracks live registrations
    live_workers_ttl_s: float = 1.0  # cache TTL for the live-worker lookup
    gc_iterations: int = 64      # retain at most this many iteration states
    checkpoint_keep: int = 0     # retention: keep newest N checkpoint files (0 = keep all)
    # Replication (replication/replicator.py): address of this shard's
    # backup replica PS.  When set, the post-apply store streams there
    # after every barrier close so the backup can be promoted on a
    # primary death.  Mode via `replication` / PSDT_REPLICATION:
    # "async" (default — close pays a CV notify, a slow backup lags) |
    # "sync" (close blocks until the backup acks — an applied iteration
    # can never be lost) | "off".
    backup_address: str = ""
    replication: str = ""
    # Cross-replica sharded update (replication/sharded_update.py,
    # ISSUE 18): partition each arena close across the replica set and
    # all-gather the fresh slabs instead of shipping full post-apply
    # state.  Requires sync replication + PSDT_ARENA.  Tri-state: ""
    # defers to the PSDT_SHARDED_UPDATE env (default off), "1"/"0"
    # force.  Exchange dtype for the sums/param legs via
    # `sharded_update_dtype` / PSDT_SHARDED_UPDATE_DTYPE: "raw"
    # (default — bit-exact f32) | "bf16" | "int8" (EQuARX-style
    # quantized exchange with sums-leg error feedback).
    sharded_update: str = ""
    sharded_update_dtype: str = ""
    # K-of-N quorum barriers (elastic/quorum.py, ISSUE 13): close the
    # synchronous barrier once ceil(quorum * live width) contributors
    # committed AND quorum_grace_ms past the K-th commit elapsed;
    # stragglers sealed out fold forward into the next iteration damped
    # by PSDT_STALENESS_BETA^staleness.  0.0 = PSDT_QUORUM env, which
    # defaults off (today's all-of-N, byte-identical); 1.0 == off too.
    quorum: float = 0.0
    # Grace window in ms past the K-th commit before a quorum close
    # fires (-1 = PSDT_QUORUM_GRACE_MS env, default 250).
    quorum_grace_ms: float = -1.0
    # Replication headroom (ISSUE 9 satellite): the address this PS
    # re-arms its Replicator toward AFTER it is promoted from backup to
    # primary — without it the promoted primary silently runs with no
    # backup (surfaced as the ps.replica.unarmed gauge).  Dormant until
    # the first barrier close proves this process is serving as a
    # primary; ignored when backup_address is set (already armed).
    standby_address: str = ""
    # Free-running barrier-free training (freerun/, ISSUE 16): every
    # push applies on arrival under beta^staleness damping; no barrier,
    # no seal, no grace window.  False = PSDT_FREERUN env (default off,
    # byte-identical paths).  Mutually exclusive with buffered
    # aggregation, bounded-staleness async, and K-of-N quorum — see the
    # downgrade matrix in docs/training.md.
    freerun: bool = False

    @property
    def synchronous(self) -> bool:
        return self.staleness_bound == 0


@dataclasses.dataclass(frozen=True)
class WorkerConfig:
    coordinator_address: str = "127.0.0.1:50052"
    worker_id: int = 0
    iterations: int = 10
    address: str = "127.0.0.1"
    port: int = 50060
    checkpoint_path: str = ""
    heartbeat_period_s: float = HEARTBEAT_PERIOD_S
    retry_max_attempts: int = RETRY_MAX_ATTEMPTS
    retry_base_delay_s: float = RETRY_BASE_DELAY_S
    sync_poll_period_s: float = SYNC_POLL_PERIOD_S
    sync_poll_max: int = SYNC_POLL_MAX
    sync_outer_retries: int = SYNC_OUTER_RETRIES
    batch_size: int = 32
    model: str = "mnist_mlp"
    # Model-construction knobs forwarded to the registry (same tri-state
    # semantics as TrainLoopConfig: None/"" = model default)
    model_dtype: str = ""
    remat: bool | None = None
    scan_layers: bool | None = None
    # File-backed dataset (data/files.py): token shard for LMs, npz
    # elsewhere.  Empty = synthetic loaders.
    data_path: str = ""
    # Tensor payload encoding on push/pull: "f32" (reference-compatible
    # repeated float), "raw" (f32 bytes blob), "bf16" (half the bytes;
    # TPU-native number format), "int8" (quarter-size gradient pushes
    # with error feedback; pulls stay bf16), or "topk" (top-k sparsified
    # pushes — ~topk_density*3/4 of the bf16 payload, unsent mass carried
    # by error feedback; pulls stay bf16).  Packed encodings require a
    # framework PS (negotiated; falls back to f32 against the reference).
    wire_dtype: str = "f32"
    # Fraction of gradient entries a "topk" push keeps (by |value|).
    # Default lives in rpc/messages.py (TOPK_DEFAULT_DENSITY) — one owner
    # for the wire layer, this config, and the CLI.
    topk_density: float = 0.01  # == messages.TOPK_DEFAULT_DENSITY
    # Intra-worker model parallelism: a mesh spec over the worker's local
    # chips (e.g. "fsdp:2,data:2", "tensor:4").  Empty = pure local data
    # parallelism.  Params are sharding-constrained inside the jitted
    # step; the PS protocol still sees one packed host store.
    mesh: str = ""
    # Pipelined data plane (rpc/data_plane.py PushPullStream): collapse
    # each synchronous step's push + barrier polls + pull into one fused
    # RPC round with bucketed D2H/encode/transport overlap.  Degrades
    # automatically (per connection) to the serial reference protocol
    # against a reference PS; False forces the serial path everywhere.
    fused_step: bool = True
    # Client timeout of the fused call.  It spans push + barrier wait +
    # pull, so it must exceed the SERVER-side barrier cap
    # (PSDT_FUSED_BARRIER_TIMEOUT_S, default 60 s) — the server answers a
    # clean not-ready inside this window and the worker falls back to its
    # poll loop rather than aborting the stream.
    fused_timeout_s: float = 120.0
    # Hierarchical aggregation (tiers/, ISSUE 9): join the coordinator's
    # two-tier reduction topology — same-host workers fold locally at an
    # elected leaf aggregator and ONE quantized contribution goes
    # upstream per group.  Tri-state: None = PSDT_TIERS env (default
    # off).  Requires the fused data plane and a single-PS topology;
    # degrades permanently to flat on any refusal (docs/training.md
    # "Hierarchical aggregation").
    tiers: bool | None = None
    # Same-host identity override for the tier grouping (tests/bench
    # simulate multi-host groups in one process; empty = the real
    # hostname+boot-id of rpc/shm_transport.py host_id()).
    tier_host_id: str = ""
    # Free-running loop (freerun/, ISSUE 16): skip the barrier entirely
    # — push, pull whatever version is published, step again.  Pair
    # with a PS running --freerun (a barriered PS would still answer
    # every push complete=False and the loop would spin on stale
    # params).  False = PSDT_FREERUN env.
    freerun: bool = False


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh for the SPMD data plane.

    Axes follow the scaling-book convention: data / fsdp (ZeRO param-shard,
    the 'ps_shard' analogue) / tensor / sequence / pipeline / expert.  Any
    axis of size 1 is collapsed when the mesh is built.
    """
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    pipeline: int = 1
    expert: int = 1

    @property
    def axis_sizes(self) -> dict[str, int]:
        return {"data": self.data, "fsdp": self.fsdp, "tensor": self.tensor,
                "sequence": self.sequence, "pipeline": self.pipeline,
                "expert": self.expert}

    @property
    def num_devices(self) -> int:
        n = 1
        for v in self.axis_sizes.values():
            n *= v
        return n


def env_or(name: str, default: str) -> str:
    return os.environ.get(name, default)


def parse_argv(argv: Sequence[str]) -> tuple[list[str], dict[str, str]]:
    """Split argv into (positional, flags): ``--k=v`` -> flags[k]=v,
    bare ``--k`` -> flags[k]="1".  Shared by all CLI mains."""
    positional = [a for a in argv if not a.startswith("--")]
    flags = dict(f.lstrip("-").split("=", 1) if "=" in f else (f.lstrip("-"), "1")
                 for f in argv if f.startswith("--"))
    return positional, flags


def require_flag_value(argv: Sequence[str], *names: str,
                       hint: str = "") -> None:
    """Reject bare value-flags: :func:`parse_argv` maps ``--k`` (no "=")
    to the string "1", which for flags like ``--lora-alpha`` would
    silently substitute a wrong value instead of failing loudly.  Call
    with the raw argv and the ``--name`` spellings to demand; ``hint``
    tells the user WHAT value belongs there."""
    for name in names:
        if name in argv:
            raise SystemExit(f"{name} requires an explicit value "
                             f"({name}=...{f' — {hint}' if hint else ''})")


def parse_host_port(addr: str, default_port: int) -> tuple[str, int]:
    """Split 'host:port' like the reference coordinator main
    (reference: src/coordinator_main.cpp:12-18)."""
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        return host, int(port)
    return addr, default_port
